"""Wire-compatibility registry (docs/analysis.md).

Single source of truth for the repo's wire-evolution discipline: every
RPC tag ``ControllerService`` handles and every field of the
rank→coordinator negotiation messages (``RequestList``/``CacheRequest``)
must have an entry here **naming its degrade** — what happens when the
peer is the native C++ controller whose binary wire predates the
feature, or an old-format client. The ``analysis/wire.py`` checker
cross-references this dict against the AST of ``ops/controller.py`` and
``ops/messages.py``: a new tag or field without an entry fails lint
(HVL401/HVL402), and an entry whose tag/field no longer exists is stale
(HVL403). The pattern being enforced is the one PRs 3/5/6/8/9 each
re-derived by hand: "the native wire predates the field → deterministic
degrade, warned once".

Since the checkpoint plane (docs/checkpoint.md) the same discipline
covers the two OTHER driver-side services that grew real wire
vocabularies: ``ELASTIC_RPC_TAGS`` (``elastic/health.py``'s
``ElasticService`` — heartbeats, the commit store, the chunked ckpt
streams and the ticket journal) and ``SERVING_RPC_TAGS``
(``serving/plane.py``'s ``ServingPlane`` — dispatch, the result
rendezvous, weight-swap acks), checked by the same scanner under the
same HVL401/HVL403 codes with the service class named in the finding.

``ERROR_CLASSES`` plays the same role for the error taxonomy
(HVL603): a ``HorovodInternalError`` subclass defined outside
``core/status.py`` must be registered with the story of how its
attribution survives the wire.
"""

from __future__ import annotations

from typing import Dict

# RPC tags dispatched by ControllerService._handle (ops/controller.py).
# Value = native-controller / old-peer degrade, in one sentence.
RPC_TAGS: Dict[str, str] = {
    "hello": "baseline wire (both controllers): rank identification at "
             "connect; native C++ service speaks the same tag",
    "cycle": "baseline wire (both controllers): the negotiation "
             "rendezvous itself",
    "payload": "baseline wire (both controllers): host data-plane fused "
               "payload exchange; rides the PR-9 second connection "
               "where armed",
    "bye": "baseline wire: clean tooling detach; native service treats "
           "an unknown tag as a no-op close",
    "watch": "Python controller only: abort push channel; native "
             "controller clients poll wait_world_shutdown instead",
    "metrics": "Python controller only (PR 5): native wire predates the "
               "RPC — publisher never dials it, world snapshots "
               "degrade to local-only, warned once",
    "metrics_pull": "Python controller only (PR 5): native wire "
                    "predates the RPC — metrics_snapshot(world=True) "
                    "degrades to the local registry, warned once",
    "clock_probe": "Python controller only (PR 6): native wire predates "
                   "the RPC — clock_sync_supported=False, traces merge "
                   "uncorrected and say so",
    "sentry": "Python controller only (PR 8): native wire predates the "
              "verdict rendezvous — the gradient sentry degrades to a "
              "local verdict, warned once",
    "flightrec": "Python controller only (PR 14): native wire predates "
                 "the incident-push RPC — the flight recorder degrades "
                 "to a rank-local blackbox dump, warned once",
    "hello_island": "Python controller only (PR 18, docs/hierarchy.md): "
                    "a sub-coordinator identifying itself and its "
                    "member set to the root at connect; the native "
                    "wire predates every island RPC, so HOROVOD_"
                    "HIERARCHY degrades the whole world to flat, "
                    "warned once on rank 0. Since the recovery plane "
                    "(docs/recovery.md) it doubles as the SUCCESSION "
                    "announcement: a hello from a NEW head rank "
                    "supersedes the old head's reconnect window and "
                    "rewrites the root's island-head map — the native "
                    "degrade is the same flat world, where succession "
                    "cannot arise",
    "island_cycle": "Python controller only (PR 18): one island's "
                    "merged negotiation cycle (IslandSubmission) "
                    "forwarded head→root; same flat degrade as "
                    "hello_island",
    "payload_island": "Python controller only (PR 18): the island's "
                      "UNSUMMED per-member payload map forwarded on "
                      "the head's second data connection — float "
                      "addition is non-associative, so only the root "
                      "combines; same flat degrade",
    "sentry_island": "Python controller only (PR 18): the island's "
                     "OR-folded gradient-sentry verdict bits forwarded "
                     "on the head's dedicated sentry channel; same "
                     "flat degrade",
    "abort_island": "Python controller only (PR 18): a head's "
                    "best-effort escalation naming a member rank that "
                    "died mid-job, so the root can abort the world "
                    "with the island named; same flat degrade",
}

# RPC tags dispatched by ElasticService._handle (elastic/health.py) —
# scanned since the checkpoint plane (docs/checkpoint.md) grew this wire
# past the original beat/commit vocabulary. The native C++ controller
# never speaks this service at all (the elastic driver is pure Python),
# so the degrade story is about OLD-DRIVER peers: a worker whose driver
# predates a tag gets ValueError'd at dispatch, which the sender treats
# as the documented fallback.
ELASTIC_RPC_TAGS: Dict[str, str] = {
    "beat": "baseline elastic wire: liveness heartbeat since PR 2",
    "goodbye": "baseline elastic wire: clean-exit deregistration",
    "commit": "baseline elastic wire: the legacy synchronous whole-tree "
              "state push (rank 0)",
    "fetch": "baseline elastic wire: restore fetch of the legacy store",
    "advise_evict": "PR 12: a driver that predates the tag errors the "
                    "advisory request; the coordinator's detector warns "
                    "once and keeps training (advisory-only degrade)",
    "ckpt_begin": "checkpoint plane: a driver that predates the plane "
                  "errors the stream open; the AsyncCommitter drops the "
                  "stream with a warning and the rank's commits degrade "
                  "to the legacy synchronous push (HOROVOD_CKPT_ASYNC "
                  "should be unset against old drivers)",
    "ckpt_chunk": "checkpoint plane: same stream as ckpt_begin — an "
                  "old driver never sees chunks because the begin "
                  "already failed; a lost chunk leaves the commit "
                  "unsealed, which restore treats as 'never happened'",
    "ckpt_end": "checkpoint plane: the digest vote + seal ack; without "
                "it a commit can never seal, so restore falls back to "
                "the last sealed (or legacy) commit — the safe default",
    "ckpt_fetch": "checkpoint plane: sealed-epoch restore; on any error "
                  "State._fetch_sealed falls back to the legacy "
                  "('fetch',) store, warned once",
    "ckpt_journal_put": "checkpoint plane: gateway ticket journal "
                        "persistence; an old driver errors the put and "
                        "the journal degrades to gateway-process memory "
                        "(requests survive relaunches but not driver "
                        "restarts)",
    "ckpt_journal_get": "checkpoint plane: journal lookup twin of "
                        "ckpt_journal_put, same in-memory degrade",
    "ckpt_journal_del": "checkpoint plane: journal cleanup twin of "
                        "ckpt_journal_put, same in-memory degrade",
    "shard_manifest": "sharding plane (docs/sharding.md): per-rank "
                      "ZeRO-1 shard-digest vote folded into the seal "
                      "meta as partition provenance; a driver that "
                      "predates the tag errors the put, State warns "
                      "once and commits proceed with the whole-tree "
                      "digest only (the manifest never gates a seal, "
                      "so restore semantics are unchanged). Replicated "
                      "worlds never send it — the tag rides only "
                      "commits of sharded state",
    "recover": "recovery plane (docs/recovery.md): a warm survivor "
               "parking in the driver's epoch-fenced recovery barrier "
               "after a world fault; a driver that predates the tag "
               "errors the park, elastic/recovery.maybe_recover returns "
               "None and the survivor exits for the classic cold "
               "relaunch — warm relaunch is additive, never required. "
               "Native-controller worlds never send it: warm_enabled_env "
               "forces the plane off there (the C++ service cannot be "
               "rebuilt in-process), warned once by the driver",
    "recover_poll": "recovery plane: the parked survivor's assignment "
                    "poll — ('wait',), ('assign', env) or ('exit', "
                    "reason); same old-driver degrade as 'recover' (any "
                    "error while parked means cold exit, never a hang), "
                    "and the same native-controller force-off",
}

# RPC tags dispatched by ServingPlane._handle (serving/plane.py) — the
# serving coordinator wire (PR 11), scanned since the checkpoint plane
# added hot-swap frames to it. Same peer model as the elastic service:
# Python-only coordinator, so degrades are about version-skewed workers.
SERVING_RPC_TAGS: Dict[str, str] = {
    "shello": "baseline serving wire (PR 11): rank identification + "
              "epoch fence at connect",
    "infer": "baseline serving wire (PR 11): the batch dispatch "
             "broadcast; since the checkpoint plane its answer may also "
             "be a ('swap', ...) frame — a worker that predates swaps "
             "fails its `assert resp[0] == 'batch'`, raises "
             "ServingAbortedError, and the elastic driver relaunches it "
             "(loud, never torn weights)",
    "result": "baseline serving wire (PR 11): the digest rendezvous",
    "swap_ack": "checkpoint plane: weight-swap receipt; a plane that "
                "predates the tag ValueErrors the ack, the worker's "
                "ServingAbortedError tears the world down and the "
                "relaunch re-arms both sides at the same version — "
                "acks can be lost, weights can never tear",
}

# Fields of the negotiation messages (ops/messages.py): the rank ->
# coordinator envelopes (RequestList/CacheRequest) plus the per-tensor
# Request and per-batch Response records that ride inside them (scanned
# since PR 13 — per-tensor wire growth like the codec and the fused-
# apply fingerprint follows the same predates-the-field discipline).
# Value = what a wire that predates the field does.
MESSAGE_FIELDS: Dict[str, str] = {
    "Request.request_rank": "baseline wire: present since the reference "
                            "message.h layout",
    "Request.request_type": "baseline wire: reference message.h layout",
    "Request.tensor_name": "baseline wire: reference message.h layout",
    "Request.tensor_type": "baseline wire: reference message.h layout",
    "Request.tensor_shape": "baseline wire: reference message.h layout",
    "Request.root_rank": "baseline wire: reference message.h layout",
    "Request.device": "baseline wire: the reference's CUDA device id "
                      "slot; informational only, never negotiated",
    "Request.codec": "PR 1: the native C++ negotiator's schema predates "
                     "the field — NativeNegotiator keeps per-name codec "
                     "bookkeeping in Python and stamps/splits responses; "
                     "the native controller wire drops it (engine "
                     "enqueue falls back to the full-precision wire, "
                     "warned once). PR 16: the sparse \"topk\" tag rides "
                     "the same field and the same degrade — the native "
                     "data plane cannot carry indices+values payloads, "
                     "so sparse requests reduce dense at full precision "
                     "there, warned once",
    "Request.apply_fingerprint": "PR 13: negotiated like the codec; the "
                                 "native controller wire predates the "
                                 "field and drops it — the engine keeps "
                                 "its apply contexts rank-side and runs "
                                 "the split reduce-then-apply execution, "
                                 "warned once (applied parameters still "
                                 "land)",
    "Response.response_type": "baseline wire: reference message.h layout",
    "Response.tensor_names": "baseline wire: reference message.h layout",
    "Response.error_message": "baseline wire: reference message.h layout",
    "Response.tensor_sizes": "baseline wire: reference message.h layout",
    "Response.tensor_dtype": "baseline wire: reference message.h layout",
    "Response.payload_bytes": "baseline wire: fusion-planner metadata "
                              "since the seed; old peers re-derive from "
                              "shape/dtype",
    "Response.tensor_codec": "PR 1: absent on wires that predate it — "
                             "ranks read it via getattr default "
                             "\"none\" and execute the full-precision "
                             "program",
    "Response.fused_apply": "PR 13: the apply-capable response kind; "
                            "absent (empty) on wires that predate it — "
                            "the engine's rank-side apply contexts "
                            "degrade to the split reduce-then-apply "
                            "execution, warned once",
    "RequestList.rank": "baseline wire: present since the reference "
                        "message.h layout",
    "RequestList.requests": "baseline wire: present since the reference "
                            "message.h layout",
    "RequestList.shutdown": "baseline wire: negotiated-drain bit from "
                            "the reference layout",
    "RequestList.integrity_digest": "PR 8: native controller wire "
                                    "predates the field — consensus "
                                    "verification disabled, warned once",
    "RequestList.flush_ordinal": "PR 9: None on wires that predate the "
                                 "field — the coordinator skips the "
                                 "cycle-alignment cross-check for that "
                                 "rank",
    "CacheRequest.rank": "PR 3 steady-state wire: the native controller "
                         "never receives CacheRequest at all "
                         "(cache_generation=None full-path fallback)",
    "CacheRequest.bits": "PR 3: same full-path fallback — the native "
                         "wire predates the cache-bit fast path "
                         "entirely",
    "CacheRequest.generation": "PR 3: generation pins the cache state; "
                               "wires without it never send bits",
    "CacheRequest.integrity_digest": "PR 8: warm-cache digest piggyback; "
                                     "absent on wires that predate it — "
                                     "judge warns once about the "
                                     "never-digesting rank",
    "CacheRequest.flush_ordinal": "PR 9: warm-path twin of "
                                  "RequestList.flush_ordinal; None "
                                  "skips the cross-check",
    "Request.member_ranks": "PR 18 (docs/hierarchy.md): the global "
                            "ranks a merged island request speaks for; "
                            "None on every flat-wire request and on the "
                            "root's re-expanded per-rank requests, so "
                            "peers that predate the field never see it "
                            "non-None",
    "Request.gather_dim0s": "PR 18: per-member allgather first-dim "
                            "sizes aligned to member_ranks, so one "
                            "merged request preserves the ragged "
                            "geometry; None except on merged ALLGATHER "
                            "requests inside an IslandSubmission",
    "IslandSubmission.island": "PR 18: which island this submission "
                               "speaks for; head→root wire only — "
                               "never reaches a member or the native "
                               "wire",
    "IslandSubmission.members": "PR 18: the island's global ranks; the "
                                "root validates raw maps against it "
                                "and names these ranks in abort texts",
    "IslandSubmission.flush_ordinal": "PR 18: the HEAD's own upstream "
                                      "cycle count — the per-LEVEL "
                                      "PR 9 cross-check; a desynced "
                                      "island fails loudly by name",
    "IslandSubmission.cache": "PR 18: the AND-merged cache-bit form "
                              "(PR 3 steady state) — set only when "
                              "every member sent identical bits at one "
                              "generation",
    "IslandSubmission.requests": "PR 18: the congruence-merged cold "
                                 "form; codec and apply_fingerprint "
                                 "negotiated per level like dtypes",
    "IslandSubmission.raw": "PR 18: verbatim per-member fallback when "
                            "ANY member deviates — the root runs the "
                            "flat path and produces byte-identical "
                            "flat error texts",
    "IslandSubmission.member_ordinals": "PR 18: members' own PR 9 flush "
                                        "ordinals preserved through the "
                                        "merge so the root's per-rank "
                                        "cross-check still runs",
    "IslandSubmission.digests": "PR 18: members' consensus digest "
                                "windows (PR 8) preserved through the "
                                "merge for the root's judge",
    "IslandSubmission.fold": "PR 18: the head's digest-of-digests over "
                             "the shipped windows; the root recomputes "
                             "and a mismatch escalates as island-level "
                             "wire corruption",
    "IslandSubmission.shutdown_ranks": "PR 18: members draining toward "
                                       "negotiated shutdown, forwarded "
                                       "so the root's drain logic sees "
                                       "global ranks",
}

# HorovodInternalError subclasses defined OUTSIDE core/status.py, with
# how their attribution round-trips (or deliberately doesn't).
ERROR_CLASSES: Dict[str, str] = {
    "ServingAbortedError": "serving/worker.py (PR 11): crosses the wire "
                           "as message text; elastic classifies it as a "
                           "world fault via the HorovodInternalError "
                           "subclass check in failure_record — no tag "
                           "of its own by design (the relaunch path "
                           "needs no rank attribution)",
}
