"""HVL7xx — pytest-marker audit (docs/analysis.md).

Every ``pytest.mark.<name>`` used under ``tests/`` must be registered in
``pyproject.toml``'s ``[tool.pytest.ini_options] markers`` list:
an unregistered marker is a silent no-op under ``--strict-markers`` and
— worse — a typo'd ``slow``/``soak`` mark silently promotes an expensive
test into the tier-1 budget. The audit is itself an hvdlint checker so
it cannot regress into a one-off review note.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from .base import Finding, SourceModule

# pytest builtins (plus plugins baked into the image) that need no
# registration row
BUILTIN_MARKS: Set[str] = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "timeout", "anyio", "asyncio",
}

_MARKERS_BLOCK_RE = re.compile(
    r"markers\s*=\s*\[(.*?)\]", re.DOTALL)


def used_markers(test_modules: List[SourceModule]
                 ) -> Dict[str, Tuple[str, int]]:
    """marker -> (rel, line) of first use of pytest.mark.<marker>."""
    out: Dict[str, Tuple[str, int]] = {}
    for mod in test_modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "mark" and \
                    isinstance(node.value.value, ast.Name) and \
                    node.value.value.id == "pytest":
                out.setdefault(node.attr, (mod.rel, node.lineno))
    return out


def registered_markers(pyproject_text: str) -> Set[str]:
    """Marker names from [tool.pytest.ini_options] markers. Uses tomllib
    where available (3.11+); regex fallback keeps the checker working on
    the 3.10 floor."""
    try:
        import tomllib

        data = tomllib.loads(pyproject_text)
        rows = (data.get("tool", {}).get("pytest", {})
                .get("ini_options", {}).get("markers", []))
    except Exception:
        m = _MARKERS_BLOCK_RE.search(pyproject_text)
        rows = re.findall(r"\"((?:[^\"\\]|\\.)*)\"", m.group(1)) \
            if m else []
    out: Set[str] = set()
    for row in rows:
        name = str(row).split(":", 1)[0].strip()
        if name:
            out.add(name)
    return out


def check(test_modules: List[SourceModule],
          pyproject_text: str) -> List[Finding]:
    registered = registered_markers(pyproject_text)
    findings: List[Finding] = []
    for marker, (rel, line) in sorted(used_markers(test_modules).items()):
        if marker in BUILTIN_MARKS or marker in registered:
            continue
        findings.append(Finding(
            code="HVL701", path=rel, line=line,
            message=f"pytest marker {marker!r} is not registered in "
                    "pyproject.toml [tool.pytest.ini_options] markers",
            key=f"marker:{marker}"))
    return findings


def run(root: str, test_modules: List[SourceModule]) -> List[Finding]:
    try:
        with open(os.path.join(root, "pyproject.toml"), "r",
                  encoding="utf-8") as f:
            pyproject_text = f.read()
    except OSError:
        pyproject_text = ""
    return check(test_modules, pyproject_text)
