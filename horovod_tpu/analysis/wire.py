"""HVL4xx — wire-compatibility lint (docs/analysis.md).

Cross-references the wire-compat registry
(``analysis/wire_registry.py``) against the code:

* HVL401: ``ControllerService`` dispatches an RPC tag the registry does
  not know — a new RPC shipped without deciding (and writing down) its
  native-controller degrade.
* HVL402: a negotiation message class (``Request``/``RequestList``/
  ``Response``/``CacheRequest``) grew a field the registry does not
  know — the "predates the field → degrade warned once" pattern
  (PRs 3/5/6/8/9/13) must be stated before the wire grows. ``Request``
  and ``Response`` joined the scan when PR 13's fused-apply fields
  proved per-tensor/per-batch wire growth follows the same discipline.
* HVL403: registry entry names a tag/field the code no longer has, or
  carries no degrade text — the registry only stays authoritative if it
  cannot rot.

Since the checkpoint plane the same scan covers the elastic driver
service (``elastic/health.py:ElasticService`` vs ``ELASTIC_RPC_TAGS``)
and the serving coordinator (``serving/plane.py:ServingPlane`` vs
``SERVING_RPC_TAGS``): their wires grew real vocabularies (chunked
commit streams, journal persistence, weight-swap acks) and a tag
shipped without its degrade story is the same HVL401 no matter which
service dispatches it — findings carry the service class name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .base import Finding, SourceModule, const_str

CONTROLLER_REL = "horovod_tpu/ops/controller.py"
MESSAGES_REL = "horovod_tpu/ops/messages.py"
ELASTIC_REL = "horovod_tpu/elastic/health.py"
SERVING_REL = "horovod_tpu/serving/plane.py"
MESSAGE_CLASSES = ("Request", "RequestList", "Response", "CacheRequest",
                   "IslandSubmission")


def scan_rpc_tags(controller_mod: SourceModule,
                  service_class: str = "ControllerService"
                  ) -> Dict[str, int]:
    """tag -> line for every ``kind == "tag"`` comparison inside the
    service class (the _handle dispatch and its helpers)."""
    tags: Dict[str, int] = {}
    for node in ast.walk(controller_mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == service_class:
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Compare) and
                        isinstance(sub.left, ast.Name) and
                        sub.left.id == "kind" and len(sub.ops) == 1):
                    continue
                op, comp = sub.ops[0], sub.comparators[0]
                if isinstance(op, (ast.Eq, ast.NotEq)):
                    tag = const_str(comp)
                    if tag is not None:
                        tags.setdefault(tag, sub.lineno)
                elif isinstance(op, (ast.In, ast.NotIn)) and \
                        isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    # `kind in ("a", "b")` dispatch: every member is a
                    # handled tag — missing this shape would report the
                    # registry entries as stale, steering it WRONG
                    for elt in comp.elts:
                        tag = const_str(elt)
                        if tag is not None:
                            tags.setdefault(tag, sub.lineno)
    return tags


def scan_message_fields(messages_mod: SourceModule,
                        classes: Tuple[str, ...] = MESSAGE_CLASSES
                        ) -> Dict[str, int]:
    """'Class.field' -> line for every annotated dataclass field."""
    fields: Dict[str, int] = {}
    for node in messages_mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name in classes:
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    fields[f"{node.name}.{stmt.target.id}"] = stmt.lineno
    return fields


def check(controller_mod: SourceModule, messages_mod: SourceModule,
          rpc_registry: Dict[str, str],
          field_registry: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    tags = scan_rpc_tags(controller_mod)
    fields = scan_message_fields(messages_mod)
    for tag, line in sorted(tags.items()):
        if tag not in rpc_registry:
            findings.append(Finding(
                code="HVL401", path=controller_mod.rel, line=line,
                message=f"RPC tag {tag!r} handled by ControllerService "
                        "has no wire-compat registry entry naming its "
                        "native-controller degrade",
                key=f"rpc:{tag}"))
    for name, line in sorted(fields.items()):
        if name not in field_registry:
            findings.append(Finding(
                code="HVL402", path=messages_mod.rel, line=line,
                message=f"negotiation message field {name} has no "
                        "wire-compat registry entry naming its "
                        "predates-the-field degrade",
                key=f"field:{name}"))
    registry_rel = "horovod_tpu/analysis/wire_registry.py"
    for tag, note in sorted(rpc_registry.items()):
        if tag not in tags:
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry RPC tag {tag!r} is not dispatched by "
                        "ControllerService any more — delete the entry",
                key=f"stale-rpc:{tag}"))
        elif not str(note).strip():
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry RPC tag {tag!r} has an empty degrade "
                        "note",
                key=f"empty-rpc:{tag}"))
    for name, note in sorted(field_registry.items()):
        if name not in fields:
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry message field {name} no longer "
                        "exists — delete the entry",
                key=f"stale-field:{name}"))
        elif not str(note).strip():
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry message field {name} has an empty "
                        "degrade note",
                key=f"empty-field:{name}"))
    return findings


def check_service(mod: SourceModule, service_class: str,
                  registry: Dict[str, str]) -> List[Finding]:
    """HVL401/HVL403 for one driver-side service wire (ElasticService,
    ServingPlane): same scan and codes as the controller, finding keys
    namespaced by the service class so the baselines cannot collide."""
    findings: List[Finding] = []
    tags = scan_rpc_tags(mod, service_class=service_class)
    registry_rel = "horovod_tpu/analysis/wire_registry.py"
    for tag, line in sorted(tags.items()):
        if tag not in registry:
            findings.append(Finding(
                code="HVL401", path=mod.rel, line=line,
                message=f"RPC tag {tag!r} handled by {service_class} has "
                        "no wire-compat registry entry naming its "
                        "old-peer degrade",
                key=f"rpc:{service_class}:{tag}"))
    for tag, note in sorted(registry.items()):
        if tag not in tags:
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry RPC tag {tag!r} is not dispatched by "
                        f"{service_class} any more — delete the entry",
                key=f"stale-rpc:{service_class}:{tag}"))
        elif not str(note).strip():
            findings.append(Finding(
                code="HVL403", path=registry_rel, line=0,
                message=f"registry RPC tag {tag!r} ({service_class}) has "
                        "an empty degrade note",
                key=f"empty-rpc:{service_class}:{tag}"))
    return findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    del root
    from . import wire_registry

    controller = next((m for m in modules if m.rel == CONTROLLER_REL),
                      None)
    messages = next((m for m in modules if m.rel == MESSAGES_REL), None)
    if controller is None or messages is None:
        return [Finding(
            code="HVL403", path=CONTROLLER_REL, line=0,
            message="controller/messages module missing — wire-compat "
                    "lint cannot run",
            key="wire-scan-missing")]
    findings = check(controller, messages, wire_registry.RPC_TAGS,
                     wire_registry.MESSAGE_FIELDS)
    for rel, service_class, registry in (
            (ELASTIC_REL, "ElasticService",
             wire_registry.ELASTIC_RPC_TAGS),
            (SERVING_REL, "ServingPlane",
             wire_registry.SERVING_RPC_TAGS)):
        mod = next((m for m in modules if m.rel == rel), None)
        if mod is None:
            findings.append(Finding(
                code="HVL403", path=rel, line=0,
                message=f"{service_class} module missing — its "
                        "wire-compat lint cannot run",
                key=f"wire-scan-missing:{service_class}"))
            continue
        findings.extend(check_service(mod, service_class, registry))
    return findings
