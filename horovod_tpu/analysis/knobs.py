"""HVL1xx — knob-registry lint (docs/analysis.md).

Every ``HOROVOD_*`` runtime knob routes through ``core/config.py``: the
constant declaration is the registry (operational muscle memory: one
grep finds every knob), and a docs row is the operator contract. This
checker enforces both ends:

* HVL101: an ``os.environ`` / ``os.getenv`` read of a string-literal
  ``HOROVOD_*`` name anywhere outside ``core/config.py``. The read must
  go through the declared constant (``_config.HOROVOD_X``) so renames
  and greps stay atomic.
* HVL102: a read through ``<mod>.HOROVOD_X`` where ``HOROVOD_X`` is not
  actually declared in ``core/config.py`` — the typo is caught at lint
  time instead of as an AttributeError on the first execution of a
  possibly-rare code path.
* HVL103: a constant declared in ``core/config.py`` whose env-var name
  appears nowhere under ``docs/`` — an undocumented knob.

Env *writes* (``os.environ[X] = ...``, launcher exports, chaos matrix
subprocess env dicts) are deliberately out of scope: producing a knob is
the launcher's job; the registry disciplines *consumers*.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, List, Optional, Tuple

from .base import Finding, SourceModule, call_name, const_str

CONFIG_REL = "horovod_tpu/core/config.py"

# call shapes that read the environment; (suffix match on dotted name)
_READ_CALLS = ("environ.get", "getenv", "environ.pop")


def declared_knobs(config_mod: SourceModule) -> Dict[str, Tuple[str, int]]:
    """constant-name -> (env-var-name, line) for every module-level
    ``NAME = "HOROVOD_..."`` assignment in core/config.py."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in config_mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            value = const_str(node.value)
            if value is not None and value.startswith("HOROVOD_"):
                out[node.targets[0].id] = (value, node.lineno)
    return out


def _env_key_node(node: ast.AST) -> Optional[ast.AST]:
    """The name-expression of an environment read, or None."""
    if isinstance(node, ast.Call):
        if call_name(node).endswith(_READ_CALLS) and node.args:
            return node.args[0]
        return None
    if isinstance(node, ast.Subscript) and \
            isinstance(node.ctx, ast.Load):
        value = node.value
        if (isinstance(value, ast.Attribute) and
                value.attr == "environ") or \
                (isinstance(value, ast.Name) and value.id == "environ"):
            return node.slice
    return None


def check_env_reads(modules: List[SourceModule],
                    declared: Dict[str, Tuple[str, int]],
                    config_rel: str = CONFIG_REL) -> List[Finding]:
    findings: List[Finding] = []
    constant_names = set(declared)
    for mod in modules:
        if mod.rel == config_rel:
            continue
        for node in ast.walk(mod.tree):
            key = _env_key_node(node)
            if key is None:
                continue
            literal = const_str(key)
            if literal is not None:
                if literal.startswith("HOROVOD_"):
                    findings.append(Finding(
                        code="HVL101", path=mod.rel, line=node.lineno,
                        message=f"literal env read of {literal!r}: use "
                                "the core.config constant",
                        key=f"{literal}@{mod.rel}"))
                continue
            if isinstance(key, ast.Attribute) and \
                    key.attr.startswith("HOROVOD_") and \
                    key.attr not in constant_names:
                findings.append(Finding(
                    code="HVL102", path=mod.rel, line=node.lineno,
                    message=f"env read via {call_name(key)}: constant "
                            f"{key.attr} is not declared in "
                            "core/config.py",
                    key=f"{key.attr}@{mod.rel}"))
            elif isinstance(key, ast.Name) and \
                    key.id.startswith("HOROVOD_") and \
                    key.id not in constant_names:
                # `from core.config import HOROVOD_X` style reads of a
                # name that config does not declare
                findings.append(Finding(
                    code="HVL102", path=mod.rel, line=node.lineno,
                    message=f"env read via bare name {key.id}: not "
                            "declared in core/config.py",
                    key=f"{key.id}@{mod.rel}"))
    return findings


def docs_corpus(root: str) -> str:
    """Concatenated text of every docs/*.md plus README.md — a knob row
    anywhere in the operator docs satisfies HVL103."""
    chunks: List[str] = []
    for path in sorted(glob.glob(os.path.join(root, "docs", "*.md"))) + \
            [os.path.join(root, "README.md")]:
        try:
            with open(path, "r", encoding="utf-8") as f:
                chunks.append(f.read())
        except OSError:
            pass
    return "\n".join(chunks)


# docs name knob families in a combined form ("HOROVOD_RANK/SIZE",
# "HOROVOD_ELASTIC_ADDR / _PORT", "HOROVOD_HIERARCHICAL_ALLREDUCE/
# ALLGATHER") — each slash segment documents a sibling knob
_DOC_KNOB_RE = re.compile(
    r"HOROVOD_[A-Z0-9_]+(?:[`\s]*/[`\s]*_?[A-Z0-9_]+)*")


def documented_knob_names(docs_text: str) -> set:
    out = set()
    for m in _DOC_KNOB_RE.finditer(docs_text):
        parts = re.split(r"[`\s]*/[`\s]*", m.group(0))
        base = parts[0]
        out.add(base)
        for seg in parts[1:]:
            if seg.startswith("HOROVOD_"):
                out.add(seg)
                continue
            stripped = seg.lstrip("_")
            # both readings of the shorthand: a fresh HOROVOD_ name, and
            # the base with its last chunk(s) swapped
            out.add("HOROVOD_" + stripped)
            out.add(base.rsplit("_", 1)[0] + "_" + stripped)
    return out


def check_docs_rows(config_mod: SourceModule,
                    docs_text: str) -> List[Finding]:
    findings: List[Finding] = []
    documented = documented_knob_names(docs_text)
    for const, (env_name, line) in \
            sorted(declared_knobs(config_mod).items()):
        if env_name not in documented:
            findings.append(Finding(
                code="HVL103", path=config_mod.rel, line=line,
                message=f"knob {env_name} ({const}) has no docs row "
                        "under docs/",
                key=env_name))
    return findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    config_mod = next((m for m in modules if m.rel == CONFIG_REL), None)
    if config_mod is None:
        return [Finding(code="HVL102", path=CONFIG_REL, line=0,
                        message="core/config.py not found or unparseable",
                        key="config-missing")]
    declared = declared_knobs(config_mod)
    findings = check_env_reads(modules, declared)
    findings += check_docs_rows(config_mod, docs_corpus(root))
    return findings
