"""HVL5xx — metrics/docs drift (docs/analysis.md).

Three surfaces describe the metric families and they must agree:

* the code — ``registry().counter/gauge/histogram("horovod_...")``
  registration sites,
* ``docs/metrics.md`` — the operator-facing family tables,
* ``tools/metrics_summary.py`` — the ``*_PREFIXES`` section routing.

HVL501: family registered in code, absent from docs/metrics.md.
HVL502: family named in docs, registered nowhere (a ghost row — usually
a rename that only landed on one side).
HVL503: a metrics_summary section prefix that matches no registered
family (the section would silently render empty forever).

Docs tokens support the ``horovod_foo_tx/rx_bytes_total`` combined form
(one row documenting a tx/rx pair) — both expansions count as
documented.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from .base import Finding, SourceModule, call_name, const_str

DOCS_REL = "docs/metrics.md"
SUMMARY_REL = "tools/metrics_summary.py"
_REGISTER_METHODS = ("counter", "gauge", "histogram")
_FAMILY_TOKEN_RE = re.compile(r"horovod_[a-z0-9_]+(?:/[a-z0-9_]+)?")
# not family names: the package itself, and bare plane-prefix mentions
_IGNORE_TOKENS = {"horovod_tpu"}


def _module_str_constants(mod: SourceModule) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings — registration sites
    like ``reg.gauge(GAUGE_OFFSET, ...)`` (obs/tracing.py) name their
    family through a constant, and the scan must see through it."""
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            value = const_str(node.value)
            if value is not None:
                out[node.targets[0].id] = value
    return out


def registered_families(modules: List[SourceModule]
                        ) -> Dict[str, Tuple[str, int]]:
    """family -> (rel, line) of its first registration site."""
    out: Dict[str, Tuple[str, int]] = {}
    for mod in modules:
        consts = _module_str_constants(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dotted = call_name(node)
            if dotted.rsplit(".", 1)[-1] not in _REGISTER_METHODS:
                continue
            arg = node.args[0]
            name = const_str(arg)
            if name is None and isinstance(arg, ast.Name):
                name = consts.get(arg.id)
            if name and name.startswith("horovod_"):
                out.setdefault(name, (mod.rel, node.lineno))
    return out


def _expand(token: str) -> List[str]:
    """'horovod_negotiation_tx/rx_bytes_total' -> both variants."""
    if "/" not in token:
        return [token]
    head, _, rest = token.partition("/")
    alt_first, _, alt_rest = rest.partition("_")
    a = head + ("_" + alt_rest if alt_rest else "")
    b = head.rsplit("_", 1)[0] + "_" + alt_first + \
        ("_" + alt_rest if alt_rest else "")
    return [a, b]


def docs_families(docs_text: str) -> Dict[str, int]:
    """family-ish token -> first line number in docs/metrics.md."""
    out: Dict[str, int] = {}
    for i, line in enumerate(docs_text.splitlines(), start=1):
        for m in _FAMILY_TOKEN_RE.finditer(line):
            raw = m.group(0)
            # "horovod_tpu/tune/" is a package path, not a tx/rx pair
            if raw == "horovod_tpu" or raw.startswith("horovod_tpu/"):
                continue
            for token in _expand(raw):
                if token not in _IGNORE_TOKENS:
                    out.setdefault(token, i)
    return out


def summary_prefixes(summary_mod: SourceModule) -> Dict[str, int]:
    """prefix -> line for every *_PREFIXES tuple in metrics_summary."""
    out: Dict[str, int] = {}
    for node in summary_mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.endswith("_PREFIXES") and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                prefix = const_str(elt)
                if prefix:
                    out[prefix] = node.lineno
    return out


def check(code_families: Dict[str, Tuple[str, int]],
          doc_tokens: Dict[str, int],
          prefixes: Dict[str, int]) -> List[Finding]:
    findings: List[Finding] = []
    doc_set: Set[str] = set(doc_tokens)
    for family, (rel, line) in sorted(code_families.items()):
        if family not in doc_set:
            findings.append(Finding(
                code="HVL501", path=rel, line=line,
                message=f"metric family {family} registered here is "
                        f"missing from {DOCS_REL}",
                key=f"family:{family}"))
    for token, line in sorted(doc_tokens.items()):
        if token in code_families:
            continue
        # only an EXPLICIT prefix mention (trailing underscore, e.g.
        # "horovod_sentry_") is a plane reference; a complete-looking
        # token that happens to prefix a family is exactly the one-sided
        # rename drift this check exists for
        if token.endswith("_") and \
                any(fam.startswith(token) for fam in code_families):
            continue
        findings.append(Finding(
            code="HVL502", path=DOCS_REL, line=line,
            message=f"{DOCS_REL} names {token} but no code registers "
                    "it — stale row or rename drift",
            key=f"docs:{token}"))
    for prefix, line in sorted(prefixes.items()):
        if not any(fam.startswith(prefix) for fam in code_families):
            findings.append(Finding(
                code="HVL503", path=SUMMARY_REL, line=line,
                message=f"metrics_summary section prefix {prefix!r} "
                        "matches no registered family",
                key=f"prefix:{prefix}"))
    return findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    from .base import load_module

    code_families = registered_families(modules)
    docs_path = os.path.join(root, DOCS_REL)
    try:
        with open(docs_path, "r", encoding="utf-8") as f:
            doc_tokens = docs_families(f.read())
    except OSError:
        doc_tokens = {}
    summary_mod = load_module(os.path.join(root, SUMMARY_REL), root)
    prefixes = summary_prefixes(summary_mod) if summary_mod else {}
    return check(code_families, doc_tokens, prefixes)
