"""HVL3xx — collective-divergence lint (docs/analysis.md).

Collectives must execute in rank-identical order: every rank joins every
negotiation cycle, every sentry rendezvous, every payload exchange, in
the same sequence — the invariant ``flush_ordinal``'s cross-check and
PR 8's consensus judge verify at *runtime*. This is the static twin: a
collective or rendezvous call site lexically reachable under a
rank-conditional branch is exactly the shape that lets one rank skip (or
double-join) an exchange its peers are parked in, which surfaces hours
later as a hang or a desync naming the wrong rank.

Legitimate rank-gated sites exist — coordinator-only bookkeeping,
rank-0 persistence after a collective commit — and are waived inline
with a written reason (``# hvdlint: disable=HVL301 -- why``), which
doubles as the review artifact the runtime checks don't give you.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .base import Finding, SourceModule, call_name

# callee last-names that are collectives wherever they appear
COLLECTIVE_NAMES: Set[str] = {
    "allreduce", "allreduce_async", "grouped_allreduce",
    "allgather", "allgather_async", "all_gather", "all_to_all",
    "broadcast", "broadcast_async", "broadcast_object",
    "broadcast_parameters", "barrier", "reduce_scatter",
    "quantized_allreduce",
    "psum", "pmean", "pmax", "pmin",
}

# callee last-names that are collective ONLY on a rendezvous/controller
# receiver (`self._cycles.submit(...)`, `client.payload(...)`)
CHANNEL_METHODS: Set[str] = {"submit", "cycle", "payload", "sentry"}
CHANNEL_RECEIVERS = ("rendezvous", "_cycles", "_payloads", "_sentry",
                     "client", "controller", "negotiator")

# identifiers in an `if` test that make the branch rank-conditional
RANK_IDENTIFIERS: Set[str] = {
    "rank", "_rank", "local_rank", "cross_rank", "world_rank",
    "my_rank", "node_rank", "push_rank", "root_rank",
}


def is_collective_call(node: ast.Call) -> bool:
    dotted = call_name(node)
    if not dotted:
        return False
    last = dotted.rsplit(".", 1)[-1]
    if last in COLLECTIVE_NAMES:
        return True
    if last in CHANNEL_METHODS and "." in dotted:
        receiver = dotted.rsplit(".", 1)[0].lower()
        return any(tok in receiver for tok in CHANNEL_RECEIVERS)
    return False


def is_rank_conditional(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in RANK_IDENTIFIERS:
            return True
        if isinstance(node, ast.Attribute) and \
                node.attr in RANK_IDENTIFIERS:
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, mod: SourceModule):
        self.mod = mod
        self.findings: List[Finding] = []
        self.qual: List[str] = []
        self.rank_depth = 0

    def _qualname(self) -> str:
        return ".".join(self.qual) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.qual.append(node.name)
        self.generic_visit(node)
        self.qual.pop()

    def _visit_func(self, node) -> None:
        self.qual.append(node.name)
        # a nested def under a rank conditional runs later, possibly on
        # every rank — reset the conditional context inside it
        saved, self.rank_depth = self.rank_depth, 0
        self.generic_visit(node)
        self.rank_depth = saved
        self.qual.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_If(self, node: ast.If) -> None:
        conditional = is_rank_conditional(node.test)
        self.visit(node.test)  # calls in the test run on every rank
        if conditional:
            self.rank_depth += 1
        for child in node.body + node.orelse:
            self.visit(child)
        if conditional:
            self.rank_depth -= 1

    def visit_IfExp(self, node: ast.IfExp) -> None:
        conditional = is_rank_conditional(node.test)
        self.visit(node.test)
        if conditional:
            self.rank_depth += 1
        self.visit(node.body)
        self.visit(node.orelse)
        if conditional:
            self.rank_depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        if self.rank_depth > 0 and is_collective_call(node):
            callee = call_name(node)
            self.findings.append(Finding(
                code="HVL301", path=self.mod.rel, line=node.lineno,
                message=f"collective call {callee}() under a "
                        "rank-conditional branch — every rank must join "
                        "every exchange in the same order",
                key=f"{callee}@{self.mod.rel}:{self._qualname()}"))
        self.generic_visit(node)


def scan_module(mod: SourceModule) -> List[Finding]:
    visitor = _Visitor(mod)
    visitor.visit(mod.tree)
    return visitor.findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    del root
    findings: List[Finding] = []
    for mod in modules:
        findings.extend(scan_module(mod))
    return findings
