"""HVL6xx — error-taxonomy lint (docs/analysis.md).

The repo's structured errors survive the wire as *text*: a tag rendered
by a ``format_*`` helper in ``core/status.py`` rides every abort reason,
and ``Status.raise_if_error`` re-parses it into the typed exception on
the receiving rank. That round trip is a contract with three legs this
checker pins:

* HVL601: a ``HorovodInternalError`` subclass defined in
  ``core/status.py`` that ``raise_if_error`` never raises — the typed
  error can be thrown locally but arrives at every peer as the generic
  base class, losing its attribution.
* HVL602: a ``format_*`` tag renderer without a ``parse_*`` twin wired
  into ``raise_if_error`` — a tag that can be written but never read.
* HVL603: a ``HorovodInternalError`` subclass defined *outside*
  ``core/status.py`` that is not in the wire-compat error registry —
  new planes may add structured errors, but must write down how the
  attribution survives (or deliberately doesn't survive) the wire.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .base import Finding, SourceModule, call_name

STATUS_REL = "horovod_tpu/core/status.py"
BASE_CLASS = "HorovodInternalError"


def _class_bases(node: ast.ClassDef) -> List[str]:
    names = []
    for b in node.bases:
        if isinstance(b, ast.Name):
            names.append(b.id)
        elif isinstance(b, ast.Attribute):
            names.append(b.attr)
    return names


def status_subclasses(status_mod: SourceModule) -> Dict[str, int]:
    """name -> line of every (transitive) HorovodInternalError subclass
    defined in core/status.py."""
    known: Set[str] = {BASE_CLASS}
    out: Dict[str, int] = {}
    changed = True
    while changed:
        changed = False
        for node in status_mod.tree.body:
            if isinstance(node, ast.ClassDef) and node.name not in known \
                    and any(b in known for b in _class_bases(node)):
                known.add(node.name)
                out[node.name] = node.lineno
                changed = True
    return out


def _find_raise_if_error(status_mod: SourceModule):
    for node in ast.walk(status_mod.tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "raise_if_error":
            return node
    return None


def raised_in_raise_if_error(status_mod: SourceModule) -> Set[str]:
    fn = _find_raise_if_error(status_mod)
    out: Set[str] = set()
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Raise) and \
                isinstance(node.exc, ast.Call):
            name = call_name(node.exc)
            if name:
                out.add(name.rsplit(".", 1)[-1])
    return out


def parsers_called(status_mod: SourceModule) -> Set[str]:
    fn = _find_raise_if_error(status_mod)
    out: Set[str] = set()
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node).rsplit(".", 1)[-1]
            if name.startswith("parse_"):
                out.add(name)
    return out


def check_status(status_mod: SourceModule) -> List[Finding]:
    findings: List[Finding] = []
    raised = raised_in_raise_if_error(status_mod)
    for name, line in sorted(status_subclasses(status_mod).items()):
        if name not in raised:
            findings.append(Finding(
                code="HVL601", path=status_mod.rel, line=line,
                message=f"{name} subclasses {BASE_CLASS} but "
                        "Status.raise_if_error never raises it — its "
                        "wire tag cannot round-trip",
                key=f"err:{name}"))
    # every format_X needs parse_X, and parse_X must be wired into
    # raise_if_error (reading the tag is what makes it a contract)
    defined = {n.name: n.lineno for n in status_mod.tree.body
               if isinstance(n, ast.FunctionDef)}
    parsers = parsers_called(status_mod)
    for name, line in sorted(defined.items()):
        if not name.startswith("format_"):
            continue
        twin = "parse_" + name.removeprefix("format_")
        if twin not in defined or twin not in parsers:
            findings.append(Finding(
                code="HVL602", path=status_mod.rel, line=line,
                message=f"{name} has no {twin} twin wired into "
                        "Status.raise_if_error — a tag that can be "
                        "written but never read",
                key=f"tag:{name}"))
    return findings


def check_external_subclasses(modules: List[SourceModule],
                              status_names: Set[str],
                              registry: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    known = status_names | {BASE_CLASS}
    for mod in modules:
        if mod.rel == STATUS_REL:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and \
                    any(b in known for b in _class_bases(node)):
                if node.name not in registry:
                    findings.append(Finding(
                        code="HVL603", path=mod.rel, line=node.lineno,
                        message=f"{node.name} subclasses {BASE_CLASS} "
                                "outside core/status.py but is not in "
                                "the wire-compat error registry — "
                                "state how its attribution survives "
                                "the wire",
                        key=f"err:{node.name}@{mod.rel}"))
    return findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    del root
    from . import wire_registry

    status_mod = next((m for m in modules if m.rel == STATUS_REL), None)
    if status_mod is None:
        return [Finding(code="HVL601", path=STATUS_REL, line=0,
                        message="core/status.py missing — error-taxonomy "
                                "lint cannot run",
                        key="status-missing")]
    findings = check_status(status_mod)
    findings += check_external_subclasses(
        modules, set(status_subclasses(status_mod)),
        wire_registry.ERROR_CLASSES)
    return findings
