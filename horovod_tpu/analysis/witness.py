"""Runtime lock witness (docs/analysis.md, ``HOROVOD_LOCK_WITNESS=1``).

The AST lock-order pass (``analysis/locks.py``) is intra-procedural: it
sees ``with self._lock:`` nesting but not an order established through a
call chain (engine holds its lock, calls into the registry, which takes
its own). This opt-in runtime layer closes that gap in tests: witnessed
locks record the *actual* per-thread acquisition order into one global
held-before graph, and an acquisition that would close a cycle raises
``LockInversionError`` at the exact second site — the moment the
inverted order is *attempted*, not the rare schedule where it deadlocks.

Off by default and free when off: ``maybe_wrap`` returns the raw lock
unless the knob is set, so production paths carry zero overhead and the
witness can wrap hot locks without a second thought. Timing-dependent
cases (Condition-wrapped locks, ``_release_save`` re-entry) bypass
recording by design — the witness is a test amplifier, not a jailer.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

# The knob constant lives in core/config.py like every other knob; the
# fallback literal keeps this module loadable standalone (tools/hvdlint
# loads the analysis package by path on jax-less machines, where the
# parent package — and so core.config — is unreachable).
try:
    from ..core import config as _config

    HOROVOD_LOCK_WITNESS = _config.HOROVOD_LOCK_WITNESS
except ImportError:  # pragma: no cover - the standalone load
    HOROVOD_LOCK_WITNESS = "HOROVOD_LOCK_WITNESS"


class LockInversionError(RuntimeError):
    """Two locks were acquired in both orders across the process's
    lifetime — a deadlock waiting for the right schedule."""


class LockWitness:
    """Global held-before graph over witnessed lock names.

    ``on_acquire(name)`` runs before the raw grab: for every lock the
    calling thread already holds it checks whether ``name`` can reach
    the held lock through previously observed edges — if so, the
    reverse order was already witnessed and the acquisition is an
    inversion — and otherwise records the edge ``held -> name``.
    ``on_acquired(name)`` pushes onto the thread's held stack once the
    raw acquire succeeded."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        # edge -> (thread name, first-seen stack summary)
        self._edges: Dict[Tuple[str, str], str] = {}
        # incremental adjacency mirror of _edges: rebuilt-per-acquire
        # would serialize every wrapped lock in the process on an
        # O(edges) scan once the witness is armed
        self._adj: Dict[str, List[str]] = {}
        self._tls = threading.local()

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    def _reaches_locked(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst through observed edges, or None.
        Caller holds ``_graph_lock``."""
        adj = self._adj
        seen = {src}
        frontier = [(src, [src])]
        while frontier:
            node, path = frontier.pop()
            for nxt in adj.get(node, []):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, path + [nxt]))
        return None

    def on_acquire(self, name: str) -> List[Tuple[str, str]]:
        """Called BEFORE the raw lock is acquired: an inversion must
        raise while the caller holds nothing new, or the diagnosis
        itself would wedge the lock it was acquiring. Reach-check and
        edge insertion happen atomically under the graph lock, so two
        threads establishing opposite orders concurrently cannot both
        slip their edge in unchecked. Returns the edges newly recorded
        by this call so a failed non-blocking acquire can retract them
        (an order that never happened must not condemn a later one).

        Re-acquiring a lock this thread already holds is a no-op: an
        owned re-entrant grab (RLock) can never deadlock, so patterns
        like ``with a: with b: with a:`` are not inversions."""
        held = self._held()
        if name in held:
            return []
        me = f"thread {threading.current_thread().name}"
        added: List[Tuple[str, str]] = []
        with self._graph_lock:
            for h in held:
                path = self._reaches_locked(name, h)
                if path is not None:
                    first = self._edges.get((path[0], path[1]), "?")
                    raise LockInversionError(
                        f"lock-order inversion: acquiring {name!r} while "
                        f"holding {h!r}, but the order "
                        f"{' -> '.join(path)} was already witnessed "
                        f"(first at: {first})")
                if (h, name) not in self._edges:
                    self._edges[(h, name)] = me
                    self._adj.setdefault(h, []).append(name)
                    added.append((h, name))
        return added

    def on_acquired(self, name: str) -> None:
        """Called after the raw acquire succeeded."""
        self._held().append(name)

    def retract(self, edges: List[Tuple[str, str]]) -> None:
        """Remove edges recorded by an acquire attempt that failed (a
        trylock that returned False established no order)."""
        if not edges:
            return
        with self._graph_lock:
            for edge in edges:
                if self._edges.pop(edge, None) is not None:
                    succs = self._adj.get(edge[0], [])
                    if edge[1] in succs:
                        succs.remove(edge[1])

    def on_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._graph_lock:
            return dict(self._edges)

    def reset(self) -> None:
        with self._graph_lock:
            self._edges.clear()
            self._adj.clear()
        self._tls = threading.local()


_GLOBAL = LockWitness()


def global_witness() -> LockWitness:
    return _GLOBAL


def enabled() -> bool:
    # same disabled spellings as native_controller_enabled() and the
    # bench init cache: an explicit "off"/"no" must never ARM the witness
    return os.environ.get(HOROVOD_LOCK_WITNESS, "").strip().lower() \
        not in ("", "0", "false", "off", "no")


class WitnessedLock:
    """Context-manager/acquire/release shim recording order into a
    witness; everything else delegates to the wrapped lock. Bound
    methods reached through ``__getattr__`` (``Condition``'s
    ``_release_save``/``_acquire_restore``) bypass recording — their
    release-and-reacquire is not an ordering decision."""

    def __init__(self, lock, name: str,
                 witness: Optional[LockWitness] = None):
        self._lock = lock
        self._name = name
        self._witness = witness or _GLOBAL

    def acquire(self, *args, **kwargs):
        # inversion check BEFORE the raw grab: on a violation the raw
        # lock is untouched, so the structured error propagates instead
        # of wedging every other thread behind a lock nobody releases
        added = self._witness.on_acquire(self._name)
        got = self._lock.acquire(*args, **kwargs)
        if got:
            self._witness.on_acquired(self._name)
        else:
            self._witness.retract(added)
        return got

    def release(self):
        self._witness.on_release(self._name)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __getattr__(self, attr):
        return getattr(self._lock, attr)

    def __repr__(self) -> str:
        return f"<WitnessedLock {self._name} {self._lock!r}>"


def maybe_wrap(lock, name: str):
    """Witness ``lock`` under HOROVOD_LOCK_WITNESS=1; otherwise return it
    untouched (zero overhead when the knob is off)."""
    if not enabled():
        return lock
    return WitnessedLock(lock, name)
