"""hvdlint: the contract-analysis plane (docs/analysis.md).

Pure-stdlib AST checkers that enforce the repo's cross-cutting
invariants — knob registry, lock order, collective order, wire
compatibility, metrics/docs agreement, error taxonomy, pytest markers —
plus an opt-in runtime lock witness (``HOROVOD_LOCK_WITNESS=1``) for the
orders the AST pass cannot see. Nothing in this package may import jax
(or anything that transitively does): ``tools/hvdlint.py`` must run
anywhere ``runner.network`` does, including by loading this package
straight from its files on machines without the package installed.

CLI: ``python tools/hvdlint.py [--json]``; gate: ``tools/lint.sh``.
Tier-1 enforcement: ``tests/test_analysis.py`` runs the whole suite over
the repo and fails on any unwaived finding.
"""

# Only the witness is imported eagerly: it is the one piece production
# code touches (obs/registry, ops/engine, ops/controller wrap their
# locks through maybe_wrap), and it must stay cheap. The checker suite
# (runner + 7 checker modules) loads lazily via PEP 562 so a worker's
# import of horovod_tpu never pays for — or can be broken by — lint-only
# code.
from .witness import (
    LockInversionError,
    LockWitness,
    WitnessedLock,
    global_witness,
    maybe_wrap,
)

__all__ = [
    "BASELINE_REL",
    "Baseline",
    "CODES",
    "Finding",
    "LockInversionError",
    "LockWitness",
    "WitnessedLock",
    "global_witness",
    "maybe_wrap",
    "run_all",
    "summary_json",
]

_LAZY = {
    "BASELINE_REL": "runner",
    "run_all": "runner",
    "summary_json": "runner",
    "Baseline": "base",
    "CODES": "base",
    "Finding": "base",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
