"""hvdlint orchestration: run every checker, apply suppressions and the
baseline, produce the report (docs/analysis.md).

``tools/hvdlint.py`` is the CLI face; tests call :func:`run_all`
directly. Adding a checker = add a module with a
``run(root, modules) -> List[Finding]`` function, register it in
``CHECKERS`` below, claim a code range in ``base.CODES``, and document
the row in docs/analysis.md — the test suite cross-checks all three.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from . import (
    collectives,
    errors,
    knobs,
    locks,
    markers,
    metrics_docs,
    wire,
)
from .base import (
    Baseline,
    CODES,
    Finding,
    SourceModule,
    apply_inline_suppressions,
    load_tree,
)

# checker name -> (module, which tree it scans)
CHECKERS = (
    ("knobs", knobs, "library"),
    ("locks", locks, "library"),
    ("collectives", collectives, "library"),
    ("wire", wire, "library"),
    ("metrics_docs", metrics_docs, "library"),
    ("errors", errors, "library"),
    ("markers", markers, "tests"),
)

BASELINE_REL = "tools/hvdlint_baseline.json"


def run_all(root: str,
            baseline_path: Optional[str] = None,
            only: Optional[List[str]] = None) -> dict:
    """Run the suite over the repo at ``root``.

    Returns ``{"findings": [Finding...], "waived": int,
    "by_code": {...}, "checkers": [...], "ok": bool}`` — the dict the
    CLI serializes (Findings rendered) as its final JSON line."""
    library = load_tree(root, ["horovod_tpu"])
    tests = load_tree(root, ["tests"])
    modules_by_rel: Dict[str, SourceModule] = {
        m.rel: m for m in library + tests}

    findings: List[Finding] = []
    ran: List[str] = []
    if only:
        unknown = sorted(set(only) - {name for name, _, _ in CHECKERS})
        if unknown:  # a typo'd --only must never turn the gate green
            raise ValueError(
                f"unknown checker(s): {', '.join(unknown)} — valid: "
                f"{', '.join(name for name, _, _ in CHECKERS)}")
    for name, module, scope in CHECKERS:
        if only and name not in only:
            continue
        ran.append(name)
        scan = library if scope == "library" else tests
        for f in module.run(root, scan):
            if f.code not in CODES:  # a checker emitting outside its range
                raise ValueError(
                    f"checker {name} emitted unknown code {f.code}")
            findings.append(f)

    findings = apply_inline_suppressions(findings, modules_by_rel)
    # a malformed inline suppression never silently no-ops: reasonless /
    # typo'd-code comments are findings themselves (the baseline layer's
    # HVL901/902 contract, applied to the inline layer)
    for mod in library + tests:
        findings.extend(mod.suppression_hygiene())

    if baseline_path is None:
        import os

        baseline_path = os.path.join(root, BASELINE_REL)
    baseline = Baseline.load(baseline_path)
    findings, hygiene, waived = baseline.apply(findings)
    findings.extend(hygiene)

    by_code: Dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    return {
        "findings": findings,
        "waived": waived,
        "by_code": dict(sorted(by_code.items())),
        "checkers": ran,
        "ok": not findings,
    }


def summary_json(result: dict) -> str:
    """The final-line JSON contract (the trace_merge/bench convention)."""
    return json.dumps({
        "tool": "hvdlint",
        "ok": result["ok"],
        "findings": len(result["findings"]),
        "waived": result["waived"],
        "by_code": result["by_code"],
        "checkers": result["checkers"],
    })


def render(result: dict) -> List[str]:
    return [f.render() for f in result["findings"]]
