"""hvdlint core: findings, suppressions, baselines, source loading.

The contract-analysis plane (docs/analysis.md) statically enforces the
repo's cross-cutting conventions — knob registry, lock order, collective
order, wire compatibility, metrics/docs agreement, error taxonomy,
pytest markers. This module is the shared substrate every checker builds
on and is deliberately **stdlib-only**: ``tools/hvdlint.py`` must run
anywhere ``runner.network`` does (CI boxes, jax-less workstations), so
nothing under ``horovod_tpu/analysis/`` may import jax, numpy, or any
module that transitively does.

Suppression syntax (the single place a violation may be silenced in
source)::

    something_flagged()  # hvdlint: disable=HVL301 -- reason why this is fine

applies to the flagged line or, when placed alone, to the line directly
below it. Repo-wide waivers live in ``tools/hvdlint_baseline.json`` as
``{"code", "key", "reason"}`` records keyed by each finding's *stable*
fingerprint (never a line number, so unrelated edits don't invalidate
them); a waiver without a written reason, or one matching nothing, is
itself a finding — the baseline can only shrink honestly.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

# One catalogue of every code a checker may emit: the runner validates
# emitted findings against it, docs/analysis.md and the troubleshooting
# table are generated from the same names, and a typo'd suppression code
# fails loudly instead of silently suppressing nothing.
CODES: Dict[str, str] = {
    # knob registry (analysis/knobs.py)
    "HVL101": "HOROVOD_* env read through a string literal outside "
              "core/config.py — reference the core.config constant",
    "HVL102": "env read references a knob constant not declared in "
              "core/config.py",
    "HVL103": "knob constant declared in core/config.py has no row in "
              "docs/ — document it",
    # lock order (analysis/locks.py)
    "HVL201": "lock-acquisition order cycle across the merged "
              "per-module lock graphs — potential deadlock",
    # collective divergence (analysis/collectives.py)
    "HVL301": "collective/rendezvous call reachable under a "
              "rank-conditional branch — rank-divergent collective order",
    # wire compatibility (analysis/wire.py)
    "HVL401": "controller RPC tag not present in the wire-compat "
              "registry naming its native-controller degrade",
    "HVL402": "negotiation message field not present in the wire-compat "
              "registry naming its predates-the-field degrade",
    "HVL403": "stale wire-compat registry entry: names a tag/field the "
              "code no longer has",
    # metrics/docs drift (analysis/metrics_docs.py)
    "HVL501": "metric family registered in code but missing from "
              "docs/metrics.md",
    "HVL502": "metric family named in docs/metrics.md but registered "
              "nowhere in code",
    "HVL503": "tools/metrics_summary.py section prefix matches no "
              "registered family",
    # error taxonomy (analysis/errors.py)
    "HVL601": "structured error defined in core/status.py is never "
              "raised by Status.raise_if_error — its wire tag cannot "
              "round-trip",
    "HVL602": "format_* tag renderer has no parse_* twin wired into "
              "Status.raise_if_error",
    "HVL603": "HorovodInternalError subclass defined outside "
              "core/status.py is not in the wire-compat error registry",
    # pytest markers (analysis/markers.py)
    "HVL701": "pytest marker used in tests/ but not registered in "
              "pyproject.toml [tool.pytest.ini_options] markers",
    # suppression hygiene (analysis/base.py, analysis/runner.py)
    "HVL901": "stale baseline waiver: matches no current finding",
    "HVL902": "baseline waiver carries no written reason",
    "HVL903": "inline suppression without a written reason — it "
              "suppresses nothing until '-- reason' is added",
    "HVL904": "inline suppression names an unknown finding code — "
              "typo'd codes must fail loudly, not silently no-op",
}

_SUPPRESS_RE = re.compile(
    r"#\s*hvdlint:\s*disable=([A-Z0-9,\s]+?)(?:\s*--\s*(.*))?\s*$")


@dataclass
class Finding:
    """One contract violation.

    ``key`` is the stable fingerprint baseline waivers match against —
    derived from *what* is wrong (env name + function, lock-cycle node
    set, tag name, …), never from line numbers, so formatting-only edits
    neither create nor destroy waiver matches.
    """

    code: str
    path: str  # repo-relative, "" for repo-level findings
    line: int  # 1-based; 0 for repo-level findings
    message: str
    key: str

    def render(self) -> str:
        where = f"{self.path}:{self.line}: " if self.path else ""
        return f"{where}{self.code} {self.message} [{self.key}]"


@dataclass
class SourceModule:
    """A parsed python module plus everything checkers keep asking for."""

    path: str  # absolute
    rel: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    def suppressed_codes(self, line: int) -> List[str]:
        """Codes EFFECTIVELY disabled at ``line`` (1-based): an inline
        trailing comment on the line itself, or a comment-ONLY line
        directly above (a trailing suppression on the previous statement
        must not leak onto this one). A suppression without a written
        reason or with an unknown code suppresses nothing — it is
        reported instead (HVL903/HVL904, see ``suppression_hygiene``)."""
        codes: List[str] = []
        for ln in (line, line - 1):
            if not 1 <= ln <= len(self.lines):
                continue
            text = self.lines[ln - 1]
            if ln == line - 1 and not text.lstrip().startswith("#"):
                continue
            m = _SUPPRESS_RE.search(text)
            if m and (m.group(2) or "").strip():
                codes.extend(c.strip() for c in m.group(1).split(",")
                             if c.strip() in CODES)
        return codes

    def suppression_hygiene(self) -> List["Finding"]:
        """HVL903/HVL904 for every malformed suppression comment in this
        module — the inline layer enforces the same written-reason and
        known-code contract the baseline layer does."""
        out: List[Finding] = []
        for ln, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            listed = [c.strip() for c in m.group(1).split(",")
                      if c.strip()]
            if not (m.group(2) or "").strip():
                out.append(Finding(
                    code="HVL903", path=self.rel, line=ln,
                    message="inline suppression has no '-- reason'; it "
                            "is ignored until one is written",
                    key=f"inline-reasonless:{self.rel}:{ln}"))
            for code in listed:
                if code not in CODES:
                    out.append(Finding(
                        code="HVL904", path=self.rel, line=ln,
                        message=f"inline suppression names unknown code "
                                f"{code!r}; it suppresses nothing",
                        key=f"inline-unknown:{code}:{self.rel}:{ln}"))
        return out


def load_module(path: str, root: str) -> Optional[SourceModule]:
    """Parse one file; syntactically-broken files return None (the test
    suite, not the linter, owns syntax errors)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError):
        return None
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return SourceModule(path=path, rel=rel, source=source, tree=tree,
                        lines=source.splitlines())


def iter_py_files(root: str, subdirs: Iterable[str]) -> List[str]:
    """All .py files under ``root/<subdir>`` for each subdir, sorted for
    deterministic finding order."""
    out: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and
                           not d.startswith(".")]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


def load_tree(root: str, subdirs: Iterable[str]) -> List[SourceModule]:
    mods = []
    for path in iter_py_files(root, subdirs):
        mod = load_module(path, root)
        if mod is not None:
            mods.append(mod)
    return mods


def apply_inline_suppressions(
        findings: List[Finding],
        modules: Dict[str, SourceModule]) -> List[Finding]:
    """Drop findings whose source line (or the line above it) carries a
    matching ``# hvdlint: disable=CODE`` comment."""
    kept: List[Finding] = []
    for f in findings:
        mod = modules.get(f.path)
        if mod is not None and f.line and \
                f.code in mod.suppressed_codes(f.line):
            continue
        kept.append(f)
    return kept


# -- baseline ----------------------------------------------------------------

@dataclass
class Baseline:
    """Checked-in repo-wide waivers (tools/hvdlint_baseline.json)."""

    entries: List[dict] = field(default_factory=list)
    path: str = ""

    @staticmethod
    def load(path: str) -> "Baseline":
        if not os.path.exists(path):
            return Baseline(entries=[], path=path)
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return Baseline(entries=list(data.get("waivers", [])), path=path)

    def apply(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], int]:
        """Returns (kept, hygiene_findings, waived_count): findings that
        survive, plus HVL901/HVL902 findings about the baseline itself."""
        hygiene: List[Finding] = []
        matched = [False] * len(self.entries)
        kept: List[Finding] = []
        waived = 0
        for f in findings:
            hit = False
            for i, e in enumerate(self.entries):
                if e.get("code") == f.code and e.get("key") == f.key:
                    matched[i] = True
                    hit = True
            if hit:
                waived += 1
            else:
                kept.append(f)
        rel = os.path.basename(self.path) if self.path else "baseline"
        for i, e in enumerate(self.entries):
            if not str(e.get("reason", "")).strip():
                hygiene.append(Finding(
                    code="HVL902", path=f"tools/{rel}", line=0,
                    message=f"waiver {e.get('code')}/{e.get('key')} has "
                            "no written reason",
                    key=f"reasonless:{e.get('code')}:{e.get('key')}"))
            if not matched[i]:
                hygiene.append(Finding(
                    code="HVL901", path=f"tools/{rel}", line=0,
                    message=f"stale waiver {e.get('code')}/"
                            f"{e.get('key')}: matches no finding — "
                            "delete it",
                    key=f"stale:{e.get('code')}:{e.get('key')}"))
        return kept, hygiene, waived


def call_name(node: ast.AST) -> str:
    """Best-effort dotted name of a call's callee ('' when dynamic)."""
    if isinstance(node, ast.Call):
        node = node.func
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
