"""HVL2xx — lock-order analysis (docs/analysis.md).

The engine's two-channel discipline exists because a real lock-inversion
deadlock was found by hand (PR 9: a flush parked in a coordinator
rendezvous holding the cycle connection's request lock). This checker
makes that class of bug a lint failure instead of a review catch:

* extract a per-module lock-acquisition graph from ``with self._lock:``
  nesting and paired ``.acquire()``/``.release()`` calls,
* merge every module's graph into one global order graph,
* fail (HVL201) on any cycle — two code paths that take the same two
  locks in opposite orders.

Lock identity is lexical: ``self._lock`` inside class ``C`` of module
``M`` is the node ``M:C._lock``; a module-level ``_LOCK`` is ``M:_LOCK``.
That makes the analysis conservative in the safe direction — distinct
instances of one class share a node, so an inversion *within* a class is
always caught, while cross-object aliasing the AST cannot see is the
runtime witness's job (``analysis/witness.py``, HOROVOD_LOCK_WITNESS=1).

Known limits (by design, documented in docs/analysis.md): the pass is
intra-procedural — an edge exists only where one function lexically
nests two acquisitions. Calls made while holding a lock are not chased;
the runtime witness records those orders in tests and raises on the
inversions this pass cannot see.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from .base import Finding, SourceModule

# attribute / name shapes that denote a synchronization primitive
_LOCKISH_RE = re.compile(r"(lock|mutex|cond)", re.IGNORECASE)

Edge = Tuple[str, str]
Witness = Tuple[str, int, str]  # (rel path, line, function qualname)


def _lockish_name(node: ast.AST, module: str, cls: str) -> str:
    """Canonical node name when ``node`` looks like a lock, else ''."""
    if isinstance(node, ast.Attribute) and _LOCKISH_RE.search(node.attr):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            owner = f"{cls}." if cls else ""
            return f"{module}:{owner}{node.attr}"
        try:
            base = ast.unparse(node.value)
        except Exception:  # pragma: no cover - unparse is total on 3.9+
            base = "?"
        return f"{module}:{base}.{node.attr}"
    if isinstance(node, ast.Name) and _LOCKISH_RE.search(node.id):
        return f"{module}:{node.id}"
    return ""


class _FunctionScan:
    """Walks one function body in statement order, maintaining the held
    stack; records an edge held -> acquired for every nested grab."""

    def __init__(self, module: str, cls: str, qualname: str, rel: str,
                 edges: Dict[Edge, Witness]):
        self.module = module
        self.cls = cls
        self.qualname = qualname
        self.rel = rel
        self.edges = edges
        self.held: List[str] = []

    def _grab(self, name: str, line: int) -> None:
        for h in self.held:
            if h != name and (h, name) not in self.edges:
                self.edges[(h, name)] = (self.rel, line, self.qualname)
        self.held.append(name)

    def _drop(self, name: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i] == name:
                del self.held[i]
                return

    def scan(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    @staticmethod
    def _own_exprs(stmt: ast.stmt) -> List[ast.AST]:
        """The statement's OWN expressions (never its nested blocks,
        which the structural recursion owns)."""
        if isinstance(stmt, ast.Expr):
            return [stmt.value]
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Return)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.Assert):
            return [stmt.test]
        return []

    def _sync_calls(self, expr: ast.AST):
        """(lock name, 'acquire'|'release', line) for every sync-
        primitive call in the expression."""
        out = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("acquire", "release"):
                target = _lockish_name(node.func.value, self.module,
                                       self.cls)
                if target:
                    out.append((target, node.func.attr, node.lineno))
        return out

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            grabbed: List[str] = []
            for item in stmt.items:
                name = _lockish_name(item.context_expr, self.module,
                                     self.cls)
                if name:
                    self._grab(name, stmt.lineno)
                    grabbed.append(name)
            self.scan(stmt.body)
            for name in reversed(grabbed):
                self._drop(name)
            return
        # acquire()/release() in any expression position the repo (or a
        # future trylock/timeout idiom) might use: bare statement,
        # `got = lock.acquire(False)`, `if lock.acquire(timeout=5):`,
        # `assert lock.acquire(...)` — an invisible acquire form would
        # let a real inversion lint green
        for expr in self._own_exprs(stmt):
            for target, kind, line in self._sync_calls(expr):
                if kind == "acquire":
                    self._grab(target, line)
                else:
                    self._drop(target)
        # nested defs get their own empty held stack (they run later)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionScan(self.module, self.cls,
                          f"{self.qualname}.{stmt.name}", self.rel,
                          self.edges).scan(stmt.body)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # compound statements: walk each block in order with the SAME
        # held stack — branch-local acquires are approximated as
        # sequential, which only ever ADDS conservative edges
        for block_name in ("body", "orelse", "finalbody"):
            block = getattr(stmt, block_name, None)
            if block:
                self.scan(block)
        for handler in getattr(stmt, "handlers", []) or []:
            self.scan(handler.body)


def module_graph(mod: SourceModule) -> Dict[Edge, Witness]:
    """Held-before edges observed in one module."""
    pkg = mod.rel.removesuffix(".py").replace("/", ".")
    edges: Dict[Edge, Witness] = {}

    def visit(body: List[ast.stmt], cls: str, prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit(node.body, node.name,
                      f"{prefix}{node.name}.")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionScan(pkg, cls, f"{prefix}{node.name}", mod.rel,
                              edges).scan(node.body)

    visit(mod.tree.body, "", "")
    return edges


def merge_graphs(graphs: List[Dict[Edge, Witness]]) -> Dict[Edge, Witness]:
    merged: Dict[Edge, Witness] = {}
    for g in graphs:
        for edge, witness in g.items():
            merged.setdefault(edge, witness)
    return merged


def find_cycles(edges: Dict[Edge, Witness]) -> List[List[str]]:
    """Strongly-connected components with >1 node (Tarjan), i.e. sets of
    locks with circular held-before orders."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        # iterative Tarjan: the lock graph is tiny, but recursion depth
        # should never depend on repo size
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack[node] = True
            advanced = False
            succs = graph.get(node, [])
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work[-1] = (node, i + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: List[str] = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def cycle_findings(edges: Dict[Edge, Witness]) -> List[Finding]:
    findings: List[Finding] = []
    for comp in find_cycles(edges):
        members = set(comp)
        involved = [(e, w) for e, w in sorted(edges.items())
                    if e[0] in members and e[1] in members]
        detail = "; ".join(
            f"{a} -> {b} at {w[0]}:{w[1]} ({w[2]})"
            for (a, b), w in involved)
        rel, line = (involved[0][1][0], involved[0][1][1]) if involved \
            else ("", 0)
        findings.append(Finding(
            code="HVL201", path=rel, line=line,
            message="lock-order cycle between "
                    f"{{{', '.join(comp)}}}: {detail}",
            key="cycle:" + "->".join(comp)))
    return findings


def run(root: str, modules: List[SourceModule]) -> List[Finding]:
    del root
    merged = merge_graphs([module_graph(m) for m in modules])
    return cycle_findings(merged)
