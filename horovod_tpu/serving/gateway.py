"""Rank-0 HTTP request gateway (docs/serving.md).

The front door of the serving plane: a loopback ``obs.httpd`` server
co-hosting the gateway routes AND the metrics route set (one HTTP
implementation, two route sets — the factoring the metrics endpoint and
this gateway share by construction):

* ``POST /v1/infer`` — one request = ONE example. JSON
  (``{"name": ..., "inputs": [...], "dtype": "float32"}``) or a raw
  tensor body (``application/octet-stream`` with ``X-Tensor-Name``,
  ``X-Tensor-Dtype``, ``X-Tensor-Shape: 4,8`` headers). The response
  mirrors the request's encoding; every response carries
  ``X-Serving-Epoch``.
* ``GET /v1/healthz`` — plane state (armed, epoch, queue depth, knobs).
* ``GET /v1/result?id=...`` — the journaled outcome of a request that
  carried an ``X-Request-Id`` header (docs/checkpoint.md): 200 with the
  stored outputs once done, 202 while pending (journaled, will be
  re-submitted when the plane re-arms), 404 for an unknown id.
* ``GET /metrics`` / ``/metrics.json`` — this (driver) process's
  registry, where every ``horovod_serving_*`` family lives.

Requests that opt in with ``X-Request-Id`` are journaled through the
checkpoint plane's :class:`~horovod_tpu.ckpt.store.TicketJournal`
(crash-durable with ``HOROVOD_CKPT_DIR``): a driver restart reloads the
journal and :meth:`_resume_journal` (wired to ``plane.on_armed``)
re-submits every still-pending envelope when the serving world arms, so
in-flight requests survive a restart instead of vanishing with it —
their clients poll ``/v1/result`` for the outcome.

Status contract (the SLO semantics table in docs/serving.md): 200 with
the output row; 400 malformed; 429 + ``Retry-After`` when admission's
queue-wait estimate exceeds the SLO budget; 503 + ``Retry-After`` with
the relaunch epoch in the body while no world is attached, when the
queue hits its hard cap, or when the deadline passes unanswered — the
gateway thread claims its own ticket at the deadline, so a request can
NEVER outwait its budget no matter what the world is doing.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Dict, Optional

import numpy as np

from ..obs.httpd import HttpError, HttpResponse
from ..obs.registry import registry as _metrics
from .plane import AdmissionError, healthz_doc

_REQUESTS = _metrics().counter(
    "horovod_serving_requests_total",
    "Gateway requests by final HTTP status code", labels=("code",))
_LATENCY = _metrics().histogram(
    "horovod_serving_latency_seconds",
    "Ticket-to-response latency of served (200) requests")


def _header(headers: Dict[str, str], name: str,
            default: Optional[str] = None) -> Optional[str]:
    for key, value in headers.items():
        if key.lower() == name.lower():
            return value
    return default


class Gateway:
    """HTTP front door bound to one :class:`ServingPlane`."""

    def __init__(self, plane, port: int = 0,
                 bind_host: str = "127.0.0.1") -> None:
        from ..obs.exposition import metrics_routes
        from ..obs.httpd import LoopbackHTTPD
        from ..obs.registry import registry

        self._plane = plane
        routes = {
            ("POST", "/v1/infer"): self._infer,
            ("GET", "/v1/healthz"): self._healthz,
            ("GET", "/v1/result"): self._result,
        }
        routes.update(metrics_routes(lambda: registry().snapshot()))
        self._httpd = LoopbackHTTPD("horovod-serving-gateway", port,
                                    routes, bind_host=bind_host)
        self.port = self._httpd.port
        # journal resume (docs/checkpoint.md): when the plane (re-)arms,
        # re-submit every still-pending journaled request
        plane.on_armed = self._resume_journal

    def close(self) -> None:
        self._httpd.close()

    # -- routes ---------------------------------------------------------------

    def _healthz(self, _query, _headers, _body):
        return HttpResponse(200, "application/json",
                            healthz_doc(self._plane))

    def _result(self, query, _headers, _body):
        """Journaled outcome lookup for X-Request-Id requests."""
        req_id = (query.get("id") or [None])[0]
        if not req_id:
            raise self._error(400, "GET /v1/result needs ?id=<request id>",
                              self._plane.current_epoch)
        entry = self._plane.journal.get(req_id)
        if entry is None:
            raise self._error(404, f"unknown request id {req_id!r}",
                              self._plane.current_epoch)
        state = entry.get("state")
        if state == "pending":
            body = json.dumps({"state": "pending", "id": req_id}).encode()
            return HttpResponse(202, "application/json", body)
        _REQUESTS.labels(code="200").inc()
        return HttpResponse(
            200, "application/json",
            json.dumps(dict(entry, id=req_id)).encode())

    def _error(self, status: int, message: str, epoch: int,
               retry_after_s: Optional[float] = None):
        headers = {"X-Serving-Epoch": str(epoch)}
        if retry_after_s is not None:
            headers["Retry-After"] = str(max(int(round(retry_after_s)), 1))
        body = json.dumps({"error": message, "epoch": epoch,
                           "retry_after_s": retry_after_s}).encode()
        _REQUESTS.labels(code=str(status)).inc()
        return HttpError(status, message, headers=headers,
                         content_type="application/json", body=body)

    def _parse(self, query, headers, body):
        """(name, example array, raw?) or raise 400."""
        ctype = (_header(headers, "Content-Type", "") or "").lower()
        try:
            if "octet-stream" in ctype:
                name = _header(headers, "X-Tensor-Name") or \
                    (query.get("name") or [None])[0]
                if not name:
                    raise ValueError("raw tensor body needs X-Tensor-Name "
                                     "(or ?name=)")
                dtype = np.dtype(_header(headers, "X-Tensor-Dtype",
                                         "float32"))
                shape_s = _header(headers, "X-Tensor-Shape", "")
                shape = tuple(int(d) for d in shape_s.split(",")
                              if d.strip() != "")
                array = np.frombuffer(body, dtype=dtype)
                if shape:
                    array = array.reshape(shape)
                return str(name), array, True
            doc = json.loads(body.decode() or "{}")
            name = doc.get("name")
            if not name or "inputs" not in doc:
                raise ValueError('JSON body needs "name" and "inputs"')
            array = np.asarray(doc["inputs"],
                               dtype=np.dtype(doc.get("dtype", "float32")))
            return str(name), array, False
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 - malformed input is a 400
            raise ValueError(f"malformed request body: {exc}") from exc

    def _infer(self, query, headers, body):
        plane = self._plane
        try:
            name, array, raw = self._parse(query, headers, body)
        except ValueError as exc:
            raise self._error(400, str(exc), plane.current_epoch)
        deadline_ms = _header(headers, "X-Serving-Deadline-Ms")
        try:
            deadline_s = (float(deadline_ms) / 1e3 if deadline_ms
                          else plane.default_deadline_s)
        except ValueError:
            # malformed input is the client's 400, not a 500
            raise self._error(400, f"malformed X-Serving-Deadline-Ms "
                                   f"{deadline_ms!r}",
                              plane.current_epoch)
        req_id = _header(headers, "X-Request-Id")
        if req_id:
            # journal the envelope BEFORE admission (docs/checkpoint.md):
            # a driver that dies anywhere past this line re-submits the
            # request when it restarts and the world re-arms; the client
            # polls GET /v1/result?id= for the outcome
            plane.journal.put(req_id, {
                "state": "pending", "name": name,
                "dtype": str(array.dtype),
                "shape": list(array.shape),
                "inputs_b64": base64.b64encode(
                    np.ascontiguousarray(array).tobytes()).decode(),
                "deadline_ms": deadline_s * 1e3,
            })
        try:
            ticket = plane.submit(name, array, deadline_s=deadline_s)
        except AdmissionError as exc:
            # a journaled envelope STAYS pending across an admission
            # reject: the re-arm resume is exactly for requests that
            # arrived while no world was attached
            raise self._error(exc.status, exc.message, exc.epoch,
                              exc.retry_after_s)
        # Wait out OUR deadline, then claim the ticket ourselves: the
        # never-a-hang guarantee lives in this thread, not in the world.
        ticket.wait(max(ticket.deadline - time.monotonic(), 0.0) + 0.05)
        if not ticket.closed:
            ticket.claim_timeout(epoch=plane.current_epoch)
        if ticket.state != "done":
            if req_id:
                plane.journal.put(req_id, {
                    "state": "failed", "status": ticket.status or 503,
                    "error": ticket.error or "request failed",
                    "epoch": ticket.epoch if ticket.epoch is not None
                    else plane.current_epoch,
                })
            raise self._error(ticket.status or 503,
                              ticket.error or "request failed",
                              ticket.epoch if ticket.epoch is not None
                              else plane.current_epoch,
                              ticket.retry_after_s)
        output = ticket.output
        if req_id:
            plane.journal.put(req_id, {
                "state": "done",
                "outputs": np.asarray(output).tolist(),
                "dtype": str(np.asarray(output).dtype),
                "epoch": plane.current_epoch,
            })
        latency = time.monotonic() - ticket.t0
        _REQUESTS.labels(code="200").inc()
        _LATENCY.observe(latency)
        epoch_headers = {"X-Serving-Epoch": str(plane.current_epoch)}
        if raw:
            out = np.ascontiguousarray(output)
            epoch_headers.update({
                "X-Tensor-Dtype": str(out.dtype),
                "X-Tensor-Shape": ",".join(str(d) for d in out.shape),
            })
            return HttpResponse(200, "application/octet-stream",
                                out.tobytes(), epoch_headers)
        return HttpResponse(
            200, "application/json",
            json.dumps({"outputs": np.asarray(output).tolist(),
                        "epoch": plane.current_epoch}).encode(),
            epoch_headers)

    # -- journal resume (docs/checkpoint.md) ----------------------------------

    def _resume_journal(self) -> None:
        """Re-submit every still-pending journaled request. Runs on the
        plane's ``on_armed`` hook (a daemon thread, never the RPC
        handler): after a driver restart or an elastic relaunch the
        in-flight requests a dead gateway thread was carrying complete
        here, and their clients find the outcome at ``/v1/result``."""
        plane = self._plane
        for req_id, entry in sorted(plane.journal.entries().items()):
            if entry.get("state") != "pending":
                continue
            try:
                array = np.frombuffer(
                    base64.b64decode(entry["inputs_b64"]),
                    dtype=np.dtype(entry["dtype"])).reshape(entry["shape"])
                ticket = plane.submit(
                    entry["name"], array,
                    deadline_s=float(entry.get("deadline_ms", 1e4)) / 1e3)
            except AdmissionError:
                return  # not armed after all / queue full: next re-arm
            except Exception as exc:  # noqa: BLE001 - corrupt envelope
                plane.journal.put(req_id, {
                    "state": "failed", "status": 400,
                    "error": f"journal envelope unusable: {exc}",
                    "epoch": plane.current_epoch})
                continue
            ticket.wait(max(ticket.deadline - time.monotonic(), 0.0) + 0.05)
            if not ticket.closed:
                ticket.claim_timeout(epoch=plane.current_epoch)
            if ticket.state == "done":
                out = np.asarray(ticket.output)
                plane.journal.put(req_id, {
                    "state": "done", "outputs": out.tolist(),
                    "dtype": str(out.dtype),
                    "epoch": plane.current_epoch})
            else:
                # leave it pending on a structural 503 (world went down
                # again mid-resume — the next re-arm retries); journal a
                # terminal failure otherwise
                if ticket.status == 503:
                    continue
                plane.journal.put(req_id, {
                    "state": "failed", "status": ticket.status or 500,
                    "error": ticket.error or "request failed",
                    "epoch": plane.current_epoch})
