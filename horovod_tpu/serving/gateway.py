"""Rank-0 HTTP request gateway (docs/serving.md).

The front door of the serving plane: a loopback ``obs.httpd`` server
co-hosting the gateway routes AND the metrics route set (one HTTP
implementation, two route sets — the factoring the metrics endpoint and
this gateway share by construction):

* ``POST /v1/infer`` — one request = ONE example. JSON
  (``{"name": ..., "inputs": [...], "dtype": "float32"}``) or a raw
  tensor body (``application/octet-stream`` with ``X-Tensor-Name``,
  ``X-Tensor-Dtype``, ``X-Tensor-Shape: 4,8`` headers). The response
  mirrors the request's encoding; every response carries
  ``X-Serving-Epoch``.
* ``GET /v1/healthz`` — plane state (armed, epoch, queue depth, knobs).
* ``GET /metrics`` / ``/metrics.json`` — this (driver) process's
  registry, where every ``horovod_serving_*`` family lives.

Status contract (the SLO semantics table in docs/serving.md): 200 with
the output row; 400 malformed; 429 + ``Retry-After`` when admission's
queue-wait estimate exceeds the SLO budget; 503 + ``Retry-After`` with
the relaunch epoch in the body while no world is attached, when the
queue hits its hard cap, or when the deadline passes unanswered — the
gateway thread claims its own ticket at the deadline, so a request can
NEVER outwait its budget no matter what the world is doing.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

import numpy as np

from ..obs.httpd import HttpError, HttpResponse
from ..obs.registry import registry as _metrics
from .plane import AdmissionError, healthz_doc

_REQUESTS = _metrics().counter(
    "horovod_serving_requests_total",
    "Gateway requests by final HTTP status code", labels=("code",))
_LATENCY = _metrics().histogram(
    "horovod_serving_latency_seconds",
    "Ticket-to-response latency of served (200) requests")


def _header(headers: Dict[str, str], name: str,
            default: Optional[str] = None) -> Optional[str]:
    for key, value in headers.items():
        if key.lower() == name.lower():
            return value
    return default


class Gateway:
    """HTTP front door bound to one :class:`ServingPlane`."""

    def __init__(self, plane, port: int = 0,
                 bind_host: str = "127.0.0.1") -> None:
        from ..obs.exposition import metrics_routes
        from ..obs.httpd import LoopbackHTTPD
        from ..obs.registry import registry

        self._plane = plane
        routes = {
            ("POST", "/v1/infer"): self._infer,
            ("GET", "/v1/healthz"): self._healthz,
        }
        routes.update(metrics_routes(lambda: registry().snapshot()))
        self._httpd = LoopbackHTTPD("horovod-serving-gateway", port,
                                    routes, bind_host=bind_host)
        self.port = self._httpd.port

    def close(self) -> None:
        self._httpd.close()

    # -- routes ---------------------------------------------------------------

    def _healthz(self, _query, _headers, _body):
        return HttpResponse(200, "application/json",
                            healthz_doc(self._plane))

    def _error(self, status: int, message: str, epoch: int,
               retry_after_s: Optional[float] = None):
        headers = {"X-Serving-Epoch": str(epoch)}
        if retry_after_s is not None:
            headers["Retry-After"] = str(max(int(round(retry_after_s)), 1))
        body = json.dumps({"error": message, "epoch": epoch,
                           "retry_after_s": retry_after_s}).encode()
        _REQUESTS.labels(code=str(status)).inc()
        return HttpError(status, message, headers=headers,
                         content_type="application/json", body=body)

    def _parse(self, query, headers, body):
        """(name, example array, raw?) or raise 400."""
        ctype = (_header(headers, "Content-Type", "") or "").lower()
        try:
            if "octet-stream" in ctype:
                name = _header(headers, "X-Tensor-Name") or \
                    (query.get("name") or [None])[0]
                if not name:
                    raise ValueError("raw tensor body needs X-Tensor-Name "
                                     "(or ?name=)")
                dtype = np.dtype(_header(headers, "X-Tensor-Dtype",
                                         "float32"))
                shape_s = _header(headers, "X-Tensor-Shape", "")
                shape = tuple(int(d) for d in shape_s.split(",")
                              if d.strip() != "")
                array = np.frombuffer(body, dtype=dtype)
                if shape:
                    array = array.reshape(shape)
                return str(name), array, True
            doc = json.loads(body.decode() or "{}")
            name = doc.get("name")
            if not name or "inputs" not in doc:
                raise ValueError('JSON body needs "name" and "inputs"')
            array = np.asarray(doc["inputs"],
                               dtype=np.dtype(doc.get("dtype", "float32")))
            return str(name), array, False
        except ValueError:
            raise
        except Exception as exc:  # noqa: BLE001 - malformed input is a 400
            raise ValueError(f"malformed request body: {exc}") from exc

    def _infer(self, query, headers, body):
        plane = self._plane
        try:
            name, array, raw = self._parse(query, headers, body)
        except ValueError as exc:
            raise self._error(400, str(exc), plane.current_epoch)
        deadline_ms = _header(headers, "X-Serving-Deadline-Ms")
        try:
            deadline_s = (float(deadline_ms) / 1e3 if deadline_ms
                          else plane.default_deadline_s)
        except ValueError:
            # malformed input is the client's 400, not a 500
            raise self._error(400, f"malformed X-Serving-Deadline-Ms "
                                   f"{deadline_ms!r}",
                              plane.current_epoch)
        try:
            ticket = plane.submit(name, array, deadline_s=deadline_s)
        except AdmissionError as exc:
            raise self._error(exc.status, exc.message, exc.epoch,
                              exc.retry_after_s)
        # Wait out OUR deadline, then claim the ticket ourselves: the
        # never-a-hang guarantee lives in this thread, not in the world.
        ticket.wait(max(ticket.deadline - time.monotonic(), 0.0) + 0.05)
        if not ticket.closed:
            ticket.claim_timeout(epoch=plane.current_epoch)
        if ticket.state != "done":
            raise self._error(ticket.status or 503,
                              ticket.error or "request failed",
                              ticket.epoch if ticket.epoch is not None
                              else plane.current_epoch,
                              ticket.retry_after_s)
        output = ticket.output
        latency = time.monotonic() - ticket.t0
        _REQUESTS.labels(code="200").inc()
        _LATENCY.observe(latency)
        epoch_headers = {"X-Serving-Epoch": str(plane.current_epoch)}
        if raw:
            out = np.ascontiguousarray(output)
            epoch_headers.update({
                "X-Tensor-Dtype": str(out.dtype),
                "X-Tensor-Shape": ",".join(str(d) for d in out.shape),
            })
            return HttpResponse(200, "application/octet-stream",
                                out.tobytes(), epoch_headers)
        return HttpResponse(
            200, "application/json",
            json.dumps({"outputs": np.asarray(output).tolist(),
                        "epoch": plane.current_epoch}).encode(),
            epoch_headers)
