"""Multi-tenant inference serving plane (docs/serving.md).

The repo's first post-training workload: a rank-0 request **gateway**
(stdlib HTTP, the ``obs.httpd`` machinery the metrics endpoint shares)
feeding a **continuous micro-batcher** whose packed batches broadcast to
every rank of an SPMD world over the authenticated control wire — with
deadline-aware admission (429/503 + ``Retry-After``), end-to-end
instrumentation on the obs registry (``horovod_serving_*``), the batcher
knobs on the autotune ladder, and elastic failover wired through the
PR-2 driver (``run_elastic(serving_plane=...)``).

Pieces:

* :mod:`.batcher` — tickets, padding buckets (PR-3 identity convention),
  continuous FIFO packing;
* :mod:`.plane` — the driver-resident coordinator: dispatch broadcast,
  result rendezvous with cross-rank digest verification, epochs,
  admission;
* :mod:`.gateway` — the HTTP front door (co-hosting ``/metrics``);
* :mod:`.worker` — the rank-side loop: pull, run the pre-compiled
  forward step, report.

Stdlib + numpy at module level (jax only inside ``serve_worker`` when
``jit=True``): importable in driver and tooling processes.
"""

from __future__ import annotations

from .batcher import (  # noqa: F401 - public surface
    MicroBatcher,
    Ticket,
    bucket_key,
    derive_edges,
    pad_to_edge,
)
from .plane import AdmissionError, ServingPlane  # noqa: F401
from .worker import (  # noqa: F401
    ServingAbortedError,
    parse_serving_fault,
    serve_worker,
)
