"""Driver-resident serving coordinator: dispatch, epochs, admission.

The :class:`ServingPlane` is the rank-0-side half of the serving plane
(docs/serving.md). It lives in the DRIVER process — the same process
that runs ``run_elastic`` — so it survives world relaunches: the HTTP
gateway stays up and answers structured 503s (carrying the relaunch
epoch) while a failed world is being rebuilt, instead of presenting
clients with a vanished port.

Pieces, all on the existing control-plane machinery:

* a ``BasicService`` (the authenticated HMAC wire every other plane
  rides) serving the worker RPCs — ``shello`` / ``infer`` / ``result``;
* the :class:`~horovod_tpu.serving.batcher.MicroBatcher` feeding it;
* the :class:`~horovod_tpu.serving.gateway.Gateway` HTTP front door
  (built on ``obs.httpd``, co-hosting the metrics route set).

Dispatch is a broadcast: the first rank to ask for ordinal ``k`` cuts
the batch and the resulting ``("batch", k, bucket, n_real, payload)``
frame is stored and served VERBATIM to every rank (framed once — the
``Preserialized`` idiom), so all ranks execute the identical packed
batch. Completion is a rendezvous: every rank reports the batch digest
(rank 0 also the output payload); tickets only complete when the
digests agree, and a divergence escalates instead of serving silently
wrong bytes (the PR-8 integrity bar).

Failure contract (the PR-2 interplay): ``run_elastic(serving_plane=...)``
calls :meth:`begin_epoch` before every attempt and :meth:`world_down`
when one fails. ``world_down`` drains every in-flight ticket — requeue
when the deadline still allows a post-relaunch retry (forward steps are
stateless, so re-dispatch cannot double-apply), structured 503
otherwise — and never leaves a ticket hanging: a ticket the plane
forgets is still bounded by its gateway thread's own deadline claim.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import config as _config
from ..core.config import _env_bool, _env_float, _env_int
from ..obs.registry import registry as _metrics
from ..runner.network import BasicService, Preserialized, make_secret
from .batcher import MicroBatcher, Ticket, bucket_key

_EPOCH_GAUGE = _metrics().gauge(
    "horovod_serving_relaunch_epoch",
    "Elastic epoch the serving plane currently targets (bumped by every "
    "relaunch; 503s during a relaunch carry it)")
_ARMS = _metrics().counter(
    "horovod_serving_rearms_total",
    "Times the plane (re-)armed: every member rank of the current epoch "
    "checked in and dispatch (re-)opened")
_WORLD_DOWNS = _metrics().counter(
    "horovod_serving_world_downs_total",
    "Times the serving world went down under the plane (rank death, "
    "elastic relaunch, digest divergence)")
_MISMATCHES = _metrics().counter(
    "horovod_serving_digest_mismatches_total",
    "Result rendezvous where per-rank output digests diverged (the batch "
    "failed structurally and the world was torn down, never served)")
_DISPATCHED = _metrics().counter(
    "horovod_serving_dispatched_batches_total",
    "Batches broadcast to the serving world")
_SWAPS = _metrics().counter(
    "horovod_serving_weight_swaps_total",
    "Weight hot-swaps published to the serving world "
    "(docs/checkpoint.md: delivered between micro-batches, digest-"
    "verified by every rank, acked before the next batch cuts — old-or-"
    "new atomically, never torn)")

# A requeued ticket needs this much deadline headroom to be worth
# re-dispatching after a relaunch; anything tighter fails 503 at drain.
_REQUEUE_MARGIN_S = 0.25

# A result rendezvous that outlives this bound with the world still
# nominally up is a wedge: fail structurally instead of parking forever
# (the never-a-hang bar; world_down unparks the normal failure paths).
_RESULT_RENDEZVOUS_TIMEOUT_S = 120.0


class AdmissionError(Exception):
    """Structured admission reject: the gateway renders it as the HTTP
    status + ``Retry-After`` + JSON body (429 = queue past the SLO
    budget; 503 = no world / queue hard cap / shutting down)."""

    def __init__(self, status: int, message: str, epoch: int,
                 retry_after_s: float) -> None:
        super().__init__(message)
        self.status = int(status)
        self.message = message
        self.epoch = int(epoch)
        self.retry_after_s = float(retry_after_s)


class _Inflight:
    """One broadcast batch awaiting its result rendezvous."""

    __slots__ = ("key", "tickets", "n_real", "t_cut", "votes", "payload",
                 "errors", "event", "fail_msg")

    def __init__(self, key: Tuple, tickets: List[Ticket],
                 n_real: int) -> None:
        self.key = key
        self.tickets = tickets
        self.n_real = n_real
        self.t_cut = time.monotonic()
        self.votes: Dict[int, Optional[str]] = {}
        self.payload: Optional[np.ndarray] = None
        self.errors: Dict[int, str] = {}
        self.event = threading.Event()
        self.fail_msg: Optional[str] = None


class ServingPlane:
    """Rank-0 request gateway + continuous micro-batching coordinator.

    Construct in the driver process, pass to
    ``run_elastic(serving_plane=...)`` (or export :meth:`env` into a
    plain ``runner.run`` world), point clients at
    ``http://127.0.0.1:{gateway_port}/v1/infer``, and :meth:`close` when
    done. Constructor arguments win over their ``HOROVOD_SERVING_*``
    environment defaults."""

    def __init__(self, gateway_port: Optional[int] = 0,
                 service_port: int = 0,
                 secret: Optional[str] = None,
                 batch_max: Optional[int] = None,
                 bucket_edges: Optional[Tuple[int, ...]] = None,
                 edge_ratio: Optional[float] = None,
                 queue_max: Optional[int] = None,
                 slo_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None,
                 autotune: Optional[bool] = None,
                 reconnect_window_s: Optional[float] = None,
                 world_id: str = "") -> None:
        import os

        if batch_max is None:
            batch_max = _env_int(_config.HOROVOD_SERVING_BATCH_MAX, 8)
        batch_max_explicit = bool(
            os.environ.get(_config.HOROVOD_SERVING_BATCH_MAX))
        if bucket_edges is None:
            raw = os.environ.get(_config.HOROVOD_SERVING_BUCKET_EDGES, "")
            bucket_edges = tuple(
                int(e) for e in raw.split(",") if e.strip()) or None
        edges_explicit = bucket_edges is not None
        if edge_ratio is None:
            edge_ratio = _env_float(_config.HOROVOD_SERVING_EDGE_RATIO, 2.0)
        self._queue_max = queue_max if queue_max is not None else \
            _env_int(_config.HOROVOD_SERVING_QUEUE_MAX, 256)
        self._slo_s = (slo_ms if slo_ms is not None else
                       _env_float(_config.HOROVOD_SERVING_SLO_MS,
                                  2000.0)) / 1e3
        self.default_deadline_s = (
            deadline_ms if deadline_ms is not None else
            _env_float(_config.HOROVOD_SERVING_DEADLINE_MS, 10000.0)) / 1e3
        if autotune is None:
            autotune = _env_bool(_config.HOROVOD_SERVING_AUTOTUNE)
        if reconnect_window_s is None:
            reconnect_window_s = _env_float(
                _config.HOROVOD_RECONNECT_WINDOW, 5.0)
        self._window_s = max(float(reconnect_window_s), 0.0)
        self._world_id = world_id

        self._batcher = MicroBatcher(batch_max=max(int(batch_max), 1),
                                     edges=bucket_edges,
                                     edge_ratio=float(edge_ratio))
        self._cond = threading.Condition()
        self._epoch = 0
        self._world: Optional[int] = None
        self._hellos: set = set()
        self._armed = False
        self._down_reason: Optional[str] = None
        self._stopping = False
        self._dispatch: Dict[int, bytes] = {}
        self._inflight: Dict[int, _Inflight] = {}
        self._next_ordinal = 0
        self._cutting = False
        self._conn_ranks: Dict[int, int] = {}
        self._rank_conns: Dict[int, int] = {}
        self._pending_reconnect: Dict[int, float] = {}
        self._ema_batch_s: Optional[float] = None
        self._dispatched_total = 0
        self._max_batch_real = 0
        # weight hot swap (docs/checkpoint.md): the pending swap frame
        # (version, digest, framed bytes), the ranks that applied+acked
        # it, and the last fully-applied version
        self._swap: Optional[Tuple[int, str, bytes]] = None
        self._swap_acks: set = set()
        self._weights_version: Optional[int] = None
        # fires (daemon thread) each time the plane (re-)arms — the
        # gateway resumes its journaled in-flight requests here
        self.on_armed = None
        # crash-durable in-flight request journal (docs/checkpoint.md);
        # in-memory unless HOROVOD_CKPT_DIR is set. Own filename: the
        # elastic seal ledger's wire-backed journal may share the dir.
        from ..ckpt.store import TicketJournal

        self.journal = TicketJournal(
            dir=os.environ.get(_config.HOROVOD_CKPT_DIR) or None,
            filename="tickets.json")

        self._policy = None
        if autotune:
            from ..tune.policy import TuningPolicy, serving_knobs

            self._policy = TuningPolicy(
                serving_knobs(self._batcher.batch_max, float(edge_ratio),
                              batch_max_explicit=batch_max_explicit,
                              edges_explicit=edges_explicit),
                window=5, cooldown=2)

        self._secret_hex = secret or make_secret()
        self._service = BasicService(
            "horovod-serving", self._handle,
            secret=bytes.fromhex(self._secret_hex), port=service_port,
            on_disconnect=self._on_disconnect)
        self.service_port = self._service.port
        self._gateway = None
        if gateway_port is not None:
            from .gateway import Gateway

            self._gateway = Gateway(self, port=gateway_port)

    # -- public surface -------------------------------------------------------

    @property
    def gateway_port(self) -> Optional[int]:
        return self._gateway.port if self._gateway is not None else None

    @property
    def secret(self) -> bytes:
        return bytes.fromhex(self._secret_hex)

    def env(self) -> Dict[str, str]:
        """The worker-side environment block: merged into every elastic
        attempt by ``run_elastic(serving_plane=...)`` (the secret rides
        the env exactly like the launcher's HOROVOD_SECRET_KEY)."""
        return {
            _config.HOROVOD_SERVING_ADDR: "127.0.0.1",
            _config.HOROVOD_SERVING_PORT: str(self.service_port),
            _config.HOROVOD_SERVING_SECRET: self._secret_hex,
            _config.HOROVOD_SERVING_BATCH_MAX:
                str(self._batcher.batch_max),
            # the EFFECTIVE padding edges, so worker warmup pre-compiles
            # the shapes live batches will actually present (a warmed
            # default ladder under non-default edges would pay the
            # compile on the first live request — the exact SLO hit
            # warmup exists to prevent)
            _config.HOROVOD_SERVING_BUCKET_EDGES:
                ",".join(str(e) for e in self._batcher.edges()),
        }

    def stats(self) -> dict:
        with self._cond:
            return {
                "armed": self._armed,
                "epoch": self._epoch,
                "world": self._world,
                "queue_depth": self._batcher.depth,
                "inflight": len(self._inflight),
                "dispatched_total": self._dispatched_total,
                "max_batch_real": self._max_batch_real,
                "batch_max": self._batcher.batch_max,
                "edges": list(self._batcher.edges()),
                "ema_batch_s": self._ema_batch_s,
                "stopping": self._stopping,
                "down_reason": self._down_reason,
                "weights_version": self._weights_version,
                "swap_pending": self._swap[0] if self._swap is not None
                                else None,
            }

    def config_snapshot(self) -> dict:
        """Live knob values (the gateway's /v1/healthz and tests)."""
        return {"serving_batch_max": self._batcher.batch_max,
                "serving_bucket_edges": list(self._batcher.edges()),
                "queue_max": self._queue_max,
                "slo_ms": self._slo_s * 1e3}

    def set_batch_max(self, n: int) -> None:
        self._batcher.set_batch_max(n)

    @property
    def current_epoch(self) -> int:
        return self._epoch

    @property
    def weights_version(self) -> Optional[int]:
        return self._weights_version

    def publish_weights(self, version: int, tree=None,
                        payload: Optional[bytes] = None) -> None:
        """Hot-swap the serving world to new weights between micro-batches
        (docs/checkpoint.md swap atomicity). The frame is delivered to
        each rank the next time it asks for a batch and no batch is
        already dispatched for its ordinal; every rank digest-verifies
        the payload, applies it, and acks — and the batch cut gate stays
        closed until ALL ranks acked, so every dispatched batch runs
        entirely on old or entirely on new weights, never torn. Requests
        in flight across the swap observe one or the other atomically;
        none are dropped. The natural caller is ``run_elastic``'s
        ``on_seal`` hook: publish each freshly sealed (= world-verified)
        checkpoint."""
        import pickle

        from ..integrity.consensus import digest_bytes

        if payload is None:
            payload = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
        digest = digest_bytes(payload)
        frame = self._service.wire.frame(
            ("swap", int(version), digest, payload))
        with self._cond:
            self._swap = (int(version), digest, frame)
            self._swap_acks = set()
            self._cond.notify_all()
        _SWAPS.inc()
        from ..obs import flightrec as _flightrec

        _flightrec.record(_flightrec.EV_SERVING_SWAP, int(version),
                          aux=len(payload))

    # -- admission (the gateway's entry point) --------------------------------

    def submit(self, name: str, array: np.ndarray,
               deadline_s: Optional[float] = None) -> Ticket:
        """Admit one request or raise :class:`AdmissionError`.

        Deadline-aware: 503 when no world is attached (carrying the
        relaunch epoch) or the queue hit its hard cap; 429 + Retry-After
        when the estimated queue wait exceeds the SLO budget — shedding
        at the door beats admitting work that will only time out."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        with self._cond:
            epoch = self._epoch
            if self._stopping:
                raise AdmissionError(503, "serving plane shutting down",
                                     epoch, 1.0)
            if not self._armed:
                reason = self._down_reason or "no serving world attached"
                raise AdmissionError(
                    503, f"serving world relaunching ({reason})", epoch,
                    1.0)
            depth = self._batcher.depth
            if depth >= self._queue_max:
                raise AdmissionError(
                    503, f"queue full ({depth} >= {self._queue_max})",
                    epoch, max(self._est_wait_s(depth), 0.5))
            est = self._est_wait_s(depth)
            if est > self._slo_s:
                raise AdmissionError(
                    429, f"estimated queue wait {est * 1e3:.0f}ms exceeds "
                         f"the {self._slo_s * 1e3:.0f}ms SLO budget",
                    epoch, est)
            ticket = Ticket(bucket_key(name, array.dtype, array.shape),
                            array, deadline_s)
            self._batcher.enqueue(ticket)
            return ticket

    def _est_wait_s(self, depth: int) -> float:
        """Estimated QUEUE wait (batches ahead x EMA batch time) — the
        request's own service time is excluded, so an empty queue always
        admits and only backlog trips the SLO budget."""
        per_batch = self._ema_batch_s if self._ema_batch_s is not None \
            else 0.05
        return (depth / max(self._batcher.batch_max, 1)) * per_batch

    # -- elastic interplay (docs/serving.md failover matrix) ------------------

    def begin_epoch(self, epoch: int, world: int) -> None:
        """Target a (re)launched world: called by ``run_elastic`` before
        every attempt. Supersedes any half-torn-down predecessor state,
        then waits for all ``world`` ranks' shellos to re-arm."""
        with self._cond:
            if self._armed or self._inflight or self._dispatch:
                self._world_down_locked(f"superseded by epoch {epoch}")
            self._epoch = int(epoch)
            self._world = int(world)
            self._hellos.clear()
            self._down_reason = None
            _EPOCH_GAUGE.set(self._epoch)
            self._cond.notify_all()

    def world_down(self, reason: str) -> None:
        """The current world failed (elastic attempt error, rank death).
        Idempotent; drains or structurally errors every in-flight
        ticket — never a hang."""
        with self._cond:
            self._world_down_locked(reason)

    def _world_down_locked(self, reason: str) -> None:
        if not self._armed and not self._inflight and not self._dispatch \
                and self._down_reason is not None:
            return  # already down with a recorded reason (idempotent)
        _WORLD_DOWNS.inc()
        self._armed = False
        self._down_reason = reason
        self._hellos.clear()
        self._conn_ranks.clear()
        self._rank_conns.clear()
        self._pending_reconnect.clear()
        # a dead world's swap acks are void, but the PENDING swap frame
        # survives: the relaunched world receives it before its first
        # batch, so a relaunch can never resurrect stale weights
        self._swap_acks = set()
        epoch = self._epoch
        now = time.monotonic()
        requeue: List[Ticket] = []
        for inf in self._inflight.values():
            for ticket in inf.tickets:
                if ticket.closed:
                    continue
                if ticket.deadline - now > _REQUEUE_MARGIN_S:
                    requeue.append(ticket)
                else:
                    ticket.fail(503, f"serving world relaunching "
                                     f"({reason})", epoch=epoch,
                                retry_after_s=1.0)
            if inf.fail_msg is None:
                inf.fail_msg = (f"serving epoch {epoch} torn down: "
                                f"{reason}")
            inf.event.set()
        self._inflight.clear()
        self._dispatch.clear()
        self._next_ordinal = 0
        requeue.sort(key=lambda t: t.t0)
        self._batcher.requeue(requeue)
        self._cond.notify_all()

    def stop(self) -> None:
        """Clean shutdown: parked ``infer`` handlers answer ``stop`` (so
        worker loops return their stats), queued tickets fail 503."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        for ticket in self._batcher.drain():
            ticket.fail(503, "serving plane shutting down",
                        epoch=self._epoch, retry_after_s=None)

    def close(self) -> None:
        self.stop()
        self._service.shutdown()
        if self._gateway is not None:
            self._gateway.close()

    # -- worker RPC handlers --------------------------------------------------

    def _handle(self, req, sock):
        kind = req[0]
        if kind == "shello":
            return self._shello(req, sock)
        if kind == "infer":
            _, rank, epoch, ordinal = req
            return self._infer(rank, int(epoch), int(ordinal))
        if kind == "result":
            _, rank, epoch, ordinal, digest, payload, error = req
            return self._result(int(rank), int(epoch), int(ordinal),
                                digest, payload, error)
        if kind == "swap_ack":
            _, rank, epoch, version = req
            return self._swap_ack(int(rank), int(epoch), int(version))
        raise ValueError(f"unknown serving request {kind!r}")

    def _swap_ack(self, rank: int, epoch: int, version: int):
        """A rank digest-verified and applied the published weights; the
        batch cut gate reopens when the whole world acked."""
        with self._cond:
            self._check_epoch_locked(epoch)
            if self._swap is None or version != self._swap[0]:
                return ("ok",)  # superseded swap: the ack is history
            self._swap_acks.add(rank)
            if self._world is not None and \
                    len(self._swap_acks) >= self._world:
                self._weights_version = version
                self._swap = None
                self._swap_acks = set()
                self._cond.notify_all()
            return ("ok",)

    def _shello(self, req, sock):
        _, rank, size, epoch, world_id = req
        if world_id and self._world_id and world_id != self._world_id:
            raise RuntimeError(
                f"serving world mismatch: plane serves "
                f"{self._world_id!r}, hello came from {world_id!r}")
        with self._cond:
            if self._stopping:
                raise RuntimeError("serving plane shutting down")
            if self._world is None:
                # adopt-from-first-hello: plain runner.run worlds with no
                # elastic driver calling begin_epoch
                self._epoch, self._world = int(epoch), int(size)
                _EPOCH_GAUGE.set(self._epoch)
            if int(epoch) != self._epoch:
                raise RuntimeError(
                    f"stale serving epoch {epoch} (current {self._epoch}; "
                    f"a pre-relaunch zombie worker must not rejoin)")
            if int(size) != self._world:
                raise RuntimeError(
                    f"serving world size mismatch: expected {self._world}, "
                    f"rank {rank} announced {size}")
            # supersede: a reconnecting rank's new connection takes over
            # (de-identify the old one so its close is not a rank death)
            old = self._rank_conns.get(rank)
            if old is not None and old != id(sock):
                self._conn_ranks.pop(old, None)
            self._rank_conns[rank] = id(sock)
            self._conn_ranks[id(sock)] = rank
            self._pending_reconnect.pop(rank, None)
            self._hellos.add(int(rank))
            armed_now = False
            if len(self._hellos) == self._world and not self._armed:
                self._armed = True
                self._down_reason = None
                _ARMS.inc()
                armed_now = True
                self._cond.notify_all()
        if armed_now and self.on_armed is not None:
            # outside the lock and off the RPC handler thread: the hook
            # (gateway journal resume) re-enters submit()
            threading.Thread(target=self.on_armed,
                             name="serving-on-armed", daemon=True).start()
        return ("ok", self._epoch)

    def _check_epoch_locked(self, epoch: int) -> None:
        if epoch != self._epoch or self._down_reason is not None:
            raise RuntimeError(
                f"serving epoch {epoch} torn down "
                f"({self._down_reason or f'current epoch is {self._epoch}'})")

    def _infer(self, rank: int, epoch: int, ordinal: int):
        """Park until batch ``ordinal`` exists; the first rank to reach a
        new ordinal cuts it (continuously: as soon as any ticket is
        queued). Every rank receives the stored frame verbatim."""
        with self._cond:
            while True:
                # an already-dispatched frame is served even while
                # stopping: every rank must fetch and vote on an
                # in-flight batch or the result rendezvous strands its
                # peers (completing in-flight work IS the clean drain —
                # only the NEXT ordinal answers "stop")
                # already-dispatched frames FIRST, before any pending
                # swap: every rank must run batch k with the weights it
                # was cut under before applying new ones, or the result
                # digests would tear (docs/checkpoint.md swap atomicity)
                frame = self._dispatch.get(ordinal)
                if frame is not None:
                    return Preserialized(frame)
                if self._stopping:
                    return ("stop",)
                self._check_epoch_locked(epoch)
                if self._swap is not None and rank not in self._swap_acks:
                    # deliver the pending weights at the batch boundary;
                    # the worker applies, acks, and re-requests ordinal
                    return Preserialized(self._swap[2])
                if not self._cutting and self._swap is None and \
                        ordinal == self._next_ordinal:
                    # the cut gate stays closed while a swap is pending:
                    # a batch cut mid-swap could mix old- and new-weight
                    # ranks in one rendezvous
                    self._cutting = True
                    break
                self._cond.wait(timeout=0.2)
        try:
            result = self._cut(epoch, ordinal)
        finally:
            with self._cond:
                self._cutting = False
                self._cond.notify_all()
        if result is None:
            # a swap landed while the batch was being cut: the tickets
            # went back to the queue — park again and deliver the swap
            return self._infer(rank, epoch, ordinal)
        return result

    def _cut(self, epoch: int, ordinal: int):
        while True:
            with self._cond:
                if self._stopping:
                    return ("stop",)
                self._check_epoch_locked(epoch)
            got = self._batcher.next_batch(timeout_s=0.2)
            if got is None:
                continue
            key, tickets, padded = got
            batch = self._batcher.pack(tickets, padded)
            frame = self._service.wire.frame(
                ("batch", ordinal, key, len(tickets), batch))
            with self._cond:
                if self._stopping or epoch != self._epoch or \
                        self._down_reason is not None:
                    # the world state moved between cut and registration:
                    # these tickets belong to nobody — resolve them here
                    for ticket in tickets:
                        ticket.fail(503, "serving world relaunching "
                                         "(batch dropped at cut)",
                                    epoch=self._epoch, retry_after_s=1.0)
                    if self._stopping:
                        return ("stop",)
                    self._check_epoch_locked(epoch)
                if self._swap is not None:
                    # a weight swap was published between the cut and
                    # registration: dispatching this batch would race the
                    # swap delivery across ranks (torn batch). Requeue
                    # the not-yet-dispatched tickets and let the callers
                    # re-park; the swap drains first, then a fresh cut.
                    self._batcher.requeue(
                        sorted(tickets, key=lambda t: t.t0))
                    return None
                for ticket in tickets:
                    ticket.mark_dispatched()
                self._dispatch[ordinal] = frame
                self._inflight[ordinal] = _Inflight(key, tickets,
                                                    len(tickets))
                self._next_ordinal += 1
                self._dispatched_total += 1
                self._max_batch_real = max(self._max_batch_real,
                                           len(tickets))
                _DISPATCHED.inc()
                self._cond.notify_all()
            # flight recorder (docs/blackbox.md): driver-side dispatch
            # with the batch ordinal the workers' receipts align to
            from ..obs import flightrec as _flightrec

            _flightrec.record(_flightrec.EV_SERVING_DISPATCH, ordinal,
                              aux=len(tickets))
            return Preserialized(frame)

    def _result(self, rank: int, epoch: int, ordinal: int,
                digest: Optional[str], payload, error: Optional[str]):
        with self._cond:
            self._check_epoch_locked(epoch)
            inf = self._inflight.get(ordinal)
            if inf is None:
                raise RuntimeError(
                    f"no in-flight batch {ordinal} in epoch {epoch}")
            if error:
                inf.errors[rank] = error
            inf.votes[rank] = digest
            if payload is not None:
                inf.payload = payload
            if len(inf.votes) == self._world:
                self._finalize_locked(ordinal, inf)
        if not inf.event.wait(timeout=_RESULT_RENDEZVOUS_TIMEOUT_S):
            raise RuntimeError(
                f"result rendezvous for batch {ordinal} timed out after "
                f"{_RESULT_RENDEZVOUS_TIMEOUT_S:.0f}s — a rank never "
                f"reported and the world was not torn down")
        if inf.fail_msg is not None:
            raise RuntimeError(inf.fail_msg)
        return ("ok",)

    def _finalize_locked(self, ordinal: int, inf: _Inflight) -> None:
        """All votes in (caller holds the lock): verify, complete, learn."""
        del self._inflight[ordinal]
        self._dispatch.pop(ordinal, None)
        epoch = self._epoch
        if inf.errors:
            detail = "; ".join(f"rank {r}: {m}"
                               for r, m in sorted(inf.errors.items()))
            for ticket in inf.tickets:
                ticket.fail(500, f"forward step failed ({detail})",
                            epoch=epoch)
            inf.event.set()
            return
        if len(set(inf.votes.values())) != 1:
            _MISMATCHES.inc()
            detail = ", ".join(
                f"rank {r}: {str(d)[:12]}"
                for r, d in sorted(inf.votes.items()))
            msg = (f"batch {ordinal} output digests diverged across ranks "
                   f"({detail}) — refusing to serve silently wrong bytes")
            for ticket in inf.tickets:
                ticket.fail(500, msg, epoch=epoch)
            inf.fail_msg = msg  # workers raise -> world fault -> relaunch
            self._world_down_locked(msg)
            inf.event.set()
            return
        if inf.payload is None:
            msg = f"batch {ordinal}: no rank shipped the output payload"
            for ticket in inf.tickets:
                ticket.fail(500, msg, epoch=epoch)
            inf.fail_msg = msg
            inf.event.set()
            return
        payload = np.asarray(inf.payload)
        for i, ticket in enumerate(inf.tickets):
            ticket.complete(payload[i])
        dt = max(time.monotonic() - inf.t_cut, 1e-6)
        self._ema_batch_s = dt if self._ema_batch_s is None else \
            0.8 * self._ema_batch_s + 0.2 * dt
        inf.event.set()
        if self._policy is not None:
            batch_bytes = payload.nbytes * inf.n_real / max(
                payload.shape[0], 1)
            decision = self._policy.observe(float(batch_bytes), dt * 1e6)
            if decision is not None:
                self._apply_decision(decision)

    def _apply_decision(self, decision) -> None:
        from ..tune.policy import KNOB_SERVING_BATCH, KNOB_SERVING_EDGES

        self._batcher.set_batch_max(
            int(decision.config[KNOB_SERVING_BATCH]))
        self._batcher.set_edge_ratio(
            float(decision.config[KNOB_SERVING_EDGES]))

    # -- failure detection ----------------------------------------------------

    def _on_disconnect(self, sock) -> None:
        """A rank-bound serving connection dropped: give it the reconnect
        window to supersede (a chaos-healed client redials within it),
        then declare the world down — the same grace the controller
        gives its cycle connections."""
        with self._cond:
            rank = self._conn_ranks.pop(id(sock), None)
            if rank is not None and self._rank_conns.get(rank) == id(sock):
                del self._rank_conns[rank]
            if rank is None or self._stopping or not self._armed:
                return
            deadline = time.monotonic() + self._window_s
            self._pending_reconnect[rank] = deadline
        timer = threading.Timer(self._window_s + 0.05,
                                self._reconnect_deadline,
                                args=(rank, deadline))
        timer.daemon = True
        timer.start()

    def _reconnect_deadline(self, rank: int, deadline: float) -> None:
        with self._cond:
            if self._pending_reconnect.get(rank) != deadline:
                return  # superseded or healed
            del self._pending_reconnect[rank]
            if self._stopping or rank in self._rank_conns:
                return
            self._world_down_locked(
                f"rank {rank} serving connection lost past the "
                f"{self._window_s:.1f}s reconnect window")


def healthz_doc(plane: ServingPlane) -> bytes:
    """The gateway's /v1/healthz body (here so tooling can reuse it)."""
    doc = dict(plane.stats())
    doc.update(plane.config_snapshot())
    return json.dumps(doc).encode()
