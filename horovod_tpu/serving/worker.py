"""Rank-side serving loop: pull packed batches, run the forward step,
report results.

Every member rank of a serving world runs :func:`serve_worker` with the
same ``{name: forward_fn}`` model table. The loop dials the driver's
:class:`~horovod_tpu.serving.plane.ServingPlane` coordinator on its OWN
authenticated connection — the PR-9 second-connection pattern: serving
traffic never holds (or parks behind) the training cycle channel's
request lock, so a world that trains and serves at once keeps both
planes independent. The wire is the standard self-healing control plane
(``BasicClient`` reconnect + request dedup), so a dropped batch or
result frame heals transparently and a replay can never re-invoke a
dispatch.

Protocol (all under the ``#rpc`` dedup envelope; docs/serving.md):

* ``("shello", rank, size, epoch, world_id)`` — identify; refused when
  the epoch is stale (a zombie worker of a pre-relaunch world).
* ``("infer", rank, epoch, ordinal)`` — parks until batch ``ordinal``
  exists, then every rank receives the IDENTICAL
  ``("batch", ordinal, bucket, n_real, payload)`` broadcast (framed once
  coordinator-side, the ``Preserialized`` idiom).
* ``("result", rank, epoch, ordinal, digest, payload, error)`` — the
  result rendezvous: every rank ships the batch digest (rank 0 also the
  output payload); the coordinator verifies the digests agree before any
  ticket completes — replicated dispatch is only worth broadcasting if
  divergence is caught, not averaged away.
* an ``infer`` may also answer ``("swap", version, digest, payload)`` —
  a weight hot-swap delivered at the batch boundary (docs/checkpoint.md):
  the rank verifies the payload digest, hands the unpickled tree to the
  caller's ``on_weights`` hook, drops its compiled steps (the next batch
  retraces against the new weights), acks with
  ``("swap_ack", rank, epoch, version)`` and re-requests the SAME
  ordinal. The coordinator's cut gate stays closed until every rank
  acked, so no batch ever mixes old- and new-weight ranks.

The forward step is pre-compiled per padding bucket: with ``jit=True``
each ``(name, batch_shape, dtype)`` compiles once (``jax.jit``) and
every later batch in that bucket replays the compiled step — the reason
the batcher pads to a bounded edge set at all.
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..core import config as _config
from ..core.status import HorovodInternalError
from ..obs.registry import registry as _metrics

_WORKER_BATCHES = _metrics().counter(
    "horovod_serving_worker_batches_total",
    "Packed batches this rank's serving loop executed")
_WORKER_COMPILES = _metrics().counter(
    "horovod_serving_worker_compiles_total",
    "Distinct (model, bucket) forward steps this rank compiled")


class ServingAbortedError(HorovodInternalError):
    """The serving world failed under this rank (coordinator abort, a
    peer's death, transport budget exhausted). Subclasses
    ``HorovodInternalError`` so the elastic driver classifies the
    attempt as a recoverable WORLD fault and relaunches (the PR-2
    ``_is_world_fault`` contract), instead of failing fast as if the
    user's forward fn had a bug."""


_FAULT_RE = re.compile(
    r"^kill@rank(?P<rank>\d+):batch(?P<batch>\d+)(?:@epoch(?P<epoch>\d+))?$")


def parse_serving_fault(spec: str) -> Optional[Tuple[int, int, int]]:
    """``kill@rankN:batchM[@epochE]`` -> (rank, batch_ordinal, epoch);
    empty -> None; typos fail loudly (the chaos-grammar loudness
    contract: a silently ignored fault spec certifies nothing). The
    batch ordinal is 1-based, like the chaos plane's msg ordinals."""
    spec = (spec or "").strip()
    if not spec:
        return None
    m = _FAULT_RE.match(spec)
    if m is None:
        raise ValueError(
            f"bad {_config.HOROVOD_SERVING_FAULT} spec {spec!r}; expected "
            f"'kill@rankN:batchM[@epochE]' (os._exit the rank right "
            f"before it reports its Mth batch result in epoch E)")
    if int(m.group("batch")) < 1:
        raise ValueError(
            f"bad {_config.HOROVOD_SERVING_FAULT} spec {spec!r}: batch "
            f"ordinals are 1-based")
    return (int(m.group("rank")), int(m.group("batch")),
            int(m.group("epoch") or 0))


def _digest(out: np.ndarray) -> str:
    """Cross-rank consistency digest of a batch output: bytes + dtype +
    shape (two ranks agreeing on bytes of different shapes is still a
    divergence)."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(out).tobytes())
    h.update(str(out.dtype).encode())
    h.update(repr(tuple(out.shape)).encode())
    return h.hexdigest()


def serve_worker(models: Dict[str, Callable],
                 addr: Optional[Tuple[str, int]] = None,
                 secret: Optional[bytes] = None,
                 rank: Optional[int] = None,
                 size: Optional[int] = None,
                 epoch: Optional[int] = None,
                 world_id: str = "",
                 jit: bool = True,
                 warmup: Tuple[Tuple[str, Tuple[int, ...], str], ...] = (),
                 connect_attempts: int = 100,
                 on_weights: Optional[Callable] = None) -> dict:
    """Serve until the coordinator says stop; returns this rank's stats.

    Defaults come from the environment the driver exported
    (``HOROVOD_SERVING_ADDR/PORT/SECRET`` via ``ServingPlane.env()``,
    rank/size from the launcher, epoch from the elastic driver).
    ``warmup`` pre-compiles ``(name, example_shape, dtype)`` buckets
    across every padding edge BEFORE the hello, so the first live batch
    never pays a compile. ``on_weights(version, tree)`` receives each
    digest-verified weight hot-swap the plane publishes
    (docs/checkpoint.md) — install the tree wherever the forward fns
    close over it; the dropped compile cache retraces against it. Clean
    stop returns ``{"outcome": "stopped", ...}``; any world-level
    failure raises :class:`ServingAbortedError` so the elastic driver
    relaunches."""
    from ..chaos import injector_from_env
    from ..runner.network import BasicClient, WireError

    if rank is None:
        rank = int(os.environ.get(_config.HOROVOD_RANK, "0"))
    if size is None:
        size = int(os.environ.get(_config.HOROVOD_SIZE, "1"))
    if epoch is None:
        epoch = int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))
    if addr is None:
        addr = (os.environ.get(_config.HOROVOD_SERVING_ADDR, "127.0.0.1"),
                int(os.environ[_config.HOROVOD_SERVING_PORT]))
    if secret is None:
        raw = os.environ.get(_config.HOROVOD_SERVING_SECRET, "")
        secret = bytes.fromhex(raw) if raw else None
    fault = parse_serving_fault(
        os.environ.get(_config.HOROVOD_SERVING_FAULT, ""))
    chaos = injector_from_env(rank, env=_config.HOROVOD_SERVING_CHAOS)

    compiled: Dict[Tuple, Callable] = {}
    jax_jit = None
    if jit:
        try:
            import jax

            jax_jit = jax.jit
        except Exception:  # noqa: BLE001 - numpy-only worlds still serve
            jax_jit = None

    def _step_fn(name: str):
        fn = models.get(name)
        if fn is None:
            raise KeyError(
                f"unknown model {name!r}; this world serves "
                f"{sorted(models)}")
        return fn

    def _run(name: str, batch: np.ndarray) -> np.ndarray:
        key = (name, tuple(batch.shape), str(batch.dtype))
        step = compiled.get(key)
        if step is None:
            base = _step_fn(name)
            step = jax_jit(base) if jax_jit is not None else base
            compiled[key] = step
            _WORKER_COMPILES.inc()
        return np.asarray(step(batch))

    # Pre-compile the declared buckets across every padding edge the
    # PLANE will actually pad to (its env block exports the effective
    # edge list; the env-derived ladder is only the no-plane fallback) —
    # these are the only shapes live traffic can present for the
    # declared examples.
    from ..core.config import _env_float
    from .batcher import derive_edges

    batch_max = max(int(os.environ.get(
        _config.HOROVOD_SERVING_BATCH_MAX, "8") or 8), 1)
    raw_edges = os.environ.get(_config.HOROVOD_SERVING_BUCKET_EDGES, "")
    explicit = tuple(int(e) for e in raw_edges.split(",")
                     if e.strip()) or None
    edges = derive_edges(
        batch_max, _env_float(_config.HOROVOD_SERVING_EDGE_RATIO, 2.0),
        explicit)
    for name, example_shape, dtype in warmup:
        for edge in edges:
            _run(name, np.zeros((edge,) + tuple(example_shape),
                                dtype=np.dtype(dtype)))

    shello = ("shello", rank, size, epoch, world_id)
    stats = {"rank": rank, "epoch": epoch, "batches": 0, "requests": 0,
             "compiled_buckets": 0, "outcome": "stopped",
             "swaps": 0, "weights_version": None}
    client = BasicClient(addr, secret=secret, timeout_s=None,
                         attempts=connect_attempts, chaos=chaos)
    # Re-identify after every transparent reconnect BEFORE the resent
    # request, like the controller client's hello (a dedup REPLAY
    # bypasses the handler and must not leave the connection anonymous).
    client.on_reconnect = lambda c: c.bare_request(shello)
    try:
        client.request(shello)
        ordinal = 0
        while True:
            resp = client.request(("infer", rank, epoch, ordinal))
            if resp[0] == "stop":
                break
            if resp[0] == "swap":
                # weight hot-swap at the batch boundary: verify, apply,
                # ack, re-request the SAME ordinal (docs/checkpoint.md)
                import pickle

                from ..integrity.consensus import digest_bytes
                from ..obs import flightrec as _flightrec

                _, version, want_digest, payload = resp
                if digest_bytes(payload) != want_digest:
                    raise ServingAbortedError(
                        f"weight swap v{version} payload fails its digest "
                        f"on rank {rank} — refusing torn weights")
                tree = pickle.loads(payload)
                if on_weights is not None:
                    on_weights(version, tree)
                # the compiled steps closed over the old weights: retrace
                compiled.clear()
                stats["swaps"] += 1
                stats["weights_version"] = version
                _flightrec.record(_flightrec.EV_SERVING_SWAP, version,
                                  aux=rank)
                client.request(("swap_ack", rank, epoch, version))
                continue
            assert resp[0] == "batch", resp
            _, got_ordinal, key, n_real, payload = resp
            assert got_ordinal == ordinal, (got_ordinal, ordinal)
            # flight recorder (docs/blackbox.md): batch receipt with its
            # dispatch ordinal — a wedged serving world's last evidence
            from ..obs import flightrec as _flightrec

            _flightrec.record(_flightrec.EV_SERVING_BATCH, ordinal,
                              aux=int(n_real))
            name = key[0]
            digest = None
            output = None
            error = None
            try:
                output = _run(name, payload)
                digest = _digest(output)
            except Exception as exc:  # noqa: BLE001 - a structural 500,
                # not a world fault: the coordinator fails this batch's
                # tickets and the loop keeps serving
                error = f"{type(exc).__name__}: {exc}"
            stats["batches"] += 1
            stats["requests"] += int(n_real)
            _WORKER_BATCHES.inc()
            if fault is not None and fault[0] == rank and \
                    fault[2] == epoch and stats["batches"] == fault[1]:
                os._exit(1)  # kill-mid-batch: result never reported
            _flightrec.record(_flightrec.EV_SERVING_DIGEST, ordinal)
            client.request(("result", rank, epoch, ordinal, digest,
                            output if rank == 0 else None, error))
            ordinal += 1
    except WireError as exc:
        raise ServingAbortedError(
            f"serving world aborted under rank {rank} (epoch {epoch}): "
            f"{exc}") from exc
    finally:
        client.close()
    stats["compiled_buckets"] = len(compiled)
    stats["reconnects"] = client.reconnects
    return stats
