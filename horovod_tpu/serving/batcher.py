"""Continuous micro-batcher: tickets, padding buckets, FIFO packing.

The serving plane's queueing core (docs/serving.md). Requests become
:class:`Ticket`\\ s keyed by the PR-3 response-cache identity convention —
``(name, dtype, shape)`` of one example (``bucket_key`` mirrors
``ops.response_cache.request_identity``) — so only requests a single
compiled forward step can serve together ever share a batch. Packing is
*continuous*: a batch is cut the moment a dispatch slot is free and any
ticket is queued, never waiting to fill (the 1802.05799 lesson applied to
serving — latency floors come from synchronization you didn't need). The
cut batch is padded up to the nearest bucket edge so the per-bucket
compile cache stays bounded; the fill ratio of every cut batch is
recorded on the obs registry.

Mechanism only: admission policy (SLO budget, queue caps, 429/503) lives
with the :class:`~horovod_tpu.serving.plane.ServingPlane`, which also
owns epochs and dispatch. Stdlib + numpy: importable in driver and
tooling processes that never load jax.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..obs.registry import registry as _metrics

# Observability plane (docs/metrics.md, "serving plane" section).
_QUEUE_DEPTH = _metrics().gauge(
    "horovod_serving_queue_depth",
    "Live tickets queued in the serving micro-batcher (admitted, not yet "
    "dispatched)")
_BATCHES = _metrics().counter(
    "horovod_serving_batches_total",
    "Micro-batches cut by the continuous batcher")
_FILL = _metrics().histogram(
    "horovod_serving_batch_fill_ratio",
    "Real rows over padded rows of every cut batch (1.0 = no padding "
    "waste)",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))


def bucket_key(name: str, dtype, shape) -> Tuple:
    """Padding-bucket identity of ONE example: ``(name, dtype, shape)``,
    the PR-3 response-cache identity convention (tensor name, dtype,
    shape fix the payload; see ``ops.response_cache.request_identity``).
    Requests batch together iff their keys are equal — the packed batch
    is then ``(padded_n,) + shape`` and one compiled step serves it."""
    return (str(name), str(np.dtype(dtype)), tuple(int(d) for d in shape))


def derive_edges(batch_max: int, ratio: float = 2.0,
                 explicit: Optional[Tuple[int, ...]] = None
                 ) -> Tuple[int, ...]:
    """Effective padding-bucket edges: the explicit list when given, else
    the geometric ladder 1, r, r^2, ... — always clipped to
    ``batch_max`` and always ending exactly there, so every cut batch
    pads to a member of a bounded set (the compile-cache bound)."""
    batch_max = max(int(batch_max), 1)
    if explicit:
        edges = sorted({int(e) for e in explicit if 0 < int(e) <= batch_max})
    else:
        ratio = max(float(ratio), 1.5)
        edges, edge = [], 1.0
        while int(edge) < batch_max:
            edges.append(int(edge))
            edge = max(edge * ratio, edge + 1)
    return tuple(sorted(set(edges) | {batch_max}))


def pad_to_edge(n: int, edges: Tuple[int, ...]) -> int:
    """Smallest edge >= n (callers never cut past the largest edge)."""
    for edge in edges:
        if n <= edge:
            return edge
    return edges[-1]


class Ticket:
    """One admitted request: input example, deadline, completion state.

    State transitions are one-way and race-safe: exactly one of
    ``complete`` / ``fail`` / ``claim_timeout`` wins; the losers see
    False and drop their outcome (a result arriving after the gateway
    thread already answered 503 is discarded, never a second answer)."""

    __slots__ = ("key", "array", "t0", "deadline", "_lock", "_event",
                 "state", "output", "status", "error", "epoch",
                 "retry_after_s")

    def __init__(self, key: Tuple, array: np.ndarray,
                 deadline_s: float) -> None:
        self.key = key
        self.array = array
        self.t0 = time.monotonic()
        self.deadline = self.t0 + max(float(deadline_s), 0.001)
        self._lock = threading.Lock()
        self._event = threading.Event()
        self.state = "queued"  # queued|dispatched|done|failed|timeout
        self.output: Optional[np.ndarray] = None
        self.status = 0
        self.error: Optional[str] = None
        self.epoch: Optional[int] = None
        self.retry_after_s: Optional[float] = None

    @property
    def closed(self) -> bool:
        return self.state in ("done", "failed", "timeout")

    def complete(self, output: np.ndarray) -> bool:
        with self._lock:
            if self.closed:
                return False  # the loser's outcome is dropped whole:
                # a late result must not touch the 503 already answered
            self.state = "done"
            self.status = 200
            self.output = output
        self._event.set()
        return True

    def fail(self, status: int, error: str, epoch: Optional[int] = None,
             retry_after_s: Optional[float] = None) -> bool:
        with self._lock:
            if self.closed:
                return False
            self.state = "failed"
            self.status, self.error = int(status), error
            self.epoch, self.retry_after_s = epoch, retry_after_s
        self._event.set()
        return True

    def claim_timeout(self, epoch: Optional[int] = None) -> bool:
        """The gateway thread claims its own ticket after the deadline
        passed unanswered; a late result then finds the ticket closed."""
        with self._lock:
            if self.closed:
                return False
            self.state = "timeout"
            self.status, self.epoch = 503, epoch
            self.error = "deadline exceeded"
        self._event.set()
        return True

    def mark_dispatched(self) -> None:
        """Queued -> dispatched, unless a deadline claim already closed
        the ticket (the loser of that race simply packs a row nobody is
        waiting for)."""
        with self._lock:
            if not self.closed:
                self.state = "dispatched"

    def reopen(self) -> bool:
        """Back to the queue after an elastic drain (plane only; forward
        steps are stateless, so re-dispatch cannot double-apply). False
        when a concurrent deadline claim closed the ticket first."""
        with self._lock:
            if self.closed:
                return False
            self.state = "queued"
            return True

    def wait(self, timeout_s: float) -> bool:
        return self._event.wait(timeout=timeout_s)


class MicroBatcher:
    """Per-bucket FIFO queues + continuous cut.

    ``next_batch`` blocks until any live ticket is queued (or the
    timeout lapses) and cuts up to ``batch_max`` tickets from the bucket
    holding the OLDEST queued head — cross-bucket fairness is strict
    arrival order, so a hot bucket cannot starve a cold one."""

    def __init__(self, batch_max: int = 8,
                 edges: Optional[Tuple[int, ...]] = None,
                 edge_ratio: float = 2.0) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: "OrderedDict[Tuple, Deque[Ticket]]" = OrderedDict()
        self._depth = 0
        self._batch_max = max(int(batch_max), 1)
        self._edge_ratio = float(edge_ratio)
        self._explicit_edges = tuple(edges) if edges else None

    # -- knob surface (the autotune appliers; docs/serving.md) ---------------

    @property
    def batch_max(self) -> int:
        return self._batch_max

    def set_batch_max(self, n: int) -> None:
        with self._lock:
            self._batch_max = max(int(n), 1)

    def set_edge_ratio(self, ratio: float) -> None:
        with self._lock:
            self._edge_ratio = float(ratio)

    def edges(self) -> Tuple[int, ...]:
        return derive_edges(self._batch_max, self._edge_ratio,
                            self._explicit_edges)

    @property
    def depth(self) -> int:
        return self._depth

    # -- queue mechanics ------------------------------------------------------

    def enqueue(self, ticket: Ticket, front: bool = False) -> None:
        with self._lock:
            queue = self._queues.get(ticket.key)
            if queue is None:
                queue = self._queues[ticket.key] = deque()
            if front:
                queue.appendleft(ticket)
            else:
                queue.append(ticket)
            self._depth += 1
            _QUEUE_DEPTH.set(self._depth)
            self._cond.notify_all()

    def requeue(self, tickets: List[Ticket]) -> None:
        """Front-requeue in original arrival order (the elastic drain:
        re-dispatch after re-arm must not jump the line both ways)."""
        for ticket in reversed(tickets):
            if ticket.reopen():
                self.enqueue(ticket, front=True)

    def _drop_closed_head(self, queue: Deque[Ticket]) -> None:
        while queue and queue[0].closed:
            queue.popleft()
            self._depth -= 1

    def next_batch(self, timeout_s: float = 0.2
                   ) -> Optional[Tuple[Tuple, List[Ticket], int]]:
        """Cut the next batch: ``(key, tickets, padded_n)``; None when
        nothing live is queued within ``timeout_s``. Closed tickets
        (deadline claims) are skimmed off, never packed."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        with self._lock:
            while True:
                oldest_key, oldest_t0 = None, None
                for key, queue in list(self._queues.items()):
                    self._drop_closed_head(queue)
                    if not queue:
                        # emptied buckets are removed, not kept: raw
                        # tensor shapes are client-controlled, so
                        # retained empties would grow (and be rescanned
                        # on every cut) forever in the one process that
                        # must stay up across relaunches
                        del self._queues[key]
                        continue
                    if oldest_t0 is None or queue[0].t0 < oldest_t0:
                        oldest_key, oldest_t0 = key, queue[0].t0
                if oldest_key is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _QUEUE_DEPTH.set(self._depth)
                    return None
                self._cond.wait(timeout=remaining)
            queue = self._queues[oldest_key]
            tickets: List[Ticket] = []
            while queue and len(tickets) < self._batch_max:
                ticket = queue.popleft()
                self._depth -= 1
                if not ticket.closed:
                    tickets.append(ticket)
            if not queue:
                del self._queues[oldest_key]
            _QUEUE_DEPTH.set(self._depth)
            edges = derive_edges(self._batch_max, self._edge_ratio,
                                 self._explicit_edges)
        if not tickets:  # every popped ticket was already closed
            return None
        padded = pad_to_edge(len(tickets), edges)
        _BATCHES.inc()
        _FILL.observe(len(tickets) / padded)
        return oldest_key, tickets, padded

    def drain(self) -> List[Ticket]:
        """Remove and return every live queued ticket (plane teardown /
        world-down bookkeeping)."""
        with self._lock:
            out: List[Ticket] = []
            for queue in self._queues.values():
                while queue:
                    ticket = queue.popleft()
                    if not ticket.closed:
                        out.append(ticket)
            self._queues.clear()
            self._depth = 0
            _QUEUE_DEPTH.set(0)
            return out

    def pack(self, tickets: List[Ticket], padded: int) -> np.ndarray:
        """Stack ticket examples into the padded batch array (zeros rows
        past the real count — sliced off again at completion, so padding
        is numerics-neutral by construction)."""
        _, dtype, shape = tickets[0].key
        batch = np.zeros((padded,) + tuple(shape), dtype=np.dtype(dtype))
        for i, ticket in enumerate(tickets):
            batch[i] = ticket.array
        return batch
