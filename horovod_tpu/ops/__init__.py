"""Public collective op API: sync + async named-tensor operations.

Mirrors the per-framework op surface of the reference
(``horovod/torch/mpi_ops.py:73-438``, ``horovod/tensorflow/mpi_ops.py``):
``allreduce[_async]`` / ``allgather[_async]`` / ``broadcast[_async]`` +
``poll`` / ``synchronize``, with optional compression. Two dispatch modes:

* **Eager** (default): the named tensor goes through the background engine —
  negotiation, fusion, timeline — and the result is returned as the same
  framework type that was passed in (JAX array in, JAX array out).
* **SPMD** (``axis_name=...``): inside ``shard_map``/``pjit`` the op lowers
  directly to an XLA collective (``ops.spmd``); no engine, no negotiation —
  the jit program order plays the role of the coordinator (SURVEY §7).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional

import numpy as np

from .. import basics
from ..core.status import HorovodInternalError
from . import fused_apply, spmd
from .compression import Compression
from .engine import ApplyContext, ApplyResult, _is_jax_array, get_engine
from .messages import OP_NAMES, RequestType

_noname_counter = itertools.count()
_ctx_lock = threading.Lock()
_handle_ctx: Dict[int, dict] = {}


# one jax-array detector for the whole package (the engine uses it to pick
# the device-resident execution path; here it picks snapshot + output type)
_is_jax = _is_jax_array


def _is_tracer(tensor: Any) -> bool:
    import jax.core

    return isinstance(tensor, jax.core.Tracer)


def _auto_name(op: str, name: Optional[str]) -> str:
    if name is not None:
        return name
    # Reference auto-names by handle ("allreduce.noname.<n>",
    # ``torch/mpi_ops.py:62-71``).
    return f"{op}.noname.{next(_noname_counter)}"


_jitted_copy = None
_jitted_copy_lock = threading.Lock()


def _device_snapshot(tensor):
    """On-device copy via one shape-polymorphic jitted program (jit caches
    per-shape executables internally) — ~4x cheaper per call than eager
    ``jnp.array(copy=True)`` on the submit path."""
    global _jitted_copy
    if _jitted_copy is None:
        with _jitted_copy_lock:
            if _jitted_copy is None:
                import jax
                import jax.numpy as jnp

                _jitted_copy = jax.jit(jnp.copy)
    return _jitted_copy(tensor)


def _to_numpy(tensor: Any) -> np.ndarray:
    arr = np.asarray(tensor)
    if arr.dtype == np.dtype("O"):
        raise TypeError(f"unsupported tensor type {type(tensor)!r}")
    return arr


def _submit(op: RequestType, tensor: Any, name: Optional[str],
            root_rank: int = -1, average: bool = False,
            compression=Compression.none) -> int:
    if _is_tracer(tensor):
        raise ValueError(
            "eager collective called on a traced value inside jit; pass "
            "axis_name= to use the SPMD collective instead.")
    name = _auto_name(OP_NAMES[op], name)
    compressed, comp_ctx = compression.compress(tensor)
    # Quantized and sparse codecs compress INSIDE the collective (shared
    # block scales need a cross-rank pmax, top-k pairs need the gather —
    # impossible pre-submit); the negotiation metadata carries the codec
    # tag so every rank picks the same wire.
    codec = getattr(compression, "codec_name", "none") \
        if (getattr(compression, "quantized", False)
            or getattr(compression, "sparse", False)) else "none"
    if _is_jax(compressed):
        # JAX arrays stay device-resident: the engine fuses and reduces
        # them with on-chip programs (no host round-trip) whenever the
        # negotiated batch allows, converting lazily only when a host wire
        # needs the bytes. The submission is an on-device SNAPSHOT: the
        # caller may donate or delete its buffer before the fusion cycle
        # packs it (jit donate_argnums invalidates buffers regardless of
        # Python references), and one deleted array would poison every
        # tensor fused into the same batch.
        arr = _device_snapshot(compressed)
    else:
        arr = _to_numpy(compressed)
    engine = get_engine()
    handle = engine.enqueue(op, arr, name, root_rank=root_rank, codec=codec)
    with _ctx_lock:
        # The handle stays bound to the engine that produced it: a completed
        # result must remain readable even after that engine stops (e.g. a
        # peer-initiated coordinated shutdown) — poll/synchronize must never
        # spin up a fresh engine.
        _handle_ctx[handle] = {
            "average": average,
            "compression": compression,
            "comp_ctx": comp_ctx,
            "jax_out": _is_jax(tensor),
            "engine": engine,
        }
        _evict_stale_ctx_locked()
    return handle


# Keep the API-layer context map bounded the same way the engine bounds its
# result table: abandoned handles are evicted oldest-first.
_MAX_RETAINED_CTX = 1 << 16


def _evict_stale_ctx_locked() -> None:
    while len(_handle_ctx) > _MAX_RETAINED_CTX:
        del _handle_ctx[next(iter(_handle_ctx))]


def release(handle: int) -> None:
    """Drop an async handle without waiting on it (the reference exposes
    ``HandleManager::ReleaseHandle``, ``torch/handle_manager.cc``). The
    collective still runs; only the result bookkeeping is discarded."""
    with _ctx_lock:
        _handle_ctx.pop(handle, None)


def _engine_of(handle: int):
    with _ctx_lock:
        ctx = _handle_ctx.get(handle)
    if ctx is None:
        raise ValueError(f"unknown handle {handle}")
    return ctx["engine"]


def poll(handle: int) -> bool:
    """True when the async op completed (``torch/mpi_ops.py:406-413``)."""
    return _engine_of(handle).handles.poll(handle)


def synchronize(handle: int) -> Any:
    """Block until done; raise on coordinator-constructed errors
    (``torch/mpi_ops.py:422-438`` → ``WaitAndClear``)."""
    engine = _engine_of(handle)
    with _ctx_lock:
        ctx = _handle_ctx.pop(handle, {})
    result = engine.handles.wait(handle)
    if result is None:
        raise HorovodInternalError("collective returned no result")
    if ctx.get("average"):
        size = basics.size()
        if size > 1:
            orig = result.dtype
            result = (result / size).astype(orig)
    out: Any = result
    if ctx.get("jax_out"):
        import jax.numpy as jnp

        out = jnp.asarray(result)
    compression = ctx.get("compression", Compression.none)
    return compression.decompress(out, ctx.get("comp_ctx"))


# -- allreduce ----------------------------------------------------------------

def allreduce(tensor: Any, average: bool = True, name: Optional[str] = None,
              compression=Compression.none,
              axis_name: Optional[spmd.AxisName] = None) -> Any:
    """Average (or sum) across ranks (``torch/mpi_ops.py:110-160``)."""
    if axis_name is not None:
        if getattr(compression, "quantized", False):
            # block-quantized wire: the codec owns the whole collective
            # (quantize -> int8/fp8 reduce -> dequantize), see spmd
            return spmd.quantized_allreduce(tensor, axis_name,
                                            average=average,
                                            codec=compression)
        if getattr(compression, "sparse", False):
            # top-k sparse wire: select -> gather pairs -> scatter-add,
            # see spmd.sparse_allreduce (error feedback is the caller's
            # state to thread — call spmd.sparse_allreduce directly with
            # ``residual=`` to carry it)
            return spmd.sparse_allreduce(tensor, axis_name,
                                         average=average,
                                         codec=compression)
        compressed, ctx = compression.compress(tensor)
        reduced = spmd.allreduce(compressed, axis_name, average=average)
        return compression.decompress(reduced, ctx)
    handle = allreduce_async(tensor, average=average, name=name,
                             compression=compression)
    return synchronize(handle)


def allreduce_async(tensor: Any, average: bool = True,
                    name: Optional[str] = None,
                    compression=Compression.none) -> int:
    return _submit(RequestType.ALLREDUCE, tensor, name,
                   average=average, compression=compression)


# -- fused reduce+apply (docs/tensor-fusion.md §fused apply) ------------------

def fused_apply_async(grad: Any, param: Any, slots, rule, count: int,
                      name: Optional[str] = None, average: bool = True,
                      compression=Compression.none,
                      zero1: bool = False) -> int:
    """Submit one gradient leaf for an apply-capable allreduce: the
    engine lands the APPLIED parameter and fresh optimizer slots from a
    fused reduce+apply program (or its split degrade) instead of
    handing the reduced gradient back. The caller must keep ``param``
    and ``slots`` alive (and unmutated) until :func:`apply_synchronize`
    returns — the engine packs them into the flush's buckets on its own
    thread. float32 only: the apply bucket math is defined at the wire
    dtype, and a silent cast here would change the optimizer's
    numerics."""
    if _is_tracer(grad):
        raise ValueError(
            "fused_apply_async called on a traced value inside jit; use "
            "spmd.reduce_apply (axis_name) there instead.")
    rule_obj = fused_apply.rule_of(rule) or rule
    if not isinstance(rule_obj, fused_apply.ApplyRule):
        raise TypeError(
            f"rule must be an ApplyRule or a transform from "
            f"hvd.fused_sgd/fused_momentum/fused_adam, got {rule!r}")
    for leaf in (grad, param) + tuple(slots):
        if str(getattr(leaf, "dtype", None)) != "float32":
            raise TypeError(
                f"fused apply requires float32 grads/params/slots, got "
                f"{getattr(leaf, 'dtype', type(leaf))} (cast the model "
                f"or keep the two-dispatch path)")
    if len(slots) != rule_obj.nslots:
        raise ValueError(
            f"rule {rule_obj.kind!r} needs {rule_obj.nslots} slot "
            f"leaves, got {len(slots)}")
    name = _auto_name("allreduce", name)
    codec = getattr(compression, "codec_name", "none") \
        if (getattr(compression, "quantized", False)
            or getattr(compression, "sparse", False)) else "none"
    arr = _device_snapshot(grad) if _is_jax(grad) else _to_numpy(grad)
    engine = get_engine()
    handle = engine.enqueue(
        RequestType.ALLREDUCE, arr, name, codec=codec,
        apply=ApplyContext(rule=rule_obj, param=param,
                           slots=tuple(slots), count=int(count),
                           average=average, zero1=zero1))
    with _ctx_lock:
        _handle_ctx[handle] = {"apply": True, "jax_out": _is_jax(param),
                               "engine": engine}
        _evict_stale_ctx_locked()
    return handle


def apply_synchronize(handle: int):
    """Block on an apply-capable handle; returns
    ``(new_param, new_slots)`` in the submission's array flavor (jax
    param in → jax out). Raises like :func:`synchronize` on coordinator
    errors, sentry aborts, and shutdowns."""
    engine = _engine_of(handle)
    with _ctx_lock:
        ctx = _handle_ctx.pop(handle, {})
    result = engine.handles.wait(handle)
    if not isinstance(result, ApplyResult):
        raise HorovodInternalError(
            "apply_synchronize on a non-apply handle (use synchronize "
            "for plain collectives)")
    if ctx.get("jax_out"):
        import jax.numpy as jnp

        return (jnp.asarray(result.param),
                tuple(jnp.asarray(s) for s in result.slots))
    # copy, never a view: host-route results are reshape views into the
    # power-of-two padded apply buckets — handing them out would pin up
    # to ~2x param+slot memory on the caller's long-lived state trees
    return (np.array(result.param),
            tuple(np.array(s) for s in result.slots))


def zero1_active() -> bool:
    """True when the running engine armed ZeRO-1 execution — config
    opt-in AND the XLA device plane AND a world bigger than one
    (docs/sharding.md). The runtime answer front-ends MUST consult
    before localizing optimizer state: ``HOROVOD_ZERO=1`` alone is
    intent, not capability, and shard slots submitted to an unarmed
    engine fail loudly."""
    return bool(getattr(get_engine(), "_zero1_exec", False))


# -- allgather ----------------------------------------------------------------

def allgather(tensor: Any, name: Optional[str] = None,
              axis_name: Optional[spmd.AxisName] = None) -> Any:
    """Concatenate across ranks along dim 0 (``torch/mpi_ops.py:236-300``).
    Per-rank first dimensions may differ in eager mode; inside jit they must
    match (static shapes)."""
    if axis_name is not None:
        return spmd.allgather(tensor, axis_name)
    return synchronize(allgather_async(tensor, name=name))


def allgather_async(tensor: Any, name: Optional[str] = None) -> int:
    return _submit(RequestType.ALLGATHER, tensor, name)


# -- broadcast ----------------------------------------------------------------

def broadcast(tensor: Any, root_rank: int, name: Optional[str] = None,
              axis_name: Optional[spmd.AxisName] = None) -> Any:
    """All ranks receive root's value (``torch/mpi_ops.py:318-380``)."""
    if axis_name is not None:
        return spmd.broadcast(tensor, root_rank, axis_name)
    return synchronize(broadcast_async(tensor, root_rank, name=name))


def broadcast_async(tensor: Any, root_rank: int,
                    name: Optional[str] = None) -> int:
    return _submit(RequestType.BROADCAST, tensor, name, root_rank=root_rank)


__all__ = [
    "Compression",
    "allreduce", "allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async",
    "fused_apply", "fused_apply_async", "apply_synchronize",
    "poll", "synchronize", "release",
    "spmd",
]
