"""Fused reduce+apply optimizer rules for the eager data plane.

PAPERS 2305.06942 (fused computation-collective operations) shows the
win from compiling a collective and its consumer into ONE program; here
the consumer is the optimizer leaf update. An :class:`ApplyRule`
describes one of the three supported elementwise update rules —
SGD / momentum / Adam — with its hyperparameters baked in, and this
module is the SINGLE definition of the update math every execution path
shares:

* the **optax twin** (:func:`sgd` / :func:`momentum` / :func:`adam`
  return an ``optax``-style ``(updates, new_state)`` transform) — the
  two-dispatch reference path ``DistributedOptimizer`` / ``apply_step``
  run when ``HOROVOD_FUSED_APPLY`` is off;
* the engine's **split** execution (reduce dispatch, then the per-leaf
  jitted apply) — the native-controller / mixed-batch degrade;
* the engine's **fused bucket program** (host plane: one compiled apply
  over the padded fusion bucket; device plane: the same body compiled
  INTO the psum program by ``XlaDataPlane.reduce_apply``).

Because all paths call the same jnp expressions in the same order, the
bit-exactness the ``dryrun_fused_apply`` certification demands —
fused vs two-dispatch, split vs fused, bucket vs leaf — holds by
construction: the update is elementwise with scalar hyperparameters, so
concatenating leaves into a bucket cannot change any element's value.

The rule ``fingerprint`` is the apply-program identity: it rides the
negotiation (``Request.apply_fingerprint`` / ``Response.fused_apply``),
keys the compiled-program caches, and joins the response-cache request
identity — an optimizer-hyperparameter change is a new fingerprint and
therefore a cache MISS, never a silent replay of stale apply programs.
"""

from __future__ import annotations

import atexit
import threading
from dataclasses import dataclass
from typing import Any, NamedTuple, Tuple

KINDS = ("sgd", "momentum", "adam")

# slot buffers per rule kind (momentum: trace; adam: mu, nu)
_NSLOTS = {"sgd": 0, "momentum": 1, "adam": 2}


@dataclass(frozen=True)
class ApplyRule:
    """One fusable optimizer leaf-update rule, hyperparameters baked in.

    ``loss_scale`` is divided out of the reduced gradient before the
    update math (the mixed-precision unscale fused into the same
    program); 1.0 (default) skips the divide entirely so the unscaled
    path stays bit-identical to a rule that never heard of loss
    scaling."""

    kind: str
    lr: float
    momentum: float = 0.0
    nesterov: bool = False
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    loss_scale: float = 1.0

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fused-apply rule {self.kind!r}; expected one of "
                f"{'|'.join(KINDS)}")
        if self.loss_scale <= 0:
            raise ValueError(
                f"loss_scale must be positive, got {self.loss_scale}")

    @property
    def nslots(self) -> int:
        return _NSLOTS[self.kind]

    @property
    def fingerprint(self) -> str:
        """Stable apply-program identity. Every hyperparameter
        participates: two rules with different math must never share a
        compiled program, a fused batch, or a cached response layout."""
        if self.kind == "sgd":
            extra = ""
        elif self.kind == "momentum":
            extra = f",m={self.momentum!r},nag={int(self.nesterov)}"
        else:
            extra = f",b1={self.b1!r},b2={self.b2!r},eps={self.eps!r}"
        return (f"{self.kind}:lr={self.lr!r}{extra}"
                f",ls={self.loss_scale!r}")

    # -- the single definition of the update math -----------------------------

    def update_math(self, g, count, slots: Tuple) -> Tuple[Any, Tuple]:
        """``(update, new_slots)`` from an (averaged, unscaled) gradient.

        Elementwise jnp ops mirroring the optax formulas exactly
        (``optax._src.transform``: ``trace``/``scale_by_adam`` +
        ``scale(-lr)``), in the same order — the property the bit-exact
        twin tests pin. ``count`` is the already-incremented step number
        (optax's ``count_inc``), shared by every leaf of a step."""
        import jax.numpy as jnp

        if self.loss_scale != 1.0:
            g = g / jnp.float32(self.loss_scale)
        if self.kind == "sgd":
            return (-self.lr) * g, ()
        if self.kind == "momentum":
            (trace,) = slots
            new_trace = g + self.momentum * trace
            d = g + self.momentum * new_trace if self.nesterov \
                else new_trace
            return (-self.lr) * d, (new_trace,)
        mu, nu = slots
        new_mu = (1 - self.b1) * g + self.b1 * mu
        new_nu = (1 - self.b2) * (g ** 2) + self.b2 * nu
        c1 = 1 - jnp.float32(self.b1) ** count
        c2 = 1 - jnp.float32(self.b2) ** count
        mu_hat = new_mu / c1.astype(new_mu.dtype)
        nu_hat = new_nu / c2.astype(new_nu.dtype)
        u = (-self.lr) * (mu_hat / (jnp.sqrt(nu_hat) + self.eps))
        return u, (new_mu, new_nu)

    def apply_body(self, g, p, count, slots: Tuple, gate: bool,
                   denom: int) -> Tuple:
        """Full in-program body over one (leaf or bucket) gradient:
        nonfinite census of the raw reduced values → optional census
        gate (zero the gradient on a non-finite batch, the sentry's
        collective ``skip`` semantics — bit-identical to the sentry
        zeroing the reduced batch before a separate apply dispatch) →
        average divide → unscale+update → landed parameters.

        Returns ``(new_p, nan_count, inf_count, *new_slots)``."""
        import jax.numpy as jnp

        nans = jnp.isnan(g).sum()
        infs = (~jnp.isfinite(g)).sum() - nans
        new_p, new_slots = self.shard_apply_body(g, p, count, slots,
                                                 gate, denom, nans, infs)
        return (new_p, nans, infs) + tuple(new_slots)

    def shard_apply_body(self, g, p, count, slots: Tuple, gate: bool,
                         denom: int, nans, infs) -> Tuple[Any, Tuple]:
        """The gate→divide→update tail of :meth:`apply_body`, with the
        nonfinite census supplied by the caller — the ZeRO-1 sharded
        program computes the census over its reduce-scattered shard and
        psums it to the GLOBAL batch counts before gating, so every
        rank's shard gates on the identical collective verdict. Same jnp
        expressions in the same order as the replicated body: a shard of
        the bucket lands bit-identically to the same slice of the
        replicated bucket's output (elementwise math, scalar
        hyperparameters).

        Returns ``(new_p, new_slots)``."""
        import jax.numpy as jnp

        if gate:
            g = jnp.where(nans + infs > 0, jnp.zeros_like(g), g)
        if denom != 1:
            g = g / denom
        u, new_slots = self.update_math(g, count, slots)
        return p + u, tuple(new_slots)


class FusedApplyState(NamedTuple):
    """Optax-style state of a fused-apply rule: the shared step count
    (Adam bias correction) and one slot tree per rule slot."""

    count: Any
    slots: Tuple


# -- compiled-program caches --------------------------------------------------
# One jitted program per (rule fingerprint, variant); jit specializes per
# input shape internally, so leaf programs serve every leaf shape and
# bucket programs every power-of-two bucket without a cache-key explosion.

_fn_lock = threading.Lock()
_fns: dict = {}


def _cached(key, builder):
    with _fn_lock:
        fn = _fns.get(key)
    if fn is not None:
        return fn
    fn = builder()
    with _fn_lock:
        _fns[key] = fn
    return fn


def clear_programs() -> None:
    """Drop every cached compiled program.

    Registered atexit because it is LOAD-BEARING, not a tidy-up: these
    executables are compiled on the engine's flush-worker thread, and on
    this jaxlib destroying such an executable during late interpreter
    finalization (module-dict purge) aborts the process in C++
    ("terminate called without an active exception" — a joinable ORC
    helper thread torn down after the runtime state it needs is gone).
    Dropping them from the atexit phase, while the runtime is still
    healthy, is safe; a concurrent caller simply recompiles on the next
    miss. Reproduced at ~30% per run by the fused-apply bench worker
    before this hook; 0/8 after."""
    with _fn_lock:
        _fns.clear()


atexit.register(clear_programs)


def leaf_update_fn(rule: ApplyRule):
    """Jitted ``(g, count, *slots) -> (u, *new_slots)`` — the optax
    twin's per-leaf compute, shared so the two-dispatch reference and
    the fused plane can never drift apart numerically."""
    def _build():
        import jax

        def _update(g, count, *slots):
            u, new_slots = rule.update_math(g, count, slots)
            return (u,) + tuple(new_slots)
        return jax.jit(_update)
    return _cached(("leaf", rule.fingerprint), _build)


def bucket_apply_fn(rule: ApplyRule, gate: bool, denom: int):
    """Jitted ``(g, p, count, *slots) -> (new_p, nan, inf, *new_slots)``
    over a flat bucket — the host plane's single apply dispatch (the
    reduce itself is the TCP exchange there). The device plane compiles
    the same ``apply_body`` INTO its psum program instead
    (``XlaDataPlane.reduce_apply``)."""
    def _build():
        import jax

        def _apply(g, p, count, *slots):
            return rule.apply_body(g, p, count, slots, gate, denom)
        return jax.jit(_apply)
    return _cached(("bucket", rule.fingerprint, gate, denom), _build)


# -- optax twins --------------------------------------------------------------

def as_optax(rule: ApplyRule):
    """The rule as an ``optax.GradientTransformation`` — the
    two-dispatch reference implementation, marked with the rule so
    ``DistributedOptimizer`` can thread it into the engine when
    ``HOROVOD_FUSED_APPLY=1``."""
    import jax
    import jax.numpy as jnp
    import optax

    def init_fn(params):
        slots = tuple(
            jax.tree_util.tree_map(jnp.zeros_like, params)
            for _ in range(rule.nslots))
        return FusedApplyState(count=jnp.zeros((), jnp.int32),
                               slots=slots)

    def update_fn(updates, state, params=None):
        del params
        count_inc = state.count + 1
        fn = leaf_update_fn(rule)
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        slot_leaves = [jax.tree_util.tree_flatten(s)[0]
                       for s in state.slots]
        out_u, out_slots = [], [[] for _ in range(rule.nslots)]
        for i, g in enumerate(leaves):
            res = fn(g, count_inc, *(s[i] for s in slot_leaves))
            out_u.append(res[0])
            for k in range(rule.nslots):
                out_slots[k].append(res[1 + k])
        unflatten = jax.tree_util.tree_unflatten
        new_slots = tuple(unflatten(treedef, s) for s in out_slots)
        return (unflatten(treedef, out_u),
                FusedApplyState(count=count_inc, slots=new_slots))

    update_fn._horovod_apply_rule = rule
    return optax.GradientTransformation(init_fn, update_fn)


def sgd(lr: float, loss_scale: float = 1.0):
    """Fusable plain SGD: ``u = -lr * g`` (optax ``scale(-lr)``)."""
    return as_optax(ApplyRule("sgd", lr, loss_scale=loss_scale))


def momentum(lr: float, momentum: float, nesterov: bool = False,
             loss_scale: float = 1.0):
    """Fusable momentum SGD (optax ``trace(decay) + scale(-lr)``)."""
    return as_optax(ApplyRule("momentum", lr, momentum=momentum,
                              nesterov=nesterov, loss_scale=loss_scale))


def adam(lr: float, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8, loss_scale: float = 1.0):
    """Fusable Adam (optax ``scale_by_adam + scale(-lr)``)."""
    return as_optax(ApplyRule("adam", lr, b1=b1, b2=b2, eps=eps,
                              loss_scale=loss_scale))


def rule_of(tx) -> Any:
    """The :class:`ApplyRule` a transform carries, or ``None`` — the
    marker :func:`as_optax` leaves on its update function and
    ``DistributedOptimizer`` forwards from its inner optimizer."""
    return getattr(getattr(tx, "update", None), "_horovod_apply_rule",
                   None)
