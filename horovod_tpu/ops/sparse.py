"""DEPRECATED location — ``ops/sparse_wire.py`` owns sparse gradients now.

This module is a compatibility shim (the ``checkpoint.py`` precedent):
the tf.IndexedSlices rebuild — allgather(values) + allgather(indices)
with summing deferred to densify, Horovod's only sparse path
(``tensorflow/__init__.py:72-83``) — moved verbatim to
:mod:`horovod_tpu.ops.sparse_wire` when the top-k sparse wire landed
(docs/compression.md §sparse), so there is exactly one sparse-gradient
implementation. ``IndexedSlices``/``allreduce_sparse`` keep working from
here unchanged; new code should import ``ops.sparse_wire`` — which also
carries what this module never had: the top-k selection, the
error-feedback residual, and the byte-exact wire decode both the engine
and the consensus authority screen.
"""

from __future__ import annotations

from .sparse_wire import IndexedSlices, allreduce_sparse  # noqa: F401

__all__ = ["IndexedSlices", "allreduce_sparse"]
