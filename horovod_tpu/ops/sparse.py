"""Sparse (indexed-slices) gradient support via allgather.

Rebuild of the reference's only sparse path: TF ``tf.IndexedSlices``
gradients are allreduced as allgather(values) + allgather(indices)
(``tensorflow/__init__.py:72-83``) — summing is deferred to whoever applies
the slices, and duplicate indices across ranks are legal. JAX has no
IndexedSlices type; embedding-style gradients appear as (indices, values)
pairs, modeled here by ``IndexedSlices``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import allgather, allgather_async, spmd, synchronize


@dataclass
class IndexedSlices:
    """A sparse tensor: ``values[i]`` belongs to row ``indices[i]`` of a
    dense tensor of shape ``dense_shape`` (mirror of tf.IndexedSlices)."""

    indices: Any   # int array [n]
    values: Any    # array [n, ...]
    dense_shape: Tuple[int, ...]

    def to_dense(self):
        out = jnp.zeros(self.dense_shape,
                        dtype=jnp.asarray(self.values).dtype)
        return out.at[jnp.asarray(self.indices)].add(
            jnp.asarray(self.values))


def allreduce_sparse(slices: IndexedSlices, average: bool = True,
                     name: Optional[str] = None,
                     axis_name: Optional[spmd.AxisName] = None) -> IndexedSlices:
    """Allreduce an IndexedSlices by gathering every rank's (indices,
    values); duplicate rows sum when densified. ``average`` scales values by
    1/size, matching the dense allreduce contract
    (``tensorflow/__init__.py:76-83``)."""
    name = name or "allreduce_sparse"
    if axis_name is not None:
        gathered_values = spmd.allgather(slices.values, axis_name)
        gathered_indices = spmd.allgather(
            jnp.asarray(slices.indices).reshape(-1, 1), axis_name).reshape(-1)
        if average:
            from jax import lax

            # Divide by the product of ALL named axis sizes: a tuple
            # axis_name gathers size(a)·size(b)·… contributions, so
            # scaling by only the first axis under-divides multi-axis
            # meshes (pinned by tests/test_zzsparse.py).
            denom = 1
            for ax in ((axis_name,) if isinstance(axis_name, str)
                       else tuple(axis_name)):
                denom = denom * lax.axis_size(ax)
            gathered_values = gathered_values / denom
        return IndexedSlices(gathered_indices, gathered_values,
                             slices.dense_shape)

    from .. import basics

    values_handle = allgather_async(slices.values, name=f"{name}.values")
    indices_handle = allgather_async(
        np.asarray(slices.indices).reshape(-1, 1), name=f"{name}.indices")
    values = synchronize(values_handle)
    indices = np.asarray(synchronize(indices_handle)).reshape(-1)
    if average:
        values = values / basics.size()
    return IndexedSlices(indices, values, slices.dense_shape)
