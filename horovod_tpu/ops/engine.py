"""Eager collective engine: named-tensor async submission + cycle loop.

Rebuild of the worker half of ``horovod/common/operations.cc``: the
submission queue + tensor table of ``EnqueueTensorAllreduce/Allgather/
Broadcast`` (``operations.cc:2472-2591``), the background cycle loop
``RunLoopOnce`` (``:2030-2380``), op execution ``PerformOperation``
(``:768-1621``), and the torch-style handle manager
(``torch/handle_manager.{h,cc}``). Differences by design:

* Tensors are host numpy arrays OR device-resident ``jax.Array``s; device
  submissions fuse and reduce through on-chip programs (zero host
  transfers) and convert lazily only when a host wire needs bytes. The
  bulk-performance path on TPU remains the SPMD ``DistributedOptimizer``/
  jit route where XLA owns the collectives and none of this machinery runs
  (SURVEY §7 design stance).
* The multi-process data plane is the controller's host exchange (numpy over
  the authenticated TCP wire) — the CPU-world stand-in for MPI. On-device
  eager collectives across processes ride the same negotiated order; the
  identical ResponseList on every rank is what makes issuing the same XLA
  program legal (SURVEY §7 "hard parts").
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from .. import basics
from ..analysis.witness import maybe_wrap as _witness_wrap
from ..core import config as _config
from ..core.logging import LOG
from ..core.status import SHUT_DOWN_ERROR, Status
from ..obs import TimelineBridge, flightrec as _flightrec, \
    registry as _obs_registry
from ..runner.network import default_secret
from ..utils.timeline import TRACE_META, Timeline, rank_timeline_path
from .autotuner import Autotuner
from .controller import (
    ControllerClient,
    ControllerService,
    make_negotiator,
)
from .messages import (
    OP_NAMES as _OP_NAMES,
    DataType,
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
    dtype_of,
)


def _is_sparse_codec(codec: str) -> bool:
    """Whether a negotiated codec tag names the top-k sparse wire
    (docs/compression.md §sparse) — the routing fork shared by the
    plain and apply-fused allreduce paths."""
    if codec == "none":
        return False
    from .compression import Compression

    return bool(getattr(Compression.lookup(codec), "sparse", False))

# Observability plane (docs/tracing.md): time spent turning negotiated
# responses into results — the "execute" half of the straggler report's
# negotiation-wait vs execute breakdown. Device-plane batches are
# asynchronous dispatches, so this measures dispatch + host-path data
# movement; device completion time lives in the JAX profiler.
_EXECUTE_SECONDS = _obs_registry().histogram(
    "horovod_execute_seconds",
    "Per-response execution time on the engine loop (dispatch + "
    "host-path data movement; device completion is asynchronous)")

# Generation-ordered sub-buffer flush (docs/tensor-fusion.md): the
# compute/collective overlap the pipeline actually ACHIEVED, measured —
# seconds the loop thread spent negotiating cycle k+1 while cycle k's
# flush was executing on the flush worker. The in-flight gauges make the
# ">= 2 cycles in flight" claim falsifiable.
_OVERLAP_SECONDS = _obs_registry().counter(
    "horovod_overlap_seconds_total",
    "Seconds of negotiation overlapped with an in-flight sub-buffer "
    "flush (the measured compute/collective overlap)")
_FLUSH_INFLIGHT = _obs_registry().gauge(
    "horovod_flush_inflight",
    "Sub-buffer flushes currently in flight (negotiated, not yet "
    "executed to completion)")
_FLUSH_INFLIGHT_PEAK = _obs_registry().gauge(
    "horovod_flush_inflight_peak",
    "Peak in-flight sub-buffer flush depth observed by this engine")
_SUBBUFFER_FLUSHES = _obs_registry().counter(
    "horovod_subbuffer_flushes_total",
    "Sub-buffer flushes dispatched through the overlap pipeline")

# Fused reduce+apply plane (docs/tensor-fusion.md §fused apply): batches
# that landed applied parameters, by execution strategy — "fused" is the
# single reduce+apply program, "split" the reduce-then-apply degrade
# (native controller wire, mixed batches, or the tuned knob) — plus the
# optimizer-apply dispatch count behind the dispatches-per-step story
# (fused: one per batch; split: one per leaf).
_REDUCE_APPLY_BATCHES = _obs_registry().counter(
    "horovod_reduce_apply_batches_total",
    "Allreduce batches that landed applied parameters from the engine",
    labels=("mode",))
_APPLY_DISPATCHES = _obs_registry().counter(
    "horovod_apply_dispatches_total",
    "Optimizer-apply program dispatches (standalone per-leaf programs "
    "on the two-dispatch/split routes; one combined program per batch "
    "when fused into the reduce)")


def cut_generations(entries: List["TensorTableEntry"],
                    n: int) -> List[List["TensorTableEntry"]]:
    """Cut one cycle tick's drained submissions into up to ``n``
    generation-ordered sub-buffers (docs/tensor-fusion.md).

    Chunks are CONTIGUOUS in arrival order — backprop produces gradients
    last-layer-first, so the earliest arrivals form the first sub-buffer
    and flush while later generations are still being produced (the
    T3-style overlap, arXiv 2401.16677). Boundaries fall where the
    cumulative payload crosses ``total * k / n`` so sub-buffers carry
    roughly equal bytes; every chunk is non-empty and the concatenation
    of the chunks is exactly the input (no reordering — the negotiated
    execution order stays the arrival order, which keeps sentry
    ordinals, consensus windows, and cache positions aligned)."""
    if not entries:
        return []
    n = max(1, min(int(n), len(entries)))
    if n == 1:
        return [list(entries)]
    sizes = [max(int(getattr(e.array, "nbytes", 0) or 0), 1)
             for e in entries]
    total = sum(sizes)
    out: List[List[TensorTableEntry]] = []
    cur: List[TensorTableEntry] = []
    acc = 0
    for i, (entry, size) in enumerate(zip(entries, sizes)):
        cur.append(entry)
        acc += size
        remaining_entries = len(entries) - i - 1
        remaining_chunks = n - len(out) - 1
        if remaining_chunks and (
                acc * n >= total * (len(out) + 1)
                or remaining_entries == remaining_chunks):
            out.append(cur)
            cur = []
    if cur:
        out.append(cur)
    return out


class _FlushClock:
    """Worker-busy accounting for the overlap measurement: the flush
    worker brackets every flush with ``mark_start``/``mark_end``, and the
    loop thread reads ``busy_seconds()`` before/after a negotiation — the
    delta is EXACTLY the worker-busy time inside that window (the single
    worker thread makes busy intervals disjoint), i.e. the achieved
    negotiate-while-flushing overlap."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._busy_since: Optional[float] = None
        self._busy_total = 0.0

    def mark_start(self) -> None:
        with self._lock:
            self._busy_since = time.monotonic()

    def mark_end(self) -> None:
        with self._lock:
            if self._busy_since is not None:
                self._busy_total += time.monotonic() - self._busy_since
                self._busy_since = None

    def busy_seconds(self) -> float:
        with self._lock:
            total = self._busy_total
            if self._busy_since is not None:
                total += time.monotonic() - self._busy_since
            return total


@dataclass
class ApplyContext:
    """Fused reduce+apply submission context (docs/tensor-fusion.md
    §fused apply): everything the engine needs to land this gradient's
    APPLIED parameter instead of the reduced gradient — the baked-in
    update rule, the current parameter and optimizer-slot leaves (the
    caller keeps them alive until ``apply_synchronize`` returns), and
    the already-incremented step count (Adam bias correction)."""

    rule: Any  # fused_apply.ApplyRule
    param: Any  # np.ndarray | jax.Array
    slots: tuple  # rule.nslots leaves, same shape as param — or, when
    # ``zero1`` is set, this rank's 1-D shard of each slot
    count: int
    average: bool = True
    # ZeRO-1 submission (docs/sharding.md): slots are this rank's shard
    # rows and the batch must run the reduce-scatter → shard-apply →
    # all-gather program. Rank-local routing state — it never rides the
    # wire (the negotiated fingerprint + the init-pinned exec flag keep
    # the fused/zero1 decision rank-identical), so no registry row.
    zero1: bool = False


class ApplyResult:
    """What an apply-capable response lands in the handle table: the
    applied parameter and the fresh optimizer slots (never the reduced
    gradient). Carries ``shape`` so the timeline's end-record contract
    for results holds unchanged."""

    __slots__ = ("param", "slots")

    def __init__(self, param, slots: tuple) -> None:
        self.param = param
        self.slots = tuple(slots)

    @property
    def shape(self):
        return self.param.shape


@dataclass
class TensorTableEntry:
    """In-flight named tensor (``common.h:77-98`` TensorTableEntry).

    ``array`` is a host numpy array OR a device-resident ``jax.Array`` —
    the TPU-native analog of the reference's device tensors staying on-GPU
    through the NCCL plane: jax submissions are fused/reduced by on-chip
    programs and only hit the host when a host wire needs the bytes."""

    name: str
    op: RequestType
    array: Any  # np.ndarray | jax.Array, per the docstring contract
    handle: int
    root_rank: int = -1
    codec: str = "none"  # negotiated wire-compression tag (messages.Request)
    # fused reduce+apply context, None for a plain collective
    apply: Optional[ApplyContext] = None


def _is_jax_array(a) -> bool:
    if isinstance(a, np.ndarray):
        return False
    try:
        import jax
    except Exception:  # noqa: BLE001 - no jax in this process
        return False
    return isinstance(a, jax.Array)


def _jax_multiprocess() -> bool:
    try:
        import jax

        return jax.process_count() > 1
    except Exception:  # noqa: BLE001 - no jax runtime yet
        return False


def _adopt_controller_fd(use_native: bool) -> Optional[int]:
    """Claim the launcher-inherited controller listener, if any.

    The launcher binds the controller socket itself and rank 0 inherits
    it (launcher._free_port TOCTOU fix) — consume the env marker so a
    re-init on the same process (``shutdown(); init()``) binds the port
    normally instead of adopting an fd the first service already closed.
    The native (C++) service binds its own socket, so there the inherited
    fd is closed to free the port for it — the backlogged early
    connections reset and the clients' connect retries re-dial."""
    fd_env = os.environ.pop(_config.HOROVOD_CONTROLLER_FD, None)
    if not fd_env:
        return None
    fd = int(fd_env)
    if use_native:
        try:
            os.close(fd)
        except OSError:
            pass
        return None
    return fd


# Handle ids are unique across engine generations (an engine can be torn
# down by shutdown and a fresh one started by re-init); ids must never
# collide in the API layer's handle→context map.
_handle_counter = itertools.count()


class HandleManager:
    """Async handles: allocate / mark done / poll / wait
    (``torch/handle_manager.cc:22-52``). Results carry the numpy output so
    ``synchronize`` can hand it back to the framework layer. Completed
    results remain readable after the engine stops — only never-completed
    entries get flushed with SHUT_DOWN_ERROR.

    Eviction contract: past ``MAX_RETAINED`` completed-but-unclaimed
    results, the oldest lose their PAYLOAD (the numpy array — the part
    that matters for memory) but keep a tombstone, so a late
    ``poll``/``wait`` gets a self-explanatory eviction error rather than
    ``unknown handle``. Tombstones are only dropped entirely past
    ``MAX_TOMBSTONES`` — at that point the caller abandoned >1M handles
    and ``unknown handle`` is accurate."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._done: Dict[int, threading.Event] = {}
        self._results: Dict[int, tuple] = {}
        self._evicted: Dict[int, None] = {}  # insertion-ordered set

    def allocate(self) -> int:
        with self._lock:
            handle = next(_handle_counter)
            self._done[handle] = threading.Event()
            return handle

    # Abandoned handles (fired-and-forgotten async ops) must not grow the
    # result table without bound in week-long jobs; evict oldest completed
    # payloads past this many outstanding results, oldest tombstones past
    # MAX_TOMBSTONES. Tombstoned handles share one pre-set Event (they are
    # all completed by construction) so a tombstone costs two dict slots,
    # not a live Event.
    MAX_RETAINED = 1 << 16
    MAX_TOMBSTONES = 1 << 18
    _TOMBSTONE_EVENT = threading.Event()
    _TOMBSTONE_EVENT.set()

    def mark_done(self, handle: int, status: Status,
                  result: Optional[np.ndarray]) -> None:
        with self._lock:
            self._results[handle] = (status, result)
            self._done[handle].set()
            while len(self._results) > self.MAX_RETAINED:
                oldest = next(iter(self._results))
                del self._results[oldest]
                self._evicted[oldest] = None
                self._done[oldest] = self._TOMBSTONE_EVENT
            while len(self._evicted) > self.MAX_TOMBSTONES:
                stale = next(iter(self._evicted))
                del self._evicted[stale]
                self._done.pop(stale, None)

    def poll(self, handle: int) -> bool:
        with self._lock:
            event = self._done.get(handle)
        if event is None:
            raise ValueError(f"unknown handle {handle}")
        return event.is_set()

    def wait(self, handle: int, timeout: Optional[float] = None):
        with self._lock:
            event = self._done.get(handle)
        if event is None:
            raise ValueError(f"unknown handle {handle}")
        if not event.wait(timeout):
            raise TimeoutError(f"collective handle {handle} did not complete")
        with self._lock:
            if handle in self._evicted:
                del self._evicted[handle]
                self._done.pop(handle, None)
                raise ValueError(
                    f"handle {handle}: result evicted — it completed but "
                    f"went unclaimed while > {self.MAX_RETAINED} newer "
                    f"results piled up; synchronize() or release() handles "
                    f"promptly")
            status, result = self._results.pop(handle)
            del self._done[handle]
        status.raise_if_error()
        return result


class _DevicePlaneWorker:
    """Sacrificial executor for device-plane collectives.

    A compiled XLA collective blocks until every participant issues it;
    Python cannot interrupt that execution. If a peer dies mid-collective
    the survivors would hang until the transport's own (long or absent)
    timeout — so the engine runs device-plane calls on this daemon thread
    and waits abortably: when the controller pushes a world abort (watch
    channel), the engine abandons the call and surfaces SHUT_DOWN_ERROR
    (reference semantics, ``operations.cc:1942-1957``). The abandoned
    thread may stay blocked in the dead collective; that is fine — the
    world is over and the process is about to exit, exactly like the
    reference's ranks after a NCCL comm abort.

    Single worker thread: collectives keep the engine's launch order."""

    def __init__(self) -> None:
        import queue

        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._run, name="horovod-device-plane", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            fn, args, fut = self._q.get()
            if fn is None:
                return
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as exc:  # noqa: BLE001 - ship to waiter
                fut.set_exception(exc)

    def submit(self, fn, *args):
        from concurrent.futures import Future

        fut = Future()
        self._q.put((fn, args, fut))
        return fut

    def stop(self, join_timeout_s: float = 0.0) -> None:
        """Queue the shutdown sentinel; with ``join_timeout_s`` > 0 also
        wait (bounded) for the thread to exit. Joining matters when the
        worker has RUN compiled XLA programs: a daemon thread frozen
        mid-C++ at interpreter finalization can leave jaxlib destructors
        facing a live thread ("terminate called without an active
        exception" aborts at exit). A worker parked in a dead collective
        never consumes the sentinel — the bounded join keeps teardown
        hang-free and the daemon flag keeps the abandonment safe."""
        self._q.put((None, None, None))
        if join_timeout_s > 0:
            self._thread.join(timeout=join_timeout_s)


class Engine:
    """One per process; owns the background cycle thread."""

    def __init__(self) -> None:
        topo = basics._topology()
        cfg = basics.config()
        self._rank = topo.rank
        self._size = topo.size
        self._cfg = cfg
        # lock witness (docs/analysis.md): under HOROVOD_LOCK_WITNESS=1
        # the engine lock joins the global held-before graph so tests
        # catch cross-module inversions the AST pass cannot see
        self._lock = _witness_wrap(threading.Lock(),
                                   "ops.engine.Engine._lock")
        self._submissions: List[TensorTableEntry] = []
        self._pending: Dict[str, TensorTableEntry] = {}
        self.handles = HandleManager()
        self._stop_requested = False
        self._stopped = threading.Event()
        self._wake = threading.Event()

        # Plain HOROVOD_TIMELINE stays rank-0-only (the reference
        # artifact, back-compat); HOROVOD_TIMELINE_ALL_RANKS=1 records on
        # EVERY member rank into rank-suffixed files that
        # tools/trace_merge.py folds into one clock-corrected world trace
        # (docs/tracing.md). Members only either way: subset-world
        # NON-members also carry rank 0 (their self-world) and would
        # clobber the member artifact.
        timeline_path = ""
        if cfg.timeline_path and topo.is_member:
            if cfg.timeline_all_ranks:
                timeline_path = rank_timeline_path(cfg.timeline_path,
                                                   topo.rank)
            elif topo.rank == 0:
                timeline_path = cfg.timeline_path
        self.timeline = Timeline(timeline_path, cfg.timeline_mark_cycles)
        if self.timeline.enabled:
            # identity record first: trace_merge must know whose lane
            # this file is even if the job dies before any span closes
            self.timeline.meta(TRACE_META, {
                "rank": topo.rank, "size": topo.size,
                "epoch": basics.world_epoch()})
        # Per-cycle span stamps (cycle ordinal + cache generation): set
        # each tick by _cycle_span_args, attached to NEGOTIATE end /
        # EXECUTE begin records so spans correlate across per-rank trace
        # files without a shared clock (docs/tracing.md).
        self._span_args: Optional[dict] = None
        self._local_cycle_no = 0
        # Observability plane (docs/metrics.md): registry deltas ride the
        # timeline as Chrome counter tracks (no-op when the timeline is
        # off); the publisher below feeds cross-rank aggregation.
        self._metrics_bridge = TimelineBridge(_obs_registry(), self.timeline)
        self._metrics_stop: Optional[threading.Event] = None
        self._metrics_thread: Optional[threading.Thread] = None
        self._metrics_interval_s = cfg.metrics_interval_s
        # Closed-loop tuning plane (docs/autotune.md): the last
        # extended-knob map this rank applied from a cycle response — the
        # change detector behind the timeline AUTOTUNE audit records.
        self._applied_knobs: dict = {}
        self._clock_sync = None

        self._service: Optional[ControllerService] = None
        # Hierarchical negotiation tree (docs/hierarchy.md): island heads
        # additionally host their sub-coordinator beside (not instead of)
        # anything else they run — rank 0 hosts BOTH the root service and
        # island 0's head. The planned successor hosts a STANDBY twin
        # (docs/recovery.md) that serves members only after they fail
        # over to it.
        self._subcoord = None
        self._standby_subcoord = None
        self._client: Optional[ControllerClient] = None
        self._negotiator = None
        self._native_controller = False  # set with use_native below
        self._autotuner: Optional[Autotuner] = None
        # The autotuner lives with the controller service — launcher
        # world-rank 0 (when a member; a non-member service host builds its
        # own in start_subset_service, and this engine's size-1 self-world
        # must not grow an orphan tuner beside it). The extended knob set
        # (cache capacity / codec / metrics interval) needs the Python
        # controller wire to apply; size-1 and native-controller worlds
        # tune the classic (fusion, cycle) pair only (docs/autotune.md).
        if cfg.autotune and topo.world_rank == 0 and topo.is_member:
            extended = False
            if self._size > 1:
                from .native_controller import native_controller_enabled

                extended = not native_controller_enabled(cfg)
            self._autotuner = Autotuner(cfg, extended=extended)
        self._plane = None
        if self._size == 1:
            self._negotiator = make_negotiator(1, cfg)
            if cfg.data_plane == "xla" and not _jax_multiprocess() \
                    and not topo.in_subset_world:
                # Explicit HOROVOD_DATA_PLANE=xla in a world of one: still
                # build the device plane so host tensors ride H2D → compiled
                # reduce on the accelerator → D2H. This is how the eager
                # front-ends (torch hooks → engine → XLA plane) get a
                # measured single-chip number; "auto" keeps the pure-host
                # short-circuit. Guarded like the size>1 branch: a size-1
                # self-world inside a multi-process JAX world (subset
                # non-member, or HOROVOD_DATA_PLANE=xla exported
                # pod-wide) must not touch the global device mesh —
                # XlaDataPlane requires one JAX process per rank.
                from .xla_plane import XlaDataPlane

                self._plane = XlaDataPlane(topo)
            elif cfg.data_plane == "xla":
                LOG.warning(
                    "HOROVOD_DATA_PLANE=xla ignored for this size-1 world: "
                    "the device plane spans all JAX processes, and this "
                    "world does not own them (multi-process JAX world or "
                    "subset non-member). Collectives short-circuit on "
                    "host.")
        else:
            if topo.in_subset_world:
                # The device plane spans the FULL jax process world; a
                # subset communicator must not issue collectives over it
                # (non-members would never participate). Host exchange only.
                if cfg.data_plane == "xla":
                    LOG.warning(
                        "subset world (init(ranks=...)): forcing the host "
                        "data plane — XLA collectives span the full device "
                        "mesh, not a rank subset.")
            elif cfg.data_plane == "xla" or (
                    cfg.data_plane == "auto" and _jax_multiprocess()):
                # The reference's NCCL/MPI split: the TCP controller below
                # stays the control plane; bytes move as compiled XLA
                # collectives over the global device mesh (ICI/DCN on pods,
                # gloo on CPU test worlds).
                from .xla_plane import XlaDataPlane

                self._plane = XlaDataPlane(topo)
            secret = default_secret()
            port = int(os.environ.get(_config.HOROVOD_CONTROLLER_PORT, "0"))
            addr = os.environ.get(_config.HOROVOD_CONTROLLER_ADDR, "127.0.0.1")
            if port == 0 and topo.world_rank != 0:
                raise RuntimeError(
                    "multi-process world but HOROVOD_CONTROLLER_PORT is not "
                    "set; the launcher (horovodrun / horovod_tpu.runner) "
                    "must export the coordinator address to every rank.")
            from .native_controller import (
                NativeControllerClient,
                NativeControllerService,
                native_controller_enabled,
            )

            # Native (C++) vs Python controller: one decision from config +
            # library availability, identical on every rank (the two speak
            # different wires).
            use_native = native_controller_enabled(cfg)
            self._native_controller = use_native
            from .controller import world_id_of

            world_id = world_id_of(topo.members, self._size)
            # Hierarchical negotiation tree (docs/hierarchy.md): resolve
            # the control-plane topology once, identically on every rank
            # (pure arithmetic over size/mode/cross_size — no extra
            # negotiation round). Every degrade below is DETERMINISTIC
            # and warned once — a silently-flat world would fake the
            # scaling the knob asked for, so only known-safe fallbacks
            # stay quiet on non-zero ranks.
            from .hierarchy import FLAT as _FLAT_HIER, plan_topology

            hier = _FLAT_HIER
            if cfg.hierarchy not in ("", "flat"):
                if use_native:
                    if topo.world_rank == 0:
                        LOG.warning(
                            "HOROVOD_HIERARCHY=%s degraded to flat: the "
                            "native C++ controller wire predates the "
                            "island RPCs; set HOROVOD_NATIVE_CONTROLLER=0 "
                            "for the negotiation tree.", cfg.hierarchy)
                elif topo.in_subset_world:
                    if topo.world_rank == 0:
                        LOG.warning(
                            "HOROVOD_HIERARCHY=%s degraded to flat for "
                            "this subset world: islands are planned over "
                            "the full launcher world only.", cfg.hierarchy)
                else:
                    # Head overrides (docs/recovery.md): the elastic
                    # driver's succession verdict after a head death.
                    # Parsed on EVERY rank from the same exported string
                    # so the plan stays rank-identical.
                    from .hierarchy import parse_head_overrides

                    hier = plan_topology(
                        self._size, cfg.hierarchy, topo.cross_size,
                        head_overrides=parse_head_overrides(
                            os.environ.get(
                                _config.HOROVOD_ISLAND_HEADS, "")))
                    if not hier.flat and not os.environ.get(
                            _config.HOROVOD_SUBCOORD_PORT):
                        if topo.world_rank == 0:
                            LOG.warning(
                                "HOROVOD_HIERARCHY=%s degraded to flat: "
                                "the launcher exported no island "
                                "sub-coordinator listener "
                                "(HOROVOD_SUBCOORD_PORT); launch via "
                                "horovod_tpu.runner for the tree.",
                                cfg.hierarchy)
                        hier = _FLAT_HIER
                    elif hier.flat and topo.world_rank == 0:
                        LOG.warning(
                            "HOROVOD_HIERARCHY=%s resolved to a single "
                            "island; keeping the flat star (a 1-island "
                            "tree is the star plus a pointless hop).",
                            cfg.hierarchy)
            if not hier.flat:
                from .hierarchy import HIER_ISLANDS

                HIER_ISLANDS.set(hier.n_islands)
            # Self-healing grace for dropped rank connections: host-
            # plane worlds only, unless the knob was set explicitly.
            # With the XLA data plane a dead peer's in-flight compiled
            # collective cannot be outlived safely — on the gloo CPU
            # backend it can even complete with GARBAGE buffers before
            # a delayed abort lands — so death attribution stays
            # immediate there by default. (Hoisted from the rank-0
            # branch: island heads apply the same window to their own
            # member connections.)
            window_s = cfg.reconnect_window_s if (
                self._plane is None or cfg.reconnect_window_explicit
            ) else 0.0
            if topo.world_rank == 0:
                # Controller duty follows the launcher's advertised address
                # (world rank 0), not the subset rank numbering.
                bind_host = os.environ.get(
                    _config.HOROVOD_CONTROLLER_BIND, "127.0.0.1")
                listen_fd = _adopt_controller_fd(use_native)
                if use_native:
                    if cfg.straggler_evict != "off":
                        LOG.warning(
                            "HOROVOD_STRAGGLER_EVICT=%s ignored: the "
                            "native controller keeps its arrival data in "
                            "C++; set HOROVOD_NATIVE_CONTROLLER=0 for "
                            "straggler mitigation.", cfg.straggler_evict)
                    self._service = NativeControllerService(
                        self._size, cfg, secret=secret, port=port,
                        bind_host=bind_host, autotuner=self._autotuner,
                        world_id=world_id)
                else:
                    negotiator = make_negotiator(self._size, cfg)
                    detector = None
                    if cfg.straggler_evict != "off":
                        # Persistent-straggler mitigation: fed from the
                        # coordinator's arrival attribution; construction
                        # validates the mode loudly (docs/autotune.md).
                        # The native service keeps its arrival data in
                        # C++, so the plane is Python-controller-only.
                        from ..tune.detector import StragglerDetector

                        detector = StragglerDetector.from_config(
                            cfg, self._size)
                    self._service = ControllerService(
                        self._size, negotiator, secret=secret, port=port,
                        bind_host=bind_host, autotuner=self._autotuner,
                        world_id=world_id,
                        stall_shutdown_s=cfg.stall_shutdown_time_s,
                        stall_warning_s=cfg.stall_warning_time_s,
                        listen_fd=listen_fd,
                        cache_capacity=cfg.cache_capacity,
                        fusion_threshold_bytes=cfg.fusion_threshold_bytes,
                        reconnect_window_s=window_s,
                        straggler_detector=detector,
                        codec_min_bytes=cfg.autotune_codec_min_bytes,
                        consensus_interval_steps=(
                            cfg.consensus_interval_steps),
                        islands=hier.islands or None)
                port = self._service.port
            if not hier.flat and hier.is_head(topo.world_rank):
                # This rank heads its island: host the sub-coordinator
                # BEFORE dialing any client — members may dial the head
                # the moment its launcher-bound listener is served, and
                # rank 0 heads island 0 BESIDE the root service it just
                # started (its head dials the freshly-bound root port).
                from .hierarchy import SubCoordinatorService

                sub_fd_env = os.environ.pop(
                    _config.HOROVOD_SUBCOORD_FD, None)
                island = hier.island_of[topo.world_rank]
                root_addrs = [a.strip() for a in addr.split(",")
                              if a.strip()]
                self._subcoord = SubCoordinatorService(
                    island, hier.islands[island],
                    upstream_addr={a: (a, port) for a in root_addrs},
                    secret=secret,
                    port=int(os.environ.get(
                        _config.HOROVOD_SUBCOORD_PORT, "0")),
                    world_id=world_id,
                    listen_fd=int(sub_fd_env) if sub_fd_env else None,
                    reconnect_window_s=window_s,
                    # After a succession the serving head may not be the
                    # lowest member — its upstream hello must carry ITS
                    # rank so the root's head map tracks reality.
                    head_rank=topo.world_rank)
            if not hier.flat and not hier.is_head(topo.world_rank) and (
                    hier.successor_of(hier.island_of[topo.world_rank])
                    == topo.world_rank):
                # Planned standby head (docs/recovery.md): host a dormant
                # twin of the island service on the standby listener the
                # launcher pre-bound. It holds NO upstream channels until
                # the first member request lands — a failover that never
                # happens costs one idle listener and nothing else.
                from .hierarchy import SubCoordinatorService

                standby_fd_env = os.environ.pop(
                    _config.HOROVOD_SUBCOORD_STANDBY_FD, None)
                standby_port_env = os.environ.get(
                    _config.HOROVOD_SUBCOORD_STANDBY_PORT)
                if standby_fd_env or standby_port_env:
                    island = hier.island_of[topo.world_rank]
                    root_addrs = [a.strip() for a in addr.split(",")
                                  if a.strip()]
                    self._standby_subcoord = SubCoordinatorService(
                        island, hier.islands[island],
                        upstream_addr={a: (a, port) for a in root_addrs},
                        secret=secret,
                        port=int(standby_port_env or "0"),
                        world_id=world_id,
                        listen_fd=(int(standby_fd_env)
                                   if standby_fd_env else None),
                        reconnect_window_s=window_s,
                        head_rank=topo.world_rank,
                        standby=True)
            # The launcher may advertise several controller addresses
            # (comma-separated: every NIC of the controller host); the
            # client probes them and uses the first routable one.
            addr_list = [a.strip() for a in addr.split(",") if a.strip()]
            if not addr_list:
                raise RuntimeError(
                    f"HOROVOD_CONTROLLER_ADDR is set but empty ({addr!r}); "
                    f"the launcher must export the controller address.")
            client_cls = (NativeControllerClient if use_native
                          else ControllerClient)
            addr_map = {a: (a, port) for a in addr_list}
            client_fallback = None
            if not hier.flat:
                # Every rank's control-plane connection — cycle/payload/
                # sentry client, metrics publisher, clock sync, flight-
                # recorder push, watch — dials its ISLAND HEAD instead of
                # the root; the head aggregates or relays. This address
                # swap IS the tree from a member's point of view: no
                # other rank-side code has a hierarchy branch, which is
                # what keeps the member wire (and so the negotiated
                # bytes) identical with flat.
                sub_addrs = [s.strip() for s in os.environ.get(
                    _config.HOROVOD_SUBCOORD_ADDR, "127.0.0.1"
                ).split(",") if s.strip()] or ["127.0.0.1"]
                sub_port = (self._subcoord.port
                            if self._subcoord is not None else
                            int(os.environ.get(
                                _config.HOROVOD_SUBCOORD_PORT, "0")))
                addr_map = {a: (a, sub_port) for a in sub_addrs}
                # Head succession (docs/recovery.md): every island rank —
                # the head included, whose own service a headstop drill
                # kills under it — arms the island's planned STANDBY
                # listener as the cycle client's fallback candidate.
                # Tried only once every reconnect round against the
                # primary fails, so a live head never loses a member to
                # it. Cycle/payload/sentry wire only: the metrics
                # publisher, clock sync, and flightrec push channels stay
                # primary-only (their loss is a documented degrade, not a
                # correctness hazard).
                standby_port = (
                    self._standby_subcoord.port
                    if self._standby_subcoord is not None else
                    int(os.environ.get(
                        _config.HOROVOD_SUBCOORD_STANDBY_PORT, "0")
                        or 0))
                if standby_port and standby_port != sub_port:
                    client_fallback = {
                        a: (a, standby_port) for a in sub_addrs}
            self._client = client_cls(
                addr_map, secret=secret,
                timeout_s=None, rank=self._rank, world_id=world_id,
                **({"log_stalls": self._rank == 0,
                    "stall_shutdown_s": cfg.stall_shutdown_time_s,
                    "stall_warning_s": cfg.stall_warning_time_s}
                   if use_native else
                   {"fallback": client_fallback}))
            if not use_native:
                # Metrics publisher (docs/metrics.md): pushes this rank's
                # registry snapshot to the coordinator's store on an
                # interval, over its own ANONYMOUS connection — never the
                # cycle client, whose strict request/response sequencing a
                # metrics push would corrupt. Python controller wire only:
                # the native service's fixed binary protocol predates the
                # metrics RPC (same pattern as the cache-bit and codec
                # fields).
                self._start_metrics_publisher(addr_map, secret, world_id)
            # Clock alignment (docs/tracing.md): offset-to-coordinator
            # estimation where something consumes it; degrades
            # deterministically on the native wire (clock_sync_supported).
            self._maybe_start_clock_sync(addr_map, secret, world_id)
            # Flight recorder (docs/blackbox.md): arm this rank's dump
            # context — on any world abort the event tail ships to the
            # coordinator's incident collector over the anonymous
            # "flightrec" RPC; the native wire predates the RPC and
            # degrades to a rank-local dump (warned once at dump time).
            _flightrec.arm_push(
                addr_map, secret, world_id, self._rank,
                basics.world_epoch(), snapshot_fn=self.state_snapshot,
                local_only=not getattr(self._client,
                                       "flightrec_supported", False))

        self._host_fallback_warned = set()

        # Steady-state negotiation bypass (docs/response-cache.md): the
        # rank-side response cache, mirrored by the coordinator. Python
        # controller wire only — the native controller's fixed binary wire
        # predates the cache-bit field, so it deterministically keeps the
        # full-RequestList cycle on every rank (the same pattern PR 1
        # applies to quantized codecs there). Size-1 worlds negotiate
        # in-process; there is no metadata round trip to bypass.
        self._response_cache = None
        if self._client is not None and cfg.cache_capacity > 0:
            if self._native_controller:
                LOG.debug(
                    "response cache disabled: the native controller wire "
                    "predates the cache-bit field; set "
                    "HOROVOD_NATIVE_CONTROLLER=0 to enable the "
                    "steady-state negotiation bypass.")
            else:
                from .response_cache import ResponseCache

                self._response_cache = ResponseCache(cfg.cache_capacity)
        # The bypass arms only after the coordinator's first full response
        # CONFIRMS it carries a cache (cache_generation is not None): the
        # loop idles from init, and an unconfirmed cache-bit tick against
        # a capacity-0 coordinator (env divergence) would abort the world
        # where this handshake instead degrades deterministically.
        self._cache_confirmed = False

        # Data-plane integrity plane (docs/integrity.md): the gradient
        # sentry screens every reduced allreduce batch; the consensus
        # accumulator digests post-allreduce bytes every
        # HOROVOD_CONSENSUS_INTERVAL_STEPS batches for the coordinator to
        # compare; the data-chaos injector poisons host-side fused
        # buffers deterministically (the plane's verifiable ground
        # truth). All three default off and cost nothing disarmed.
        self._sentry = None
        self._consensus_acc = None
        self._data_chaos = None
        if cfg.grad_sentry != "off":
            from ..integrity.sentry import GradSentry

            exchange = None
            if self._client is not None:
                if getattr(self._client, "sentry_exchange_supported",
                           False):
                    exchange = self._sentry_exchange
                else:
                    LOG.warning(
                        "HOROVOD_GRAD_SENTRY=%s: the native controller "
                        "wire predates the verdict-exchange RPC; sentry "
                        "verdicts are LOCAL-ONLY on this world (a NaN "
                        "still propagates through the sum, so collective "
                        "faults are caught; set "
                        "HOROVOD_NATIVE_CONTROLLER=0 for collective "
                        "verdicts).", cfg.grad_sentry)
            self._sentry = GradSentry(
                cfg.grad_sentry, exchange=exchange,
                on_trip=self._on_sentry_trip,
                # device-resident results screen on-device (two scalars
                # synced, not a full D2H) via the plane's census program
                probe=(self._plane.nonfinite_counts
                       if self._plane is not None else None))
        if cfg.consensus_interval_steps > 0 and self._client is not None:
            if self._native_controller:
                LOG.warning(
                    "HOROVOD_CONSENSUS_INTERVAL_STEPS=%d ignored: the "
                    "native controller wire predates the digest field; "
                    "set HOROVOD_NATIVE_CONTROLLER=0 for cross-rank "
                    "consensus verification.",
                    cfg.consensus_interval_steps)
            else:
                from ..integrity.consensus import DigestAccumulator

                self._consensus_acc = DigestAccumulator(
                    cfg.consensus_interval_steps)
        from ..chaos import injector_from_env

        injector = injector_from_env(self._rank)
        if injector is not None and injector.has_data_rules():
            self._data_chaos = injector

        # Gradient numerics observatory (docs/tensorwatch.md): sampled
        # per-tensor telemetry over reduced allreduce batches — norm²/
        # absmax/nnz/log₂ histogram/top-k mass, plus decode-error SNR
        # for quantized codecs in play or consented. Disabled (interval
        # 0) = no object at all: the hot path pays one `is not None`
        # check and zero allocations (the flightrec bar, pinned by the
        # tracemalloc test). Device-resident batches measure through
        # the plane's compiled collective-free probes (scalars synced,
        # no buffer D2H — the PR 8 census pattern).
        from ..obs import tensorwatch as _tensorwatch

        self._tensorwatch = _tensorwatch.from_config(
            cfg, size=self._size, rank=self._rank,
            probe=(self._plane.tensorwatch_stats
                   if self._plane is not None else None),
            snr_probe=(self._plane.codec_snr
                       if self._plane is not None else None),
            norm2_probe=(self._plane.tensorwatch_norm2
                         if self._plane is not None else None),
            timeline=self.timeline)

        # Generation-ordered sub-buffer flush (docs/tensor-fusion.md):
        # with HOROVOD_FUSION_SUBBUFFERS >= 2 the loop cuts each tick's
        # pending queue into arrival-ordered sub-buffers and keeps up to
        # that many negotiate/execute cycles in flight — cycle k+1's
        # negotiation (a cheap cache-bit vector in steady state) overlaps
        # cycle k's allreduce on the flush worker. 1 (default) keeps
        # today's single-flush barrier byte-identically: no worker, no
        # data channel, the untouched loop body.
        # Sparse top-k error-feedback residuals (docs/compression.md
        # §sparse): the dropped (non-top-k) mass of every sparse batch,
        # carried per tensor name so it re-enters the next step's
        # selection. Stamped with the elastic world epoch — a relaunch
        # restarts from committed state, so pre-relaunch residuals must
        # never replay into it (pinned by tests/test_zzsparse.py). The
        # fraction key is validated loudly at init, not at first batch.
        from .compression import TopKCompressor

        TopKCompressor.set_fraction_key(cfg.sparse_topk)
        self._sparse_residuals: Dict[str, Any] = {}
        self._sparse_epoch = basics.world_epoch()
        self._sparse_error_feedback = cfg.sparse_error_feedback

        self._subbuffers = cfg.fusion_subbuffers
        # Fused reduce+apply plane (docs/tensor-fusion.md §fused apply):
        # execution strategy for apply-capable batches — True runs the
        # single reduce+apply program, False the reduce-then-apply split.
        # Numerics-exact either way (the shared ApplyRule math), so the
        # tuning plane may flip it live via the `fused_apply` tuned knob
        # without a consent gate — on the HOST wire only, where the
        # reduce exchange is byte-identical in both strategies; on the
        # XLA device plane the strategies issue different compiled
        # collective programs, so the value is pinned at init
        # (_apply_tuned_knobs ignores the retune there, warned once).
        self._fused_apply_exec = True
        # ZeRO-1 execution capability (docs/sharding.md): init-pinned
        # like the device plane's fused_apply strategy — the sharded and
        # replicated programs issue DIFFERENT compiled collectives, so
        # the decision must be rank-identical for the life of the world.
        # Requires the XLA device plane (the reduce-scatter/all-gather
        # pair is a compiled program, not a TCP exchange) and a world
        # big enough to shard; the front-end consults this through
        # ops.zero1_active() before localizing any state, so an unarmed
        # world simply keeps replicated slots.
        self._zero1_exec = bool(cfg.zero1) and self._plane is not None \
            and self._size > 1
        if cfg.zero1 and not self._zero1_exec:
            LOG.warning(
                "HOROVOD_ZERO=1 requested but not armed (%s): optimizer "
                "state stays replicated; applied parameters are "
                "identical either way.",
                "world of one" if self._size <= 1
                else "host data plane — ZeRO-1 needs the XLA device "
                     "plane")
        self._apply_counts = {"fused": 0, "split": 0, "dispatches": 0,
                              "zero1": 0}
        self._flush_worker: Optional[_DevicePlaneWorker] = None
        self._flush_clock: Optional[_FlushClock] = None
        self._inflight: "deque" = deque()
        self._inflight_peak = 0
        self._flush_count = 0
        self._overlap_seconds = 0.0
        self._pipeline_warned = False
        if self._subbuffers > 1:
            self._arm_flush_pipeline()

        # XLA-plane failure propagation: a rank blocked inside a compiled
        # collective is beyond the reach of a poisoned control-plane
        # response, so subscribe to the controller's abort push channel and
        # run device collectives on an abandonable worker thread.
        self._abort_event = threading.Event()
        self._abort_reason: Optional[str] = None
        self._device_worker: Optional[_DevicePlaneWorker] = None
        self._finalizer_q = None
        self._crashed = False
        self._shutdown_reason: Optional[str] = None
        if self._plane is not None and self._client is not None:
            import queue

            self._device_worker = _DevicePlaneWorker()
            self._client.watch(self._on_world_abort)
            # Completion signalling, the reference's CUDA-event-queue +
            # finalizer-thread design (``operations.cc`` event_queue): XLA
            # dispatch is asynchronous, so a just-dispatched collective is
            # NOT done — handles must complete only when the device work
            # does. The finalizer waits (abortably, on its own sacrificial
            # worker) and then marks the handles, keeping the cycle loop
            # free to negotiate the next batch while this one executes.
            self._completion_worker = _DevicePlaneWorker()
            self._finalizer_q = queue.SimpleQueue()
            self._finalizer = threading.Thread(
                target=self._finalize_loop, name="horovod-finalizer",
                daemon=True)
            self._finalizer.start()

        self._thread = threading.Thread(
            target=self._loop, name="horovod-background", daemon=True)
        self._thread.start()

    def _start_metrics_publisher(self, addr, secret,
                                 world_id: str = "") -> None:
        """Cross-rank metrics aggregation feed: a daemon thread pushes
        this process's registry snapshot to the coordinator every
        ``HOROVOD_METRICS_INTERVAL_S`` (<= 0 disables). Faults drop the
        sample and redial next tick — the controller restarting or gone
        means the world is ending and a lost metrics push is noise. The
        push rides ``BasicClient.request``, so a frame lost in transit
        heals by the wire's dedup/reconnect machinery like any other
        control message; no chaos injector is attached (chaos ordinals
        target the CYCLE channel, and a second injected stream would
        desynchronize replay determinism)."""
        interval = self._cfg.metrics_interval_s
        if interval <= 0:
            return
        if not self._cfg.metrics_port and \
                not self._cfg.metrics_interval_explicit:
            # as opt-in as the exposition server: no port and no explicit
            # interval means nothing consumes the pushes — spawn no
            # thread, dial no connection
            return
        # Live knob: the tuning plane may retune the interval mid-run
        # (_apply_tuned_knobs); the loop re-reads it each tick.
        self._metrics_interval_s = interval
        self._metrics_stop = threading.Event()
        stop = self._metrics_stop
        rank = self._rank
        from ..runner.network import BasicClient

        def _push_loop() -> None:
            failures = 0  # consecutive; a single lost push is noise, a
            # persistent streak (wrong world on a shared port, bad secret)
            # must degrade LOUDLY like every other plane here
            client = None
            try:
                # Eager dial (final-flush contract): the connection must
                # exist BEFORE a negotiated shutdown closes the
                # coordinator's listener — an ESTABLISHED connection's
                # handler thread outlives service.shutdown(), so the final
                # push below still lands, while a first-ever dial at that
                # point would find the listener gone and silently lose the
                # whole final interval.
                client = BasicClient(addr, secret=secret,
                                     timeout_s=5.0, attempts=3)
            except Exception:  # noqa: BLE001 - the first tick retries
                client = None
            try:
                while True:
                    # stop.wait returning True is the engine's teardown
                    # signal: push ONE final snapshot (the last partial
                    # interval must not be silently lost), then exit. The
                    # engine's bounded join is the time cap — best-effort
                    # by contract, the wire may already be gone.
                    stopping = stop.wait(
                        max(self._metrics_interval_s, 0.05))
                    try:
                        if client is None:
                            client = BasicClient(addr, secret=secret,
                                                 timeout_s=5.0, attempts=3)
                        # world_id rides along so a co-located different
                        # world's service (shared port) refuses the push
                        # instead of storing it
                        client.request(("metrics", rank,
                                        _obs_registry().snapshot(),
                                        world_id))
                        failures = 0
                    except Exception as exc:  # noqa: BLE001 - drop, redial
                        failures += 1
                        if failures == 3 and not stop.is_set():
                            LOG.warning(
                                "metrics publisher: %d consecutive push "
                                "failures (last: %s); world snapshots will "
                                "miss rank %d until the feed recovers",
                                failures, exc, rank)
                        if client is not None:
                            try:
                                client.close()
                            except Exception:  # noqa: BLE001
                                pass
                            client = None
                    if stopping:
                        return
            finally:
                if client is not None:
                    try:
                        client.close()
                    except Exception:  # noqa: BLE001
                        pass

        self._metrics_thread = threading.Thread(
            target=_push_loop, name="horovod-metrics-publisher",
            daemon=True)
        self._metrics_thread.start()

    def _maybe_start_clock_sync(self, addr, secret,
                                world_id: str = "") -> None:
        """Clock alignment (docs/tracing.md): runs only where something
        consumes the offset — a recording timeline on this rank, or the
        metrics plane opted in (the gauges then ride the snapshot wire).
        The coordinator-hosting rank IS the reference timebase (offset 0
        by definition, no probes); the native controller wire predates
        the clock_probe RPC and degrades deterministically."""
        if self._client is None or not getattr(
                self._client, "clock_sync_supported", False):
            return
        if not (self.timeline.enabled or self._cfg.metrics_port or
                self._cfg.metrics_interval_explicit):
            return
        from ..obs.tracing import ClockSync, set_reference_clock

        if self._service is not None:
            set_reference_clock(self._rank, self.timeline)
            return
        self._clock_sync = ClockSync(
            addr, secret, world_id=world_id, rank=self._rank,
            timeline=self.timeline,
            interval_s=self._cfg.clock_sync_interval_s)
        self._clock_sync.start()

    # -- sub-buffer flush pipeline (docs/tensor-fusion.md) --------------------

    def _arm_flush_pipeline(self) -> None:
        """Build the overlap machinery (idempotent): a serial flush
        worker — execution keeps the negotiated order, the legality
        invariant — plus the controller client's dedicated data channel,
        so a flush parked in a payload/sentry rendezvous never holds the
        cycle connection (the two-channel deadlock). Degrades
        deterministically (warned once) where the pipeline cannot run:
        size-1 worlds negotiate in-process (nothing to overlap) and the
        native controller's binary wire predates the data-channel hello
        — the same degrade pattern as the cache-bit and metrics RPCs."""
        if self._flush_worker is not None:
            return
        if self._client is None or self._native_controller:
            if not self._pipeline_warned:
                self._pipeline_warned = True
                LOG.warning(
                    "HOROVOD_FUSION_SUBBUFFERS=%d ignored: sub-buffer "
                    "flush pipelining needs the Python controller wire in "
                    "a multi-process world (size-1 worlds negotiate "
                    "in-process; set HOROVOD_NATIVE_CONTROLLER=0 "
                    "otherwise). Keeping the single-flush path.",
                    self._subbuffers)
            self._subbuffers = 1
            return
        self._client.open_data_channel()
        self._flush_clock = _FlushClock()
        self._flush_worker = _DevicePlaneWorker()
        self._flush_worker._thread.name = "horovod-flush-pipeline"

    def _execute_flush(self, responses: List[Response], span_args,
                       cycle_no: int) -> None:
        """Flush-worker body: execute one negotiated sub-buffer's
        responses in order, bracketing the busy clock the loop thread
        reads the overlap off."""
        self._flush_clock.mark_start()
        try:
            for idx, resp in enumerate(responses):
                t_exec = time.monotonic()
                self._execute(idx, resp, span_args=span_args,
                              cycle_no=cycle_no)
                _EXECUTE_SECONDS.observe(time.monotonic() - t_exec)
        finally:
            self._flush_clock.mark_end()
            # flight recorder (docs/blackbox.md): flush lifecycle end,
            # keyed by the cycle the sub-buffer was negotiated under
            _flightrec.record(_flightrec.EV_FLUSH_END, cycle_no)

    # The coordinator retains a cycle's ResponseList (the payload
    # exchange's lookup table) for a 16-cycle sliding window
    # (ControllerService history). A slow in-flight flush — e.g. an
    # apply-fused batch compiling a fresh bucket program — must not let
    # the loop thread negotiate idle cycles past that window, or the
    # flush's own payload exchange KeyErrors on an expired cycle. Half
    # the window keeps a wide safety margin; throttling is symmetric
    # (cycles are a world rendezvous, so one throttled rank simply slows
    # the world's cycle count until its flush completes).
    _MAX_FLUSH_CYCLE_LAG = 8

    def _reap_flushes(self, block: bool = False) -> None:
        """Retire completed in-flight flushes in order; ``block=True``
        waits (abortably, like ``_device_call``) for the oldest one — the
        depth-cap path. A flush whose body raised re-raises HERE, on the
        loop thread, so the loop's crash path owns the teardown."""
        from concurrent.futures import TimeoutError as _FutTimeout

        while self._inflight:
            _, fut = self._inflight[0]
            if not fut.done() and not block:
                break
            if not fut.done():
                if self._abort_event.is_set():
                    raise RuntimeError(
                        self._abort_reason or SHUT_DOWN_ERROR)
                try:
                    fut.result(timeout=0.25)
                except _FutTimeout:
                    continue
            self._inflight.popleft()
            block = False
            fut.result()  # re-raise a failed flush into the loop
        _FLUSH_INFLIGHT.set(len(self._inflight))

    def _abandon_flushes(self, timeout_s: float = 15.0) -> None:
        """Teardown drain: give in-flight flushes a bounded window to
        finish (their handles must be marked by the worker, not
        double-flushed), then abandon — the worker is a daemon and the
        world is over."""
        deadline = time.monotonic() + timeout_s
        while self._inflight:
            _, fut = self._inflight.popleft()
            try:
                fut.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 - teardown: best effort
                pass
        _FLUSH_INFLIGHT.set(0)

    def overlap_stats(self) -> Dict[str, Any]:
        """Sub-buffer flush pipeline counters for tests, the dryrun
        certification, and bench reporting (zeros when single-flush)."""
        busy = self._flush_clock.busy_seconds() \
            if self._flush_clock is not None else 0.0
        return {
            "subbuffers": self._subbuffers,
            "pipelined": self._flush_worker is not None,
            "flushes": self._flush_count,
            "overlap_seconds": self._overlap_seconds,
            "execute_busy_seconds": busy,
            "inflight_peak": self._inflight_peak,
        }

    def _downgrade_codec(self, entry: TensorTableEntry, codec: str) -> str:
        """One rule for quantized-wire eligibility on the eager plane
        (shared by the plain and apply-fused allreduce paths): the
        decision reads only NEGOTIATED metadata (codec + dtype) and
        world-uniform state (plane presence), so every rank downgrades
        identically and compiled programs stay launch-order
        compatible."""
        if codec == "none":
            return codec
        if _is_sparse_codec(codec):
            # The sparse indices+values wire is float32-only by layout,
            # but unlike the quantized wire it has a REAL host-plane
            # transport (the coordinator's reference allgather combine),
            # so a plane-less world keeps the codec; only a non-f32
            # batch degrades — still decided from negotiated metadata.
            if dtype_of(entry.array) == DataType.FLOAT32:
                return codec
            if ("codec", codec) not in self._host_fallback_warned:
                self._host_fallback_warned.add(("codec", codec))
                LOG.warning(
                    "sparse allreduce (%s) requested for a non-float32 "
                    "batch; reducing dense at full precision (the "
                    "sparse wire's value block is float32 by layout).",
                    codec)
            return "none"
        if self._plane is not None and self._plane.supports_quantized(
                dtype_of(entry.array)):
            return codec
        if self._plane is None and \
                ("codec", codec) not in self._host_fallback_warned:
            self._host_fallback_warned.add(("codec", codec))
            LOG.warning(
                "quantized allreduce (%s) requested but the host "
                "TCP data plane is active; reducing at full "
                "precision (set HOROVOD_DATA_PLANE=xla for the "
                "quantized device wire).", codec)
        return "none"

    def _warn_host_fallback(self, op_name: str, tensor_name: str,
                            array: np.ndarray) -> None:
        """The device plane is active but this dtype must ride the host TCP
        plane — at pod scale that is orders of magnitude slower, so say so
        once per (op, dtype) instead of silently degrading."""
        key = (op_name, str(array.dtype))
        if key in self._host_fallback_warned:
            return
        self._host_fallback_warned.add(key)
        LOG.warning(
            "%s of %r (dtype %s) has no device-collective wire; falling "
            "back to the host TCP data plane, which is far slower at scale. "
            "Cast the tensor (e.g. to float32/int32) to keep it on-device.",
            op_name, tensor_name, array.dtype)

    def _on_world_abort(self, reason: str) -> None:
        """Watch-channel callback (daemon thread): record the reason and
        wake any device call parked in ``_device_call``. Fires on clean
        controller stop too — harmless, nothing is in a collective then."""
        self._abort_reason = reason
        self._abort_event.set()
        if reason and "stopping" not in reason:
            # flight recorder (docs/blackbox.md): a pushed world abort —
            # a rank parked inside a compiled collective may never reach
            # the loop's own teardown trigger, so ship the tail from
            # here too (idempotent once-flag in trigger_dump)
            _flightrec.trigger_dump(reason)

    def _device_call(self, fn, *args, worker=None):
        """Run a device-plane call abortably (see ``_DevicePlaneWorker``).
        Without a watch channel (size-1 worlds, host plane) it runs
        inline."""
        worker = worker or self._device_worker
        if worker is None:
            return fn(*args)
        if self._abort_event.is_set():
            raise RuntimeError(self._abort_reason or SHUT_DOWN_ERROR)
        from concurrent.futures import TimeoutError as _FutTimeout

        fut = worker.submit(fn, *args)
        while True:
            try:
                return fut.result(timeout=0.25)
            except _FutTimeout:
                if self._abort_event.is_set():
                    raise RuntimeError(
                        self._abort_reason or SHUT_DOWN_ERROR) from None

    def _finalize_loop(self) -> None:
        """Mark device-path handles done only when the dispatched XLA
        collective actually completed (reference completion semantics:
        CUDA events + finalizer thread). A peer death leaves the wait
        blocked forever on the sacrificial worker; the watch-channel abort
        unparks this loop, which fails the handles with SHUT_DOWN_ERROR."""
        import queue as _queue

        import jax

        while True:
            item = self._finalizer_q.get()
            if item is None:
                self._completion_worker.stop()
                return
            # Drain everything already queued and wait on the UNION: the
            # batches all executed concurrently under XLA's async dispatch,
            # so k sequential per-batch waits would add k completion
            # round-trips of pure latency (a measured 2.3x on the fusion
            # bench) for work that finishes together anyway.
            batch = [item]
            while True:
                try:
                    nxt = self._finalizer_q.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:  # keep the sentinel AFTER the drain
                    self._finalizer_q.put(None)
                    break
                batch.append(nxt)
            try:
                self._device_call(
                    jax.block_until_ready,
                    [r for _, res in batch for r in res],
                    worker=self._completion_worker)
                failed_union = False
            except Exception:  # noqa: BLE001 - isolate below
                # One bad computation must not poison sibling batches that
                # completed fine: fall back to per-batch waits so each
                # batch gets its own ok/error. (A world abort re-raises
                # immediately per batch — _device_call checks the abort
                # flag at entry — so the fallback stays fast then too.)
                failed_union = True
            for entries, results in batch:
                status = None
                if failed_union:
                    try:
                        self._device_call(jax.block_until_ready, results,
                                          worker=self._completion_worker)
                    except Exception as exc:  # noqa: BLE001
                        status = Status.unknown_error(str(exc))
                if status is not None:
                    for entry in entries:
                        try:
                            self.timeline.end(entry.name)
                        except Exception:  # noqa: BLE001
                            pass
                        self.handles.mark_done(entry.handle, status, None)
                    continue
                for entry, result in zip(entries, results):
                    # mark_done is the load-bearing call: a timeline hiccup
                    # must never leave a completed handle unmarked (a
                    # waiter would hang forever on it)
                    try:
                        self.timeline.end(entry.name, shape=result.shape)
                    except Exception:  # noqa: BLE001
                        pass
                    self.handles.mark_done(entry.handle, Status.ok(),
                                           result)

    # -- submission (API threads) --------------------------------------------

    def enqueue(self, op: RequestType, array: np.ndarray, name: str,
                root_rank: int = -1, codec: str = "none",
                apply: Optional[ApplyContext] = None) -> int:
        """EnqueueTensor* (``operations.cc:2472-2591``): duplicate names are
        rejected while the previous submission is still in flight, as the
        reference's tensor_table emplace does."""
        dtype_of(array)  # validate wire dtype early
        if codec != "none" and self._native_controller:
            # The native controller's fixed binary wire has no codec slot,
            # so quantized negotiation metadata cannot reach the
            # coordinator. Deterministic on every rank (the native
            # decision is config-driven and rank-identical): fall back to
            # the full-precision wire rather than risk divergent batches.
            if codec not in self._host_fallback_warned:
                self._host_fallback_warned.add(codec)
                LOG.warning(
                    "compressed allreduce (%s) is not carried by the "
                    "native controller wire; reducing dense at full "
                    "precision. Set HOROVOD_NATIVE_CONTROLLER=0 to use "
                    "the compressed eager data plane.", codec)
            codec = "none"
        with self._lock:
            if self._stop_requested:
                raise RuntimeError(SHUT_DOWN_ERROR)
            in_flight = {e.name for e in self._submissions} | set(self._pending)
            if name in in_flight:
                raise ValueError(
                    f"Requested to {_OP_NAMES[op]} a tensor with the same "
                    f"name as another tensor that is currently being "
                    f"processed: {name}. Synchronize the outstanding handle "
                    f"first or pass a unique name.")
            handle = self.handles.allocate()
            entry = TensorTableEntry(name=name, op=op, array=array,
                                     handle=handle, root_rank=root_rank,
                                     codec=codec, apply=apply)
            self._submissions.append(entry)
        # flight recorder (docs/blackbox.md): submission lifecycle start
        _flightrec.record(_flightrec.EV_ENQUEUE, detail=name)
        self.timeline.negotiate_start(name, _OP_NAMES[op])
        # No wake: submissions ride the next cycle tick, preserving the
        # reference's fusion window (HOROVOD_CYCLE_TIME batches arrivals,
        # ``operations.cc:2030-2060``). Only shutdown wakes the loop early.
        return handle

    # -- background loop ------------------------------------------------------

    def _loop(self) -> None:
        cycle_s = max(self._cfg.cycle_time_ms, 0.1) / 1000.0
        try:
            while True:
                self._wake.wait(timeout=cycle_s)
                self._wake.clear()
                self.timeline.mark_cycle_start()
                cycle_t0 = time.monotonic()
                stop = self._stop_requested
                with self._lock:
                    new_entries, self._submissions = self._submissions, []
                    for entry in new_entries:
                        self._pending[entry.name] = entry
                if self._flush_worker is not None:
                    if not stop:
                        cycle_s, stop_loop = self._pipelined_tick(
                            new_entries, cycle_s)
                        if stop_loop:
                            break
                        continue
                    # The shutdown cycle takes the single-flush path
                    # below; drain the pipeline first so its payload
                    # exchanges complete before the drain negotiation
                    # reaches the coordinator (and so a failed flush
                    # surfaces through the crash path, not silently).
                    while self._inflight:
                        self._reap_flushes(block=True)
                requests = [self._request_of(e) for e in new_entries]
                request_list = RequestList(
                    rank=self._rank, requests=requests, shutdown=stop)
                if self._negotiator is not None:
                    self._negotiator.add_request_list(request_list)
                    response_list = self._negotiator.construct_response_list()
                    self._local_cycle_no += 1
                else:
                    assert self._client is not None
                    response_list = self._cycle_with_cache(
                        request_list, requests, stop)
                self._span_args = self._cycle_span_args(response_list)
                for idx, resp in enumerate(response_list.responses):
                    t_exec = time.monotonic()
                    self._execute(idx, resp)
                    _EXECUTE_SECONDS.observe(time.monotonic() - t_exec)
                # registry deltas as timeline counter tracks (no-op when
                # the timeline is disabled — one attribute check)
                self._metrics_bridge.emit()
                # autotune: local worlds score here; multi-process worlds
                # score on the coordinator and ship cycle time back
                if self._negotiator is not None and self._autotuner is not None:
                    active_us = (time.monotonic() - cycle_t0) * 1e6
                    tuned = self._autotuner.observe_cycle(
                        response_list, active_us=active_us)
                    if tuned is not None:
                        self._negotiator.set_fusion_threshold(
                            int(tuned.config["fusion_threshold_bytes"]))
                        cycle_s = max(
                            float(tuned.config["cycle_time_ms"]),
                            0.1) / 1000.0
                        self._audit_knobs(dict(
                            tuned.config, action=tuned.action))
                elif response_list.tuned_cycle_ms is not None:
                    new_cycle_s = max(response_list.tuned_cycle_ms,
                                      0.1) / 1000.0
                    if new_cycle_s != cycle_s:
                        self._audit_knobs({"cycle_time_ms":
                                           response_list.tuned_cycle_ms})
                    cycle_s = new_cycle_s
                if response_list.shutdown:
                    if response_list.abort_reason:
                        # Escalated shutdown (stall deadline): flush with
                        # the structured reason so waiters raise
                        # RanksAbortedError naming the missing ranks.
                        self._shutdown_reason = response_list.abort_reason
                    break
        except Exception as exc:  # noqa: BLE001 - propagate to handles
            LOG.error("background loop failed: %s", exc)
            # A dead control plane (coordinator gone, peer died and the
            # abort raced teardown) IS a world shutdown: surface the
            # reference's SHUT_DOWN_ERROR semantics, keeping the transport
            # detail as the cause (``operations.cc:1942-1957``).
            reason = str(exc)
            if "shut down" not in reason:
                reason = f"{SHUT_DOWN_ERROR} (cause: {reason})"
            self._stop_requested = True  # before the flush: an enqueue
            # racing it must be rejected, not parked on a dead loop
            self._crashed = True  # teardown ordering differs, see finally
            if self._shutdown_reason is None:
                # post-mortem ops (get_engine on the stopped singleton)
                # surface this same structured reason
                self._shutdown_reason = reason
            # In-flight sub-buffer flushes first (bounded): their entries
            # must be marked by the worker OR by the outstanding flush
            # below, never raced between the two.
            self._abandon_flushes()
            self._flush_outstanding(Status.unknown_error(reason))
        finally:
            self._stop_requested = True
            if self._shutdown_reason:
                # Flight recorder (docs/blackbox.md): an escalated
                # shutdown or loop crash — ship this rank's black-box
                # tail while the coordinator is still reachable (the
                # service teardown below). Clean negotiated shutdowns
                # leave _shutdown_reason None and dump nothing.
                _flightrec.trigger_dump(self._shutdown_reason)
            self._abandon_flushes()
            if self._clock_sync is not None:
                self._clock_sync.stop()
            if self._metrics_stop is not None:
                self._metrics_stop.set()  # publisher drains before teardown
            self._flush_outstanding(Status.unknown_error(
                self._shutdown_reason or SHUT_DOWN_ERROR))
            crashed = getattr(self, "_crashed", False)
            if not crashed and self._finalizer_q is not None:
                # Clean shutdown: drain still-completing device batches
                # BEFORE the control plane goes away. (FIFO: the sentinel
                # lands behind them; the finalizer stops its own worker —
                # stopping it here could strand an unsubmitted batch.)
                self._finalizer_q.put(None)
                self._finalizer.join(timeout=15.0)
            if self._metrics_thread is not None:
                # Final-flush rendezvous (docs/metrics.md): the stop event
                # wakes the publisher, which pushes one last snapshot so
                # the final partial interval isn't silently lost. Join
                # BEFORE the client/service teardown below — the bounded
                # timeout is what keeps the flush best-effort rather than
                # a shutdown hazard (the thread is a daemon; an overrun
                # push is abandoned, never waited out).
                self._metrics_thread.join(timeout=3.0)
            if self._client is not None:
                # Never a clean detach: after a negotiated shutdown the
                # controller ignores the drop anyway, and on the crash path
                # the drop is precisely what tells it this rank died.
                self._client.close(detach=False)
            if self._subcoord is not None:
                # Island head duty: before the root service (rank 0 hosts
                # both) so the head's upstream farewell can still land.
                self._subcoord.shutdown()
            if self._standby_subcoord is not None:
                # A never-activated standby farewells nothing (it holds
                # no upstream channels); an activated one farewells like
                # the primary it replaced.
                self._standby_subcoord.shutdown()
            if self._service is not None:
                self._service.shutdown()
            if self._autotuner is not None:
                self._autotuner.close()
            timeline_safe = True
            if self._finalizer_q is not None:
                if crashed:
                    # Crash path: teardown first (the client drop IS the
                    # death signal to peers — a 15 s drain would delay the
                    # world abort), then drain; the watch-channel abort
                    # unparks a finalizer stuck in a dead collective.
                    self._finalizer_q.put(None)
                    self._finalizer.join(timeout=15.0)
                # Close the timeline only once the finalizer is done: it
                # emits timeline events, and the native writer's close
                # frees the C++ handle (a later write is a use-after-free).
                timeline_safe = not self._finalizer.is_alive()
            if self._device_worker is not None:
                # best-effort: a worker blocked in a dead collective never
                # consumes the sentinel, but it is a daemon thread
                self._device_worker.stop()
            if self._flush_worker is not None:
                # joined bounded (unlike the device worker): the flush
                # worker runs compiled apply programs on the host plane,
                # and leaving it frozen mid-C++ at interpreter exit
                # aborts in jaxlib teardown (see _DevicePlaneWorker.stop)
                self._flush_worker.stop(join_timeout_s=3.0)
            if timeline_safe:
                self.timeline.close()
            else:
                LOG.warning(
                    "finalizer still completing at shutdown; leaving the "
                    "timeline writer open to avoid a write-after-free")
            # after the trigger above: a clean world's later structured
            # raises (tests constructing errors) must not dump against a
            # stale context
            _flightrec.disarm_push()
            self._stopped.set()

    def _pipelined_tick(self, new_entries: List[TensorTableEntry],
                        cycle_s: float):
        """One wake tick under the sub-buffer flush pipeline
        (docs/tensor-fusion.md): cut the drained queue into
        generation-ordered sub-buffers, negotiate each as its own cycle,
        and hand execution to the flush worker — so the NEXT sub-buffer's
        negotiation (a cache-bit vector in steady state) runs while the
        previous one's allreduce is still in flight. Depth is capped at
        the sub-buffer count; an idle tick still negotiates one empty
        cycle (the heartbeat every rank owes every cycle). Returns
        ``(cycle_s, stop_loop)``."""
        batches = cut_generations(new_entries, self._subbuffers) or [[]]
        response_list = None
        for sub in batches:
            self._reap_flushes()  # fail fast on a crashed flush
            while len(self._inflight) >= self._subbuffers or (
                    self._inflight and
                    self._client.last_cycle - self._inflight[0][0]
                    >= self._MAX_FLUSH_CYCLE_LAG):
                self._reap_flushes(block=True)
            requests = [self._request_of(e) for e in sub]
            request_list = RequestList(rank=self._rank, requests=requests,
                                       shutdown=False)
            busy0 = self._flush_clock.busy_seconds()
            response_list = self._cycle_with_cache(request_list, requests,
                                                   False)
            # the achieved overlap: flush-worker busy seconds inside this
            # negotiation's wall window (exact — busy intervals are
            # disjoint on the single worker thread)
            overlap = self._flush_clock.busy_seconds() - busy0
            if overlap > 0:
                _OVERLAP_SECONDS.inc(overlap)
                self._overlap_seconds += overlap
            span_args = self._cycle_span_args(response_list)
            self._span_args = span_args
            if response_list.responses:
                cycle_no = self._client.last_cycle
                fut = self._flush_worker.submit(
                    self._execute_flush, list(response_list.responses),
                    span_args, cycle_no)
                self._inflight.append((cycle_no, fut))
                self._flush_count += 1
                _SUBBUFFER_FLUSHES.inc()
                depth = len(self._inflight)
                # flight recorder (docs/blackbox.md): flush dispatch with
                # its cycle ordinal + the in-flight depth it joined
                _flightrec.record(_flightrec.EV_FLUSH_START, cycle_no,
                                  aux=depth)
                _FLUSH_INFLIGHT.set(depth)
                if depth > self._inflight_peak:
                    self._inflight_peak = depth
                    _FLUSH_INFLIGHT_PEAK.set(depth)
                if self.timeline.enabled:
                    self.timeline.counter("flush_inflight",
                                          {"inflight": depth})
            if response_list.shutdown:
                break
        self._metrics_bridge.emit()
        if response_list.tuned_cycle_ms is not None:
            new_cycle_s = max(response_list.tuned_cycle_ms, 0.1) / 1000.0
            if new_cycle_s != cycle_s:
                self._audit_knobs({"cycle_time_ms":
                                   response_list.tuned_cycle_ms})
            cycle_s = new_cycle_s
        if response_list.shutdown:
            if response_list.abort_reason:
                self._shutdown_reason = response_list.abort_reason
            self._abandon_flushes()
            return cycle_s, True
        return cycle_s, False

    def _cycle_span_args(self, response_list) -> Optional[dict]:
        """Cross-rank correlation stamps for this cycle's span records
        (docs/tracing.md): the cycle ordinal — every rank participates in
        every negotiation cycle exactly once and in order, so ordinal N
        names the SAME rendezvous in every per-rank trace file — plus the
        response-cache generation, which distinguishes replayed-layout
        cycles from renegotiated ones when reading a merged trace."""
        if not self.timeline.enabled:
            return None
        if self._client is not None:
            ordinal = self._client.last_cycle
        else:
            ordinal = self._local_cycle_no - 1
        args = {"cycle": ordinal}
        generation = getattr(response_list, "cache_generation", None)
        if generation is not None:
            args["cache_generation"] = generation
        return args

    def _cycle_with_cache(self, request_list: RequestList,
                          requests: List[Request], stop: bool):
        """One controller round trip, through the steady-state bypass when
        the whole cycle hits the response cache (docs/response-cache.md):
        ship a fixed-size cache-bit vector instead of the RequestList and,
        on an all-ranks hit, replay the cached fused responses from the
        coordinator's compact ack. A shutdown cycle always takes the full
        path — the drain negotiation must reach the coordinator as-is."""
        from .messages import CacheHitAck, CacheRequest
        from .response_cache import bits_of

        cache = self._response_cache
        positions = None
        if cache is not None and self._cache_confirmed and not stop:
            positions = cache.plan_cycle(requests)
        # consensus digests ride whichever message actually ships this
        # cycle — the warm steady state must keep verifying too
        # (docs/integrity.md)
        digests = self._drain_digests()
        if positions is not None:
            out = self._client.cycle(self._rank, CacheRequest(
                rank=self._rank, bits=bits_of(positions, cache.capacity),
                generation=cache.generation, integrity_digest=digests))
        else:
            request_list.integrity_digest = digests
            out = self._client.cycle(self._rank, request_list)
        if isinstance(out, CacheHitAck):
            response_list = ResponseList(
                responses=cache.accept_ack(out),
                tuned_cycle_ms=out.tuned_cycle_ms,
                stall_warnings=out.stall_warnings,
                stall_check=out.stall_check,
                # carried for the span stamps (_cycle_span_args): an
                # all-hit cycle's trace must still say which cache
                # generation it replayed under
                cache_generation=out.generation)
        else:
            response_list = out
            if cache is not None:
                if getattr(response_list, "cache_generation", None) is None:
                    # The coordinator runs without a cache (capacity knob
                    # diverged, or a pre-cache service): planning bypasses
                    # against it could only fail loudly later — disable.
                    LOG.warning(
                        "coordinator carries no response-cache generation; "
                        "disabling the rank-side cache "
                        "(HOROVOD_CACHE_CAPACITY should resolve "
                        "identically on every rank).")
                    self._response_cache = None
                else:
                    self._cache_confirmed = True
                    with self._lock:
                        in_flight = {name: self._request_of(e)
                                     for name, e in self._pending.items()}
                    cache.accept_response_list(response_list, in_flight)
        self._apply_tuned_knobs(out)  # list or ack: both carry the map
        self._emit_cache_counters()
        return response_list

    def _apply_tuned_knobs(self, msg) -> None:
        """Apply the coordinator's piggybacked extended-knob map
        (docs/autotune.md). Runs on the engine loop thread AFTER the
        cycle's cache processing: a capacity retune always arrives
        alongside the generation bump that cleared the cache, so resizing
        here can never orphan live positions — the next cycle plans its
        bitvector under the same capacity the coordinator now holds.
        Idempotent per value; audited on change via timeline AUTOTUNE
        metadata."""
        knobs = getattr(msg, "tuned_knobs", None)
        if not knobs:
            return
        changed = {}
        capacity = knobs.get("cache_capacity")
        if capacity is not None and self._response_cache is not None and \
                int(capacity) != self._response_cache.capacity:
            self._response_cache.capacity = int(capacity)
            changed["cache_capacity"] = int(capacity)
        interval = knobs.get("metrics_interval_s")
        if interval is not None and \
                float(interval) != self._metrics_interval_s:
            self._metrics_interval_s = float(interval)
            changed["metrics_interval_s"] = float(interval)
        subbuffers = knobs.get("fusion_subbuffers")
        if subbuffers is not None and int(subbuffers) != self._subbuffers:
            # the overlap knob (docs/tensor-fusion.md): arms the pipeline
            # on first use (flush worker + data channel); the next tick
            # cuts by the new count. Arming runs on the loop thread —
            # exactly where a retune lands — so no in-flight flush can
            # observe a half-built pipeline.
            self._subbuffers = max(int(subbuffers), 1)
            if self._subbuffers > 1:
                self._arm_flush_pipeline()
            changed["fusion_subbuffers"] = self._subbuffers
        fused_apply = knobs.get("fused_apply")
        if fused_apply is not None and \
                bool(int(fused_apply)) != self._fused_apply_exec:
            if self._plane is not None:
                # On the XLA device plane the two strategies issue
                # DIFFERENT compiled collective programs (psum+apply vs
                # plain psum) for the same negotiated batch; a retune
                # lands on each rank's loop thread at its own moment, so
                # a mid-stream flip could desynchronize launch order
                # (the plane's byte-identical-programs invariant). The
                # strategy stays pinned at its init value there.
                self._warn_apply_once(
                    "tuned-exec-plane",
                    "fused_apply retune ignored on the XLA device "
                    "plane: the execution strategy changes the compiled "
                    "collective program and cannot flip mid-stream; "
                    "pin HOROVOD_FUSED_APPLY instead.")
            else:
                # Host TCP wire: the reduce exchange is byte-identical
                # in both strategies (the apply is rank-local compute),
                # so the flip is safe at any moment — numerics-exact by
                # the shared ApplyRule math; in-flight batches finish
                # under whichever mode they started.
                self._fused_apply_exec = bool(int(fused_apply))
                changed["fused_apply"] = int(fused_apply)
        codec = knobs.get("codec")
        if codec is not None and \
                codec != (self._applied_knobs.get("codec") or "none"):
            # audit only: the codec applies as a coordinator-side response
            # rewrite, never a rank-side request rule (ops/controller.py).
            # Never-seen == the "none" baseline, so the first extended map
            # does not fake a codec-change record in every rank's trace.
            changed["codec"] = codec
        if changed:
            self._applied_knobs.update(changed)
            self._audit_knobs(changed)

    def _audit_knobs(self, record: dict) -> None:
        """Timeline half of the decision audit (the registry half lives
        with the policy): one AUTOTUNE metadata record per change."""
        if self.timeline.enabled:
            from ..utils.timeline import AUTOTUNE

            try:
                self.timeline.meta(AUTOTUNE, dict(record))
            except Exception:  # noqa: BLE001 - audit must never kill a cycle
                pass

    def _emit_cache_counters(self) -> None:
        """Per-cycle bypass observability on the rank-0 timeline: hit/miss
        cycle totals and this cycle's negotiation wire bytes, as a Chrome
        counter track (satellite of docs/response-cache.md)."""
        cache = self._response_cache
        if cache is None or not self.timeline.enabled:
            return
        self.timeline.counter("response_cache", {
            "hit_cycles": cache.hit_cycles,
            "miss_cycles": cache.miss_cycles,
            "negotiation_tx_bytes": self._client.last_cycle_tx_bytes,
            "negotiation_rx_bytes": self._client.last_cycle_rx_bytes,
        })

    # -- data-plane integrity (docs/integrity.md) -----------------------------

    def _sentry_exchange(self, ordinal: int, bits: bytes) -> bytes:
        """Collective verdict fold: OR this batch's per-tensor finite
        bits across every rank through the controller rendezvous."""
        return self._client.sentry(self._rank, ordinal, bits)

    def _on_sentry_trip(self, record: dict) -> None:
        """Timeline half of the sentry audit (the registry half lives
        with the sentry): one INTEGRITY metadata record per trip."""
        if self.timeline.enabled:
            from ..utils.timeline import INTEGRITY

            try:
                self.timeline.meta(INTEGRITY, dict(record))
            except Exception:  # noqa: BLE001 - audit must not kill a batch
                pass

    def _screen_reduced(self, entries: List[TensorTableEntry],
                        results: List) -> List:
        """Integrity pipeline over one reduced allreduce batch: consensus
        digest FIRST (the bytes as received — a sentry rewrite is
        collective and identical on every rank, so digesting after it
        would mask exactly the divergence consensus exists to catch),
        then the sentry screen (which may zero the batch or raise
        ``NonFiniteGradError``)."""
        names = [e.name for e in entries]
        if self._consensus_acc is not None:
            self._consensus_acc.observe_batch(names, results)
        if self._sentry is not None:
            results = self._sentry.screen_batch(names, results)
        return results

    def _drain_digests(self):
        """Completed consensus windows for the next cycle message."""
        if self._consensus_acc is None:
            return None
        return self._consensus_acc.drain()

    def integrity_stats(self) -> Dict[str, Any]:
        """Sentry / consensus / data-chaos state for tests, the dryrun
        certification, and bench reporting (zeros when disarmed)."""
        return {
            "sentry": self._sentry.stats() if self._sentry else None,
            "consensus_windows": (self._consensus_acc.windows_emitted
                                  if self._consensus_acc else 0),
            "data_chaos_events": (list(self._data_chaos.events)
                                  if self._data_chaos else []),
        }

    def state_snapshot(self) -> Dict[str, Any]:
        """Engine state for the black-box incident dump and
        ``hvd.health_report()`` — one definition (docs/blackbox.md): the
        in-flight flush table, pending submissions, cache/apply/overlap
        counters, and the last tuned-knob map this rank applied. Safe to
        call from any thread at any time (a live poke must never perturb
        the loop): collections are copied under the engine lock where one
        exists, best-effort elsewhere."""
        with self._lock:
            pending = sorted(self._pending)
            queued = len(self._submissions)
        try:
            inflight = [cycle_no for cycle_no, _ in list(self._inflight)]
        except RuntimeError:  # deque mutated mid-copy: retry once, coarse
            inflight = [cycle_no for cycle_no, _ in list(self._inflight)]
        client = self._client
        return {
            "rank": self._rank,
            "size": self._size,
            "stopped": self._stopped.is_set(),
            "stop_requested": self._stop_requested,
            "crashed": getattr(self, "_crashed", False),
            "shutdown_reason": self._shutdown_reason,
            "abort_reason": self._abort_reason,
            "last_cycle": (client.last_cycle if client is not None
                           else max(self._local_cycle_no - 1, 0)),
            "pending_tensors": pending,
            "queued_submissions": queued,
            "inflight_flushes": inflight,
            "subbuffers": self._subbuffers,
            "cache": self.cache_stats(),
            "apply": self.apply_stats(),
            "overlap": self.overlap_stats(),
            "tensorwatch": (self._tensorwatch.stats()
                            if self._tensorwatch is not None else None),
            "applied_knobs": dict(self._applied_knobs),
            "native_controller": self._native_controller,
        }

    def cache_stats(self) -> Dict[str, int]:
        """Rank-side response-cache counters (zeros when disabled)."""
        if self._response_cache is None:
            return {"entries": 0, "capacity": 0, "generation": 0,
                    "hit_cycles": 0, "miss_cycles": 0}
        return self._response_cache.stats()

    def _request_of(self, entry: TensorTableEntry) -> Request:
        return Request(
            request_rank=self._rank,
            request_type=entry.op,
            tensor_name=entry.name,
            tensor_type=dtype_of(entry.array),
            tensor_shape=tuple(entry.array.shape),
            root_rank=entry.root_rank,
            codec=entry.codec,
            # negotiated like the codec; the native controller's binary
            # wire predates the field and simply drops it (the engine
            # then runs the split execution off its rank-side contexts)
            apply_fingerprint=(entry.apply.rule.fingerprint
                               if entry.apply is not None else ""),
        )

    def _flush_outstanding(self, status: Status) -> None:
        """All outstanding callbacks error out on shutdown
        (``operations.cc:1942-1957``)."""
        with self._lock:
            entries = list(self._pending.values()) + self._submissions
            self._pending.clear()
            self._submissions = []
        for entry in entries:
            self.handles.mark_done(entry.handle, status, None)

    # -- execution ------------------------------------------------------------

    def _execute(self, idx: int, resp: Response,
                 span_args: Optional[dict] = None,
                 cycle_no: Optional[int] = None) -> None:
        """PerformOperation (``operations.cc:768-1621``) for one response,
        possibly a fused allreduce batch.

        ``span_args``/``cycle_no`` are captured at negotiation time by the
        flush pipeline — executing on the worker thread, the client's
        "most recent cycle" may already be a LATER one, so the payload
        exchange and trace stamps must use the ordinal this response was
        negotiated under. The single-flush path leaves them None (the
        live values are correct there, execution being serialized behind
        negotiation)."""
        if span_args is None:
            span_args = self._span_args
        with self._lock:
            if resp.response_type == ResponseType.ERROR:
                # An escalated stall ERROR targets a tensor only SOME
                # ranks submitted (that is what a stall is); ranks
                # without a pending entry for it have nothing to mark.
                entries = [e for e in (self._pending.pop(n, None)
                                       for n in resp.tensor_names)
                           if e is not None]
            else:
                # Data responses keep the strict invariant: a batch
                # naming a tensor this rank never submitted is a
                # coordinator bug and must fail loudly here, not as a
                # short-handed payload rendezvous later.
                entries = [self._pending.pop(n) for n in resp.tensor_names]
        if not entries:
            return
        tl = self.timeline
        for entry in entries:
            # cycle-ordinal + cache-generation stamps: how the same
            # span is found across per-rank trace files (docs/tracing.md)
            tl.negotiate_end(entry.name, args=span_args)

        if resp.response_type == ResponseType.ERROR:
            status = Status.precondition_error(resp.error_message)
            for entry in entries:
                self.handles.mark_done(entry.handle, status, None)
            return

        op_name = _OP_NAMES[entries[0].op]
        for entry in entries:
            tl.start(entry.name, op_name, args=span_args)
        try:
            if resp.response_type == ResponseType.ALLREDUCE:
                if any(e.apply is not None for e in entries):
                    # apply-capable batch: land applied parameters and
                    # fresh optimizer slots, not gradients
                    # (docs/tensor-fusion.md §fused apply); the path
                    # owns its own consensus/sentry interplay
                    results = self._run_reduce_apply(idx, entries, resp,
                                                     cycle_no=cycle_no)
                else:
                    results = self._run_allreduce(
                        idx, entries,
                        getattr(resp, "tensor_codec", "none"),
                        cycle_no=cycle_no)
                    if self._sentry is not None or \
                            self._consensus_acc is not None:
                        results = self._screen_reduced(entries, results)
            elif resp.response_type == ResponseType.ALLGATHER:
                results = self._run_allgather(idx, entries[0], resp,
                                              cycle_no=cycle_no)
            else:
                results = self._run_broadcast(idx, entries[0], resp,
                                              cycle_no=cycle_no)
            if self._finalizer_q is not None and any(
                    _is_jax_array(r) for r in results):
                # Device results are asynchronous dispatches, not completed
                # collectives: the finalizer marks these handles when the
                # device work finishes (or the world aborts).
                self._finalizer_q.put((entries, results))
            else:
                for entry, result in zip(entries, results):
                    tl.end(entry.name, shape=result.shape)
                    self.handles.mark_done(entry.handle, Status.ok(), result)
        except Exception as exc:  # noqa: BLE001
            from ..runner.network import WireError

            reason = str(exc)
            if isinstance(exc, (WireError, OSError)) and \
                    "shut down" not in reason:
                # Control-plane loss mid-exchange == world shutdown (see
                # the equivalent mapping in _loop); genuine op errors keep
                # their own message.
                reason = f"{SHUT_DOWN_ERROR} (cause: {reason})"
            for entry in entries:
                tl.end(entry.name)
                self.handles.mark_done(
                    entry.handle, Status.unknown_error(reason), None)

    def _run_allreduce(self, idx: int, entries: List[TensorTableEntry],
                       codec: str = "none",
                       cycle_no: Optional[int] = None) -> List[np.ndarray]:
        fused = len(entries) > 1
        tl = self.timeline
        chaos = self._data_chaos
        if chaos is not None:
            # data-plane fault ordinals count allreduce BATCHES in
            # negotiated execution order — identical on every rank, so
            # nan@rankN:msgK replays bit-identically (docs/integrity.md).
            # Armed once per batch regardless of which path runs it; the
            # device-resident (onchip) path carries no host-side buffer
            # boundary and injects nothing, but still advances the
            # ordinal so mixed-path worlds stay aligned.
            chaos.begin_batch()
        watch = self._tensorwatch
        if watch is not None:
            # numerics observatory (docs/tensorwatch.md): the sampling
            # ordinal advances per allreduce batch in negotiated
            # execution order — rank-identical, like the sentry's
            watch.begin_batch()
        # Quantized wire eligibility is decided from NEGOTIATED batch
        # metadata (codec + dtype), identical on every rank, so the
        # compiled collective programs stay launch-order compatible.
        # Ineligible dtypes and plane-less (host TCP) worlds deterministically
        # ride the full-precision wire.
        codec = self._downgrade_codec(entries[0], codec)
        if _is_sparse_codec(codec):
            # Top-k sparse wire (docs/compression.md §sparse): its own
            # select → gather → scatter-decode route; the branch reads
            # only the negotiated codec, identical on every rank.
            return self._run_sparse_allreduce(idx, entries, codec,
                                              cycle_no=cycle_no)
        device_in = all(_is_jax_array(e.array) for e in entries)
        if device_in and self._client is None:
            # World of one, device tensors: sum over a single rank without
            # leaving the device. entry.array is already a private
            # on-device snapshot (see ops._submit), so returning it cannot
            # alias — or be invalidated by — any caller buffer.
            results = []
            for e in entries:
                tl.activity_start(e.name, "EXECUTE")
                results.append(e.array)
                tl.activity_end(e.name)
            if watch is not None and watch.sampling:
                watch.observe_batch([e.name for e in entries],
                                    [e.array for e in entries],
                                    results, codec)
            return results
        if device_in and self._plane is not None and \
                self._plane.supports(dtype_of(entries[0].array)):
            # All-device batch on the XLA plane: pack → psum → unpack with
            # zero host transfers (the analog of the reference's tensors
            # staying on-GPU through the NCCL fusion buffer).
            for e in entries:
                tl.activity_start(e.name, "EXECUTE")
            results = self._device_call(self._plane.allreduce_onchip,
                                        [e.array for e in entries], codec)
            for e in entries:
                tl.activity_end(e.name)
            if watch is not None and watch.sampling:
                # device route: the observatory's compiled probes sync
                # scalars off these arrays, no buffer D2H
                watch.observe_batch([e.name for e in entries],
                                    [e.array for e in entries],
                                    results, codec)
            return results
        if fused:
            for e in entries:
                tl.activity_start(e.name, "MEMCPY_IN_FUSION_BUFFER")
            # np.asarray is the lazy D2H for any jax entries mixed into a
            # host-path batch
            buf = np.concatenate([np.asarray(e.array).ravel()
                                  for e in entries])
            for e in entries:
                tl.activity_end(e.name)
        else:
            buf = np.asarray(entries[0].array).ravel()
        if chaos is not None:
            # the host-side fused-buffer boundary (docs/integrity.md):
            # nan faults poison a COPY of the local input here, before
            # the reduce — never the caller's array
            buf = chaos.on_reduce_input(buf)
        for e in entries:
            tl.activity_start(e.name, "EXECUTE")
        if self._plane is not None and self._plane.supports(dtype_of(buf)):
            # Preferred whenever a device plane exists — including the
            # explicit size-1 plane, where the single-rank psum is how the
            # eager path's bytes actually traverse the chip.
            out = self._device_call(self._plane.allreduce,
                                    np.ascontiguousarray(buf), codec)
        elif self._client is None:
            # world of one: sum over a single rank. Copy so results never
            # alias the caller's input array.
            out = np.array(buf, copy=True)
        else:
            if self._plane is not None:
                self._warn_host_fallback("allreduce", entries[0].name, buf)
            raw = self._client.payload(self._rank, idx,
                                       np.ascontiguousarray(buf).tobytes(),
                                       cycle_no=cycle_no)
            out = np.frombuffer(raw, dtype=buf.dtype).copy()  # writable
        if chaos is not None:
            # flipbits faults corrupt THIS rank's received reduced buffer
            # — the silent single-rank divergence consensus digests exist
            # to catch (docs/integrity.md)
            out = chaos.on_reduce_output(out)
        for e in entries:
            tl.activity_end(e.name)
        results = []
        offset = 0
        if fused:
            for e in entries:
                tl.activity_start(e.name, "MEMCPY_OUT_FUSION_BUFFER")
        for e in entries:
            n = e.array.size
            results.append(out[offset:offset + n].reshape(e.array.shape))
            offset += n
        if fused:
            for e in entries:
                tl.activity_end(e.name)
        if watch is not None and watch.sampling:
            # observed as RECEIVED, pre-sentry (the consensus framing):
            # a sentry rewrite is downstream of this measurement
            watch.observe_batch([e.name for e in entries],
                                [e.array for e in entries], results,
                                codec)
        return results

    def _run_sparse_allreduce(self, idx: int,
                              entries: List[TensorTableEntry],
                              codec: str,
                              cycle_no: Optional[int] = None) -> List:
        """Fused allreduce over the top-k sparse indices+values wire
        (docs/compression.md §sparse): per-tensor top-k selection of
        this rank's contribution (+ carried error-feedback residual),
        the pairs shipped over the reference allgather shape — the
        coordinator concatenates equal-K rank payloads; the XLA plane
        runs two tiled all_gathers per entry — and scatter-added back
        to the dense SUM on every rank.  Dropped mass lands in the
        per-tensor residual (``self._sparse_residuals``) and re-enters
        the next step's selection, which is what preserves convergence.

        Consensus digests the DECODED DENSE result: the rank side via
        ``_screen_reduced`` over these results, the coordinator side
        via the same ``sparse_wire.decode_sum`` over the combined
        payload — bit-identical float scatter order by construction."""
        import math as _math

        from . import sparse_wire
        from .compression import Compression

        tl = self.timeline
        chaos = self._data_chaos
        watch = self._tensorwatch
        comp = Compression.lookup(codec)
        feedback = self._sparse_error_feedback
        epoch = basics.world_epoch()
        if epoch != self._sparse_epoch:
            # elastic relaunch: the restored world restarted from
            # committed state, so replaying pre-relaunch residuals would
            # double-count the mass they carry
            self._sparse_residuals.clear()
            self._sparse_epoch = epoch
        fused = len(entries) > 1
        names = [e.name for e in entries]
        if self._plane is not None:
            # Device plane (host-fed entries ride it too, like the dense
            # path): compiled per-entry select/decode around the shared
            # tiled all_gather program — no full-buffer D2H, residuals
            # stay device-resident. Plane presence is world-uniform
            # (the XLA plane requires one JAX process per rank), so
            # every rank issues the same collective sequence.
            for e in entries:
                tl.activity_start(e.name, "EXECUTE")
            residuals = [self._sparse_residuals.get(e.name)
                         if feedback else None for e in entries]
            results, new_res, stats = self._device_call(
                self._plane.sparse_allreduce_onchip,
                [e.array for e in entries], residuals, comp, feedback)
            if feedback:
                for e, r in zip(entries, new_res):
                    self._sparse_residuals[e.name] = r
            sparse_wire.account_batch(
                stats["selected"], stats["dropped"], stats["wire_bytes"],
                _math.sqrt(stats["residual_norm2"]), "onchip")
            for e in entries:
                tl.activity_end(e.name)
            if watch is not None and watch.sampling:
                watch.observe_batch(names, [e.array for e in entries],
                                    results, codec)
            return results
        # Host path: numpy select over the fused corrected buffer, the
        # wire over the coordinator's payload exchange (or local for a
        # world of one — still lossy, the codec's semantics don't change
        # with world size).
        spans, off = [], 0
        for e in entries:
            n = int(e.array.size)
            spans.append((off, n))
            off += n
        n_dense = off
        if fused:
            for e in entries:
                tl.activity_start(e.name, "MEMCPY_IN_FUSION_BUFFER")
        parts = []
        for e, (start, n) in zip(entries, spans):
            flat = np.asarray(e.array).ravel().astype(np.float32,
                                                      copy=False)
            if feedback:
                r = self._sparse_residuals.get(e.name)
                if r is not None:
                    flat = flat + r
            parts.append(flat)
        buf = np.concatenate(parts) if fused \
            else np.ascontiguousarray(parts[0])
        if fused:
            for e in entries:
                tl.activity_end(e.name)
        if chaos is not None:
            # nan faults poison a COPY of the local input pre-selection,
            # the same boundary as the dense path (docs/integrity.md)
            buf = chaos.on_reduce_input(buf)
        for e in entries:
            tl.activity_start(e.name, "EXECUTE")
        idx_parts, val_parts = [], []
        new_res: Dict[str, np.ndarray] = {}
        k_total = 0
        res_norm2 = 0.0
        for e, (start, n) in zip(entries, spans):
            seg = buf[start:start + n]
            k = comp.k_of(n)
            sidx, svals = sparse_wire.topk_select(seg, k)
            idx_parts.append(
                (sidx.astype(np.int64) + start).astype(np.int32))
            val_parts.append(svals)
            if feedback:
                r = np.array(seg, dtype=np.float32, copy=True)
                r[sidx] = 0.0
                new_res[e.name] = r
                res_norm2 += float(np.dot(r, r))
            k_total += k
        payload = sparse_wire.pack_pairs(np.concatenate(idx_parts),
                                         np.concatenate(val_parts))
        if self._client is None:
            combined, size = payload, 1
        else:
            combined = self._client.payload(self._rank, idx, payload,
                                            cycle_no=cycle_no)
            size = self._size
        g_idx, g_vals = sparse_wire.unpack_wire(combined, size)
        if chaos is not None:
            # flipbits faults corrupt THIS rank's received sparse INDEX
            # stream — a flipped index lands mass on the wrong row, the
            # decoded-dense divergence the consensus digests exist to
            # catch (docs/integrity.md; residual bookkeeping above used
            # the ORIGINAL selected indices, never the flipped ones)
            g_idx = chaos.on_sparse_indices(g_idx)
        out = sparse_wire.scatter_sum(g_idx, g_vals, n_dense)
        if feedback:
            # commit only after a successful exchange: a wire failure
            # must not half-advance the residual state
            self._sparse_residuals.update(new_res)
        sparse_wire.account_batch(k_total, n_dense - k_total,
                                  len(payload), _math.sqrt(res_norm2),
                                  "host")
        for e in entries:
            tl.activity_end(e.name)
        results = []
        if fused:
            for e in entries:
                tl.activity_start(e.name, "MEMCPY_OUT_FUSION_BUFFER")
        for e, (start, n) in zip(entries, spans):
            results.append(out[start:start + n].reshape(e.array.shape))
        if fused:
            for e in entries:
                tl.activity_end(e.name)
        if watch is not None and watch.sampling:
            watch.observe_batch(names, [e.array for e in entries],
                                results, codec)
        return results

    # -- fused reduce+apply (docs/tensor-fusion.md §fused apply) --------------

    def _warn_apply_once(self, key: str, msg: str, *args) -> None:
        if ("apply", key) in self._host_fallback_warned:
            return
        self._host_fallback_warned.add(("apply", key))
        LOG.warning(msg, *args)

    def _apply_leaf(self, ctx: ApplyContext, reduced) -> ApplyResult:
        """Split-path per-leaf apply: ONE jitted program per leaf — the
        same ``bucket_apply_fn`` family the fused route compiles over
        the whole bucket, so split and fused are bit-identical by
        construction (the update is elementwise; XLA's within-program
        op fusion is shape-independent, pinned by the twin tests). The
        average divide rides in-program (``denom``), gate off: the
        sentry already screened the reduced batch at full tensor
        granularity on this route."""
        from .fused_apply import bucket_apply_fn

        denom = self._size if ctx.average and self._size > 1 else 1
        out = bucket_apply_fn(ctx.rule, False, denom)(
            reduced, ctx.param, np.int32(ctx.count), *ctx.slots)
        self._apply_counts["dispatches"] += 1
        _APPLY_DISPATCHES.inc()
        return ApplyResult(out[0], tuple(out[3:]))

    def _run_reduce_apply(self, idx: int, entries: List[TensorTableEntry],
                          resp: Response,
                          cycle_no: Optional[int] = None) -> List:
        """Execute one apply-capable allreduce batch: the flush lands
        APPLIED parameters and fresh optimizer slots (``ApplyResult``)
        instead of reduced gradients.

        Two strategies, numerics-identical by the shared ``ApplyRule``
        math:

        * **fused** — ONE compiled reduce+apply dispatch per batch: on
          the device plane the psum (or quantized decode), loss-scale
          unscale, nonfinite census, and leaf update compile into a
          single donated program (``XlaDataPlane.reduce_apply``); on the
          host plane the TCP exchange reduces and one bucket program
          applies. Requires the negotiated ``Response.fused_apply``
          kind — the Python controller's guarantee that the batch is
          rule-uniform on every rank.
        * **split** — the reduce exactly as a plain batch (full sentry
          tensor granularity included), then one jitted apply per leaf:
          the degrade for the native controller wire (which predates
          the fingerprint field), mixed batches, non-uniform step
          counts, and the ``fused_apply`` tuned knob's 0 position.

        Consensus digests the reduced bytes PRE-apply on both routes;
        the sentry's verdict exchange runs per batch on both routes, at
        batch granularity under fused (the in-program census gate
        already made a poisoned step a collective no-op)."""
        codec = getattr(resp, "tensor_codec", "none")
        ctxs = [e.apply for e in entries]
        fingerprint = getattr(resp, "fused_apply", "")
        # rank-identical by construction: apply contexts are a
        # deterministic function of replicated front-end state (same
        # tensors, same step counts, same average flag on every rank),
        # the fingerprint rides the negotiated response, and the exec
        # flag is init-pinned on the device plane — so every rank takes
        # the same fused/split branch for the same batch
        uniform = all(c is not None for c in ctxs) and len(
            {(c.rule.fingerprint, c.count, c.average)
             for c in ctxs if c is not None}) == 1
        # ZeRO-1 batch (docs/sharding.md): every context carries shard
        # slots and the init-pinned capability is armed. A MIXED batch
        # (some shard, some full) is a submission bug — shard slots
        # cannot take the split path (their shapes are 1/N of the leaf),
        # so it must fail loudly, never degrade.
        zero1 = self._zero1_exec and uniform and \
            all(c.zero1 for c in ctxs)
        if not zero1 and any(c is not None and c.zero1 for c in ctxs):
            raise RuntimeError(
                f"ZeRO-1 batch cannot execute: zero1 submissions mixed "
                f"with non-zero1 contexts or the capability is unarmed "
                f"(exec={self._zero1_exec}) for batch "
                f"{[e.name for e in entries]}")
        fused = bool(fingerprint) and uniform and self._fused_apply_exec
        if zero1 and not fused and uniform and self._fused_apply_exec:
            # the native controller wire predates the fingerprint field,
            # so its responses cannot negotiate the fused kind — but a
            # zero1 batch has no split fallback (shard slots), and the
            # rank-side uniformity decision is deterministic and
            # rank-identical (same replicated front-end state, same
            # init-pinned flags), so arming fused here is safe on every
            # rank at once.
            self._warn_apply_once(
                "zero1-wire",
                "ZeRO-1 batch on a controller wire without the apply "
                "fingerprint field: arming the fused route from "
                "rank-side uniformity (deterministic on every rank).")
            fused = True
        if fused and _is_sparse_codec(
                getattr(resp, "tensor_codec", "none")):
            if zero1:
                # shard slots cannot take the split path the sparse
                # downgrade needs; apply_step's fusable gate keeps
                # sparse codecs off the zero1 route, so reaching here
                # means a mid-run codec change — fail loudly.
                raise RuntimeError(
                    "ZeRO-1 batches cannot ride a sparse (top-k) codec; "
                    "keep HOROVOD_ZERO=1 runs on a dense or quantized "
                    "compression")
            # Sparse batches downgrade to the two-dispatch split (the
            # existing _downgrade_codec composition rule): the sparse
            # decode is a gather+scatter, not a psum, so it cannot ride
            # the donated reduce+apply program. Negotiated-codec
            # decision — every rank splits the same batches.
            self._warn_apply_once(
                "sparse-split",
                "fused reduce+apply degrades to the split "
                "reduce-then-apply execution for sparse (top-k) "
                "batches; applied parameters still land.")
            fused = False
        # flight recorder (docs/blackbox.md): the negotiated fused-apply
        # strategy and fingerprint for this batch — the evidence a
        # postmortem needs when one rank applied and another reduced.
        # Enabled check BEFORE building the detail string: the disabled
        # path must stay allocation-free (the HOROVOD_FLIGHTREC=0
        # contract pinned by the tracemalloc test).
        if _flightrec.recorder().enabled:
            _flightrec.record(
                _flightrec.EV_FUSED_APPLY,
                ordinal=-1 if cycle_no is None else cycle_no,
                detail=("fused:" if fused else "split:") + fingerprint[:16])
        if fused and fingerprint and \
                fingerprint != ctxs[0].rule.fingerprint:
            # the coordinator negotiated a different apply program than
            # this rank submitted — a bug, never a silent divergence
            raise RuntimeError(
                f"fused-apply desync: response negotiated rule "
                f"{fingerprint!r} but rank {self._rank} submitted "
                f"{ctxs[0].rule.fingerprint!r} for batch "
                f"{[e.name for e in entries]}")
        if not fused:
            if not fingerprint and uniform and self._fused_apply_exec:
                self._warn_apply_once(
                    "split-wire",
                    "fused reduce+apply degrades to the split "
                    "reduce-then-apply execution: this controller wire "
                    "predates the apply fingerprint field (set "
                    "HOROVOD_NATIVE_CONTROLLER=0 for single-dispatch "
                    "apply batches). Applied parameters still land.")
            reduced = self._run_allreduce(idx, entries, codec,
                                          cycle_no=cycle_no)
            if self._sentry is not None or self._consensus_acc is not None:
                reduced = self._screen_reduced(entries, reduced)
            self._apply_counts["split"] += 1
            _REDUCE_APPLY_BATCHES.labels(mode="split").inc()
            return [r if e.apply is None else self._apply_leaf(e.apply, r)
                    for e, r in zip(entries, reduced)]

        from .fused_apply import bucket_apply_fn
        from .xla_plane import _next_bucket

        tl = self.timeline
        chaos = self._data_chaos
        if chaos is not None:
            chaos.begin_batch()  # same ordinal domain as plain batches
        watch = self._tensorwatch
        if watch is not None:
            watch.begin_batch()  # same ordinal domain as plain batches
        rule, count = ctxs[0].rule, ctxs[0].count
        denom = self._size if ctxs[0].average and self._size > 1 else 1
        # census gate: for skip/zero/abort the program must not land a
        # poisoned update (abort tears the world down right after, but
        # the params a restore reads must be the ungated ones); warn/off
        # hand values through like the two-dispatch path would
        gate = self._sentry is not None and \
            self._sentry.policy in ("skip", "zero", "abort")
        if gate and self._sentry.policy == "zero" and \
                len(entries) > 1:
            self._warn_apply_once(
                "zero-granularity",
                "HOROVOD_GRAD_SENTRY=zero applies at BATCH granularity "
                "under fused reduce+apply (the in-program census gate "
                "zeroes the whole batch, i.e. skip semantics); use the "
                "split execution for per-tensor nulling.")
        shapes = [tuple(int(s) for s in e.array.shape) for e in entries]
        sizes = [int(np.prod(s, dtype=np.int64)) if s else 1
                 for s in shapes]
        total = int(sum(sizes))
        bucket = _next_bucket(total)
        codec = self._downgrade_codec(entries[0], codec)
        for e in entries:
            tl.activity_start(e.name, "EXECUTE")
        # the observatory measures the reduced gradients pre-apply, so a
        # sampled apply-fused batch needs the host views too (one D2H on
        # the device route, sampled steps only — documented in
        # docs/tensorwatch.md; the plain route keeps the scalar probes)
        need_views = self._consensus_acc is not None or \
            self._sentry is not None or \
            (watch is not None and watch.sampling)
        if zero1:
            # ZeRO-1 device route (docs/sharding.md): shard-major
            # packing, then ONE compiled reduce-scatter → shard-apply →
            # all-gather dispatch with donated param/slot buckets. The
            # host-side interleave forces a D2H per leaf — acceptable on
            # the proof surface; device-resident packing is the
            # follow-on optimization the layout was designed for.
            from ..sharding import zero1 as _z1

            sh_lens = [_z1.shard_len(n, self._size) for n in sizes]
            sbucket = _next_bucket(int(sum(sh_lens)))
            grad_rows = _z1.pack_rows(
                [np.asarray(e.array) for e in entries],
                self._size, sbucket)
            param_full = _z1.pack_rows(
                [c.param for c in ctxs], self._size, sbucket)
            slot_rows = [
                _z1.pack_shard_row([c.slots[k] for c in ctxs], sbucket)
                for k in range(rule.nslots)]
            red_rows, newp_rows, nan, inf, slot_out_rows = \
                self._device_call(
                    self._plane.reduce_scatter_apply, grad_rows,
                    param_full, count, slot_rows, rule, codec, gate,
                    denom)
            new_p_leaves = _z1.unpack_rows(
                np.asarray(newp_rows), shapes, self._size, sbucket)
            _z1.record_imbalance(grad_rows, np.asarray(red_rows),
                                 self._size)
            slot_shards = [_z1.split_shard_row(np.asarray(r), sh_lens)
                           for r in slot_out_rows]
            red_leaves = _z1.unpack_rows(
                np.asarray(red_rows), shapes, self._size, sbucket) \
                if need_views else None
        elif self._plane is not None and self._plane.supports(
                dtype_of(entries[0].array)):
            # device route: pack grad/param/slot buckets, ONE compiled
            # psum+apply dispatch with donated buckets
            write = self._plane._write_fn(np.dtype(np.float32),
                                          np.dtype(np.float32))
            zeros = self._plane._zeros_fn(bucket, np.dtype(np.float32))

            def pack(leaves):
                buf, off = zeros(), 0
                for leaf, n in zip(leaves, sizes):
                    buf = write(buf, leaf, off)
                    off += n
                return buf

            grad_buf = pack([e.array for e in entries])
            param_buf = pack([c.param for c in ctxs])
            slot_bufs = [pack([c.slots[k] for c in ctxs])
                         for k in range(rule.nslots)]
            self._plane._account_allreduce(
                "apply", total, np.dtype(np.float32).itemsize,
                np.float32, codec)
            reduced, new_p, nan, inf, new_slots = self._device_call(
                self._plane.reduce_apply, grad_buf, param_buf, count,
                slot_bufs, rule, codec, gate, denom)
            read = lambda buf, shape, n, off: self._plane._read_fn(  # noqa: E731
                shape, n, np.dtype(np.float32), np.dtype(np.float32),
                bucket)(buf, off)
            red_host = np.asarray(reduced) if need_views else None
        else:
            # host route: the TCP exchange reduces (the same unpadded
            # concat bytes a plain batch would ship), then one bucket
            # program applies (census+gate+divide+update in a single
            # dispatch)
            buf = np.empty((total,), np.float32)
            off = 0
            for e, n in zip(entries, sizes):
                buf[off:off + n] = np.asarray(e.array).ravel()
                off += n
            if chaos is not None:
                buf = chaos.on_reduce_input(buf)
            if self._client is None:
                out = np.array(buf, copy=True)  # world of one
            else:
                raw = self._client.payload(
                    self._rank, idx,
                    np.ascontiguousarray(buf).tobytes(),
                    cycle_no=cycle_no)
                out = np.frombuffer(raw, dtype=np.float32).copy()
            if chaos is not None:
                out = chaos.on_reduce_output(out)
            # np.empty + explicit tail zero: the pad region only needs
            # deterministic FINITE values (the census reads g; params
            # and slots are never read back past ``total``), and
            # zero-filling whole power-of-two buckets was measurable on
            # the bench at fusion-buffer sizes
            gpad = np.empty((bucket,), np.float32)
            gpad[:total] = out[:total]
            gpad[total:] = 0.0
            ppad = np.empty((bucket,), np.float32)
            ppad[total:] = 0.0
            spads = [np.empty((bucket,), np.float32)
                     for _ in range(rule.nslots)]
            off = 0
            for c, n in zip(ctxs, sizes):
                ppad[off:off + n] = np.asarray(c.param).ravel()
                for k in range(rule.nslots):
                    spads[k][off:off + n] = np.asarray(c.slots[k]).ravel()
                off += n
            for k in range(rule.nslots):
                spads[k][total:] = 0.0
            fused_out = bucket_apply_fn(rule, gate, denom)(
                gpad, ppad, np.int32(count), *spads)
            new_p = np.asarray(fused_out[0])  # one D2H per bucket
            nan, inf = int(fused_out[1]), int(fused_out[2])
            new_slots = [np.asarray(s) for s in fused_out[3:]]
            red_host = gpad if need_views else None
            read = lambda buf, shape, n, off: \
                buf[off:off + n].reshape(shape)  # noqa: E731
        self._apply_counts["fused"] += 1
        if zero1:
            self._apply_counts["zero1"] += 1
        self._apply_counts["dispatches"] += 1
        _REDUCE_APPLY_BATCHES.labels(
            mode="zero1" if zero1 else "fused").inc()
        _APPLY_DISPATCHES.inc()
        names = [e.name for e in entries]
        if need_views:
            if zero1:
                # the program all-gathers the raw reduced bucket, so
                # every rank digests identical PRE-apply bytes — the
                # same consensus framing as the replicated routes
                views = red_leaves
            else:
                views, off = [], 0
                for shape, n in zip(shapes, sizes):
                    views.append(red_host[off:off + n].reshape(shape))
                    off += n
            if watch is not None and watch.sampling:
                # numerics observatory: the reduced gradients as
                # received, PRE-apply (the consensus framing)
                watch.observe_batch(names,
                                    [e.array for e in entries], views,
                                    codec)
            # consensus FIRST, on the raw reduced bytes (pre-apply, the
            # docs/integrity.md contract), then the sentry's collective
            # verdict off the in-program two-scalar census
            if self._consensus_acc is not None:
                self._consensus_acc.observe_batch(names, views)
            if self._sentry is not None:
                trips_before = len(self._sentry.trips)
                self._sentry.screen_batch(names, views,
                                          precomputed=(int(nan),
                                                       int(inf)))
                if gate and int(nan) + int(inf) == 0 and \
                        len(self._sentry.trips) > trips_before:
                    if zero1:
                        # the sharded program's census is already
                        # GLOBAL (shard counts psum-med in-program), so
                        # every rank's gate fired on the same collective
                        # verdict — a trip with a clean global census
                        # means the sentry's exchange saw something the
                        # census cannot express; consensus names the
                        # divergence, and a collective-free local
                        # rewrite is impossible without peer slot
                        # shards, so keep the landed result.
                        self._warn_apply_once(
                            "zero1-trip",
                            "sentry tripped on a ZeRO-1 batch with a "
                            "clean global census; keeping the landed "
                            "update (the in-program gate verdict is "
                            "already collective under ZeRO-1).")
                    else:
                        # The COLLECTIVE verdict says bad but this
                        # rank's local census was clean — a
                        # peer-divergent reduced buffer (the sentry's
                        # "peer" kind): the in-program gate fired on
                        # the bad rank but not here, so the full update
                        # already landed locally. Recompute the
                        # zero-gradient step from the UNTOUCHED
                        # submission contexts (collective-free — never
                        # a psum re-run) so every rank converges on the
                        # identical no-op update the gated rank
                        # applied.
                        new_p, new_slots = self._zero_grad_apply(
                            rule, ctxs, sizes, total, bucket, count,
                            denom)
                        read = lambda buf, shape, n, off: \
                            buf[off:off + n].reshape(shape)  # noqa: E731
        if zero1:
            results = [
                ApplyResult(new_p_leaves[i],
                            tuple(slot_shards[k][i]
                                  for k in range(rule.nslots)))
                for i in range(len(entries))]
        else:
            results, off = [], 0
            for shape, n in zip(shapes, sizes):
                results.append(ApplyResult(
                    read(new_p, shape, n, off),
                    tuple(read(s, shape, n, off) for s in new_slots)))
                off += n
        for e in entries:
            tl.activity_end(e.name)
        return results

    def _zero_grad_apply(self, rule, ctxs, sizes, total: int,
                         bucket: int, count: int, denom: int):
        """The collective sentry rewrite for an apply-fused batch whose
        LOCAL census was clean: re-run the bucket apply with a zeroed
        gradient over the original param/slot leaves — the exact step
        the census gate computed on the rank that saw the fault (the
        gate zeroes the gradient before the divide), so the world
        converges. Host buckets are bit-identical (same gated program,
        same shapes); a device-plane batch recomputes through the host
        program, within 1 ulp of the peer's in-program chain — in a
        scenario where the reduced bytes already diverged, which armed
        consensus names loudly regardless."""
        from .fused_apply import bucket_apply_fn

        gpad = np.zeros((bucket,), np.float32)
        ppad = np.empty((bucket,), np.float32)
        ppad[total:] = 0.0
        spads = [np.empty((bucket,), np.float32)
                 for _ in range(rule.nslots)]
        off = 0
        for c, n in zip(ctxs, sizes):
            ppad[off:off + n] = np.asarray(c.param).ravel()
            for k in range(rule.nslots):
                spads[k][off:off + n] = np.asarray(c.slots[k]).ravel()
            off += n
        for k in range(rule.nslots):
            spads[k][total:] = 0.0
        out = bucket_apply_fn(rule, True, denom)(
            gpad, ppad, np.int32(count), *spads)
        return np.asarray(out[0]), [np.asarray(s) for s in out[3:]]

    def apply_stats(self) -> Dict[str, Any]:
        """Fused reduce+apply counters for tests, the dryrun
        certification, and bench provenance (zeros when the plane never
        ran)."""
        return {
            "exec_fused": self._fused_apply_exec,
            "exec_zero1": self._zero1_exec,
            "fused_batches": self._apply_counts["fused"],
            "split_batches": self._apply_counts["split"],
            "zero1_batches": self._apply_counts["zero1"],
            "apply_dispatches": self._apply_counts["dispatches"],
        }

    def _run_allgather(self, idx: int, entry: TensorTableEntry,
                       resp: Response,
                       cycle_no: Optional[int] = None) -> List[np.ndarray]:
        if _is_jax_array(entry.array):
            if self._client is None:
                # size-1 concat == the (private, snapshot) array itself
                return [entry.array]
            if self._plane is not None and self._plane.supports_move(
                    dtype_of(entry.array)):
                return [self._device_call(self._plane.allgather_onchip,
                                          entry.array, resp.tensor_sizes)]
        arr = np.asarray(entry.array)  # lazy D2H for device submissions
        if self._client is None:
            return [arr.copy()]
        if self._plane is not None and self._plane.supports_move(
                dtype_of(arr)):
            return [self._device_call(self._plane.allgather,
                                      np.ascontiguousarray(arr),
                                      resp.tensor_sizes)]
        if self._plane is not None:
            self._warn_host_fallback("allgather", entry.name, arr)
        raw = self._client.payload(
            self._rank, idx, np.ascontiguousarray(arr).tobytes(),
            cycle_no=cycle_no)
        total_first = sum(resp.tensor_sizes)
        shape = (total_first,) + tuple(arr.shape[1:])
        return [np.frombuffer(raw, dtype=arr.dtype)
                .reshape(shape).copy()]

    def _run_broadcast(self, idx: int, entry: TensorTableEntry,
                       resp: Response,
                       cycle_no: Optional[int] = None) -> List[np.ndarray]:
        root = resp.tensor_sizes[0]
        if _is_jax_array(entry.array):
            if self._client is None:
                # size-1 broadcast == the (private, snapshot) array itself
                return [entry.array]
            if self._plane is not None and self._plane.supports_move(
                    dtype_of(entry.array)):
                return [self._device_call(self._plane.broadcast_onchip,
                                          entry.array, root)]
        arr = np.asarray(entry.array)  # lazy D2H for device submissions
        if self._client is None:
            return [arr.copy()]
        if self._plane is not None and self._plane.supports_move(
                dtype_of(arr)):
            return [self._device_call(self._plane.broadcast,
                                      np.ascontiguousarray(arr), root)]
        if self._plane is not None:
            self._warn_host_fallback("broadcast", entry.name, arr)
        payload = np.ascontiguousarray(arr).tobytes() \
            if self._rank == root else b""
        raw = self._client.payload(self._rank, idx, payload,
                                   cycle_no=cycle_no)
        return [np.frombuffer(raw, dtype=arr.dtype)
                .reshape(arr.shape).copy()]

    # -- shutdown -------------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        """Coordinated shutdown: the next cycle carries shutdown=True, the
        coordinator re-broadcasts it, every rank drains
        (``operations.cc:2065,2125-2128,2150,2374-2376``)."""
        self._stop_requested = True
        self._wake.set()
        self._stopped.wait(timeout)


def start_subset_service(subset_ranks) -> None:
    """Host the controller service for a subset world this process is NOT
    a member of (launcher world-rank 0 outside ``init(ranks=...)``): the
    launcher advertised this host's address, so the subset's control
    cycles and host-plane exchanges must rendezvous here. No engine, no
    client — pure service duty, torn down by ``hvd.shutdown``."""
    from .native_controller import (
        NativeControllerService,
        native_controller_enabled,
    )

    from .controller import world_id_of

    cfg = basics.config()
    subset_ranks = list(subset_ranks)
    subset_size = len(subset_ranks)
    # the SAME identity the members compute from their topology
    world_id = world_id_of(tuple(subset_ranks), subset_size)
    port = int(os.environ.get(_config.HOROVOD_CONTROLLER_PORT, "0"))
    bind_host = os.environ.get(_config.HOROVOD_CONTROLLER_BIND,
                               "127.0.0.1")
    use_native = native_controller_enabled(cfg)
    # local_observatory=False: this host runs NO engine, so nothing in
    # this process could ever feed the numerics observatory's evidence
    # gate — armed gating here would block the consented codec forever
    # (docs/tensorwatch.md); it degrades to consent-only, warned once.
    autotuner = Autotuner(cfg, extended=not use_native,
                          local_observatory=False) \
        if cfg.autotune else None
    listen_fd = _adopt_controller_fd(use_native)
    if use_native:  # same decision the members make
        service = NativeControllerService(
            subset_size, cfg, secret=default_secret(), port=port,
            bind_host=bind_host, autotuner=autotuner, world_id=world_id)
    else:
        detector = None
        if cfg.straggler_evict != "off":
            from ..tune.detector import StragglerDetector

            detector = StragglerDetector.from_config(cfg, subset_size)
        service = ControllerService(
            subset_size, make_negotiator(subset_size, cfg),
            secret=default_secret(), port=port, bind_host=bind_host,
            autotuner=autotuner, world_id=world_id,
            stall_shutdown_s=cfg.stall_shutdown_time_s,
            stall_warning_s=cfg.stall_warning_time_s,
            listen_fd=listen_fd,
            cache_capacity=cfg.cache_capacity,
            fusion_threshold_bytes=cfg.fusion_threshold_bytes,
            straggler_detector=detector,
            codec_min_bytes=cfg.autotune_codec_min_bytes,
            consensus_interval_steps=cfg.consensus_interval_steps,
            # Same gating as the member-hosted service above: the subset's
            # members resolve their own data plane from this same config,
            # so only a definitely-host-plane world gets the grace window
            # by default ("auto" may resolve to XLA on the members, where
            # death attribution must stay immediate).
            reconnect_window_s=cfg.reconnect_window_s if (
                cfg.data_plane == "host" or cfg.reconnect_window_explicit
            ) else 0.0)

    def _teardown() -> None:
        # Grace period: the host's own shutdown (often atexit) must not
        # yank the controller from a subset that is still mid-job.
        if not service.wait_world_shutdown(30.0):
            LOG.warning("subset-service host exiting before the subset "
                        "negotiated shutdown; tearing the controller down")
        service.shutdown()
        if autotuner is not None:
            autotuner.close()

    basics._state().engine_shutdown_hooks.append(_teardown)


_engine_lock = threading.Lock()
_engine: Optional[Engine] = None


def get_engine() -> Engine:
    """Lazy singleton start; registers teardown with ``basics.shutdown``."""
    global _engine
    with _engine_lock:
        if _engine is not None and _engine._stopped.is_set():
            # The engine stopped WITHOUT a local ``hvd.shutdown()`` (which
            # clears the singleton through _shutdown_engine): the world
            # ended underneath this process — a peer's negotiated
            # shutdown, or an escalated abort. Surface the reference's
            # shut-down semantics with the structured reason
            # (RanksAbortedError parses out of it); silently building a
            # replacement engine here raced the dying controller and
            # turned the abort into a bare "connection refused".
            Status.unknown_error(
                _engine._shutdown_reason or SHUT_DOWN_ERROR
            ).raise_if_error()
        if _engine is None:
            basics._topology()  # raises NotInitializedError when appropriate
            engine = Engine()
            basics._state().engine_shutdown_hooks.append(
                lambda: _shutdown_engine(engine))
            _engine = engine
        return _engine


def _shutdown_engine(engine: Engine) -> None:
    global _engine
    engine.stop()
    with _engine_lock:
        if _engine is engine:
            _engine = None
