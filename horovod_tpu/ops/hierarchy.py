"""Hierarchical negotiation tree: per-island sub-coordinators under one root.

The flat controller is the reference Horovod coordinator star — O(world)
messages into one socket loop on rank 0 every cycle, fine at 8 ranks and
the dominant control-plane cost at thousand-rank scale (the MPI
characterization study, arXiv 1810.11112, measures exactly this collapse;
tree reductions scale sub-linearly). This module breaks the star into the
two-level tree `parallel/hierarchical.py` already factors the DATA plane
over: one sub-coordinator per DCN island accepts its members'
RequestList/CacheRequest traffic, merges it locally — steady-state
cache-bit vectors as a fixed-size AND (the PR 3 path), cold-path
RequestLists by per-position congruence with codec and apply_fingerprint
negotiated at the island level exactly like dtypes (PR 13) — and forwards
ONE submission per cycle to the root, which expands it back into the flat
per-rank path. Expansion-at-root is the load-bearing design decision:
the root keeps a WORLD-size negotiator and runs the unchanged
``_run_cycle``, so responses, validation errors, stall warnings,
consensus verdicts and cache bookkeeping stay byte-identical with flat —
the tree only changes WHO CARRIES the messages, never what they say.

Interior nodes ride the existing wire machinery unchanged: PR 4
reconnect/dedup envelopes heal head-to-root drops, PR 9's second
identified data channel carries the payload forwarding, a per-LEVEL
flush-ordinal cross-check fails a desynced island loudly by name, PR 8's
consensus judge receives every member's digest windows through its head
(with a per-level fold cross-check), and PR 14's blackbox collector sees
relayed incident pushes so a world abort still yields ONE classified
dump. Flat topology remains the byte-identical default; the native C++
controller wire predates all of it (deterministic flat degrade, warned
once — wire-registry rows per HVL401). See docs/hierarchy.md.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.witness import maybe_wrap as _witness_wrap
from ..core import config as _config
from ..core.logging import LOG
from ..core.status import SHUT_DOWN_ERROR, format_aborted_ranks
from ..obs.registry import registry as _metrics
from ..parallel.hierarchical import island_partition
from ..runner.network import BasicClient, Preserialized
from .controller import (
    _ARRIVAL_SPREAD,
    _STRAGGLER_BLAME_S,
    _STRAGGLER_LAST,
    ControllerService,
    Negotiator,
    connect_with_hello,
    spawn_watch_thread,
)
from .messages import (
    CacheRequest,
    IslandSubmission,
    Request,
    RequestList,
    RequestType,
)
from .response_cache import and_bits

# Observability plane (docs/metrics.md §hierarchy plane): the numbers the
# tree exists to move — root messages per cycle is the scaling headline
# (~O(islands), not O(world)), merged-vs-raw is the head-side merge hit
# rate (a raw cycle forwards every member's submission verbatim and buys
# no fan-in), relayed counts the anonymous traffic heads pass through.
HIER_ISLANDS = _metrics().gauge(
    "horovod_hier_islands",
    "Islands in the negotiation tree (0 = flat topology)")
MERGED_CYCLES = _metrics().counter(
    "horovod_hier_merged_cycles_total",
    "Island cycles forwarded as ONE merged submission (cache-bit AND or "
    "congruent RequestList merge)")
RAW_CYCLES = _metrics().counter(
    "horovod_hier_raw_cycles_total",
    "Island cycles forwarded verbatim per-member (merge ineligible: "
    "divergent names, codecs, fingerprints, shapes or generations)")
ROOT_MESSAGES = _metrics().counter(
    "horovod_hier_root_messages_total",
    "Island cycle submissions received by the root coordinator")
RELAYED = _metrics().counter(
    "horovod_hier_relayed_total",
    "Anonymous control messages (metrics/flightrec/clock) relayed "
    "upstream by island heads")
SUCCESSIONS = _metrics().counter(
    "horovod_recovery_successions_total",
    "Standby island-head activations: a successor took over serving an "
    "island whose head's service died (docs/recovery.md)")


# -- topology planner ---------------------------------------------------------


@dataclass(frozen=True)
class Topology:
    """Resolved control-plane topology: ``islands`` maps island id to its
    sorted global member ranks ({} = flat star), ``island_of`` inverts
    it. The head of an island is its lowest rank (deterministic on every
    process with no extra negotiation) — unless ``head_overrides`` names
    a different member, the elastic driver's succession verdict after a
    head death (``HOROVOD_ISLAND_HEADS``, docs/recovery.md)."""

    mode: str
    islands: Dict[int, Tuple[int, ...]]
    island_of: Dict[int, int]
    head_overrides: Dict[int, int] = field(default_factory=dict)

    @property
    def flat(self) -> bool:
        return len(self.islands) <= 1

    @property
    def n_islands(self) -> int:
        return len(self.islands)

    def head_of(self, island: int) -> int:
        override = self.head_overrides.get(island)
        if override is not None and override in self.islands[island]:
            return override
        return min(self.islands[island])

    def is_head(self, rank: int) -> bool:
        island = self.island_of.get(rank)
        return island is not None and self.head_of(island) == rank

    def successor_of(self, island: int) -> Optional[int]:
        """The island's planned standby head: its lowest member that is
        NOT the current head (deterministic at plan time on every
        process), or None for a single-member island."""
        head = self.head_of(island)
        others = [r for r in self.islands[island] if r != head]
        return min(others) if others else None

    @property
    def heads(self) -> List[int]:
        return [self.head_of(i) for i in sorted(self.islands)]


FLAT = Topology(mode="flat", islands={}, island_of={})


def parse_head_overrides(raw: Optional[str]) -> Dict[int, int]:
    """Parse ``HOROVOD_ISLAND_HEADS`` ("island:rank,island:rank") — the
    driver-published succession plan; never set by hand. Malformed
    entries are skipped (the env only ever carries driver output, and a
    torn value must degrade to the planned heads, not crash launch)."""
    out: Dict[int, int] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            island, rank = part.split(":", 1)
            out[int(island)] = int(rank)
        except ValueError:
            continue
    return out


def format_head_overrides(overrides: Dict[int, int]) -> str:
    return ",".join(f"{i}:{r}" for i, r in sorted(overrides.items()))


def plan_topology(size: int, mode: Optional[str],
                  cross_size: int = 1,
                  head_overrides: Optional[Dict[int, int]] = None
                  ) -> Topology:
    """Resolve ``HOROVOD_HIERARCHY`` into a Topology.

    ``flat`` (or unset) keeps the star. ``auto`` derives one island per
    host from the launcher's cross_size — a single-host world has no DCN
    boundary to split on and stays flat. ``islands:N`` forces N
    contiguous near-equal islands (capped at one rank per island). Any
    resolved split of <= 1 island degrades to flat: a 1-island tree is
    the star plus a pointless hop. Typos fail loudly — a silently-flat
    "islnds:4" would erase the scaling the knob was set for."""
    mode = (mode or "flat").strip()
    if size <= 1 or mode in ("", "flat"):
        return FLAT
    if mode == "auto":
        n = cross_size if cross_size and cross_size > 1 else 1
    elif mode.startswith("islands:"):
        try:
            n = int(mode.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"HOROVOD_HIERARCHY={mode!r} is not a valid topology: "
                f"expected flat, auto, or islands:<N>") from None
        if n <= 0:
            raise ValueError(
                f"HOROVOD_HIERARCHY={mode!r}: island count must be "
                f"positive")
    else:
        raise ValueError(
            f"HOROVOD_HIERARCHY={mode!r} is not a valid topology: "
            f"expected flat, auto, or islands:<N>")
    n = min(n, size)
    if n <= 1:
        return FLAT
    islands = island_partition(size, n)
    island_of = {r: i for i, mem in islands.items() for r in mem}
    # sanitize succession overrides: only keep ones naming a real member
    # of a real island (a stale override from a differently-sized world
    # must degrade to the planned head, not misroute the tree)
    overrides = {i: r for i, r in (head_overrides or {}).items()
                 if i in islands and r in islands[i]}
    return Topology(mode=f"islands:{n}", islands=islands,
                    island_of=island_of, head_overrides=overrides)


# -- head-side merge ----------------------------------------------------------


def _congruent_requests(members: Tuple[int, ...],
                        lists: Dict[int, RequestList]
                        ) -> Optional[List[Request]]:
    """Merge congruent member RequestLists into one request sequence, or
    None when ANY member deviates (the raw fallback then lets the root's
    flat negotiator produce its byte-identical error naming the actual
    global ranks — island-level merging must never invent new error
    surfaces). Congruent means: same LENGTH and same per-position
    (name, op, dtype, codec, apply_fingerprint, root_rank, device) —
    order matters, the negotiation table's ready-list ordering follows
    arrival order within a list. Shapes must match exactly except
    allgather, where members legally differ in dim0 (recorded per member
    in ``gather_dim0s``, aligned to sorted members)."""
    first = lists[members[0]].requests
    length = len(first)
    for r in members[1:]:
        if len(lists[r].requests) != length:
            return None
    merged: List[Request] = []
    for pos in range(length):
        row = [lists[r].requests[pos] for r in members]
        base = row[0]
        for req in row[1:]:
            if (req.tensor_name != base.tensor_name
                    or req.request_type != base.request_type
                    or req.tensor_type != base.tensor_type
                    or getattr(req, "codec", "none")
                    != getattr(base, "codec", "none")
                    or getattr(req, "apply_fingerprint", "")
                    != getattr(base, "apply_fingerprint", "")
                    or req.root_rank != base.root_rank
                    or req.device != base.device):
                return None
        gather_dim0s = None
        if base.request_type == RequestType.ALLGATHER:
            shapes = [tuple(req.tensor_shape) for req in row]
            if any(len(s) != len(shapes[0]) or not s for s in shapes):
                return None
            if any(s[1:] != shapes[0][1:] for s in shapes):
                return None
            gather_dim0s = tuple(s[0] for s in shapes)
        else:
            if any(tuple(req.tensor_shape)
                   != tuple(base.tensor_shape) for req in row):
                return None
        merged.append(Request(
            request_rank=members[0], request_type=base.request_type,
            tensor_name=base.tensor_name, tensor_type=base.tensor_type,
            tensor_shape=tuple(base.tensor_shape),
            root_rank=base.root_rank, device=base.device,
            codec=getattr(base, "codec", "none"),
            apply_fingerprint=getattr(base, "apply_fingerprint", ""),
            member_ranks=members, gather_dim0s=gather_dim0s))
    return merged


def merge_cycle(island: int, members: Tuple[int, ...],
                slot: Dict[int, Any]) -> IslandSubmission:
    """Fold one island's cycle slot ({global rank -> RequestList or
    CacheRequest}) into its upstream submission. Three outcomes:

    * every member sent the SAME cache-bit vector under the same
      generation → one CacheRequest whose bits are the (trivially equal)
      fixed-size AND — the PR 3 steady state shrinks to one message;
    * every member sent a congruent RequestList → one merged request
      sequence (codec/apply_fingerprint negotiated at this level exactly
      like dtypes: any mismatch is merge-ineligible);
    * anything else → ``raw``: the members' submissions travel verbatim
      and the root's flat path handles divergence with byte-identical
      error texts (mixed cache generations, ragged bit vectors, codec
      mismatches all land on their flat diagnostics).

    Member flush ordinals and consensus digest windows always travel —
    merged forms carry them in side maps (plus the head's fold over the
    digests, the per-level PR 8 cross-check); raw items carry their own.
    """
    shutdown_ranks = tuple(
        r for r in members
        if getattr(slot[r], "shutdown", False))
    ordinals = {r: getattr(slot[r], "flush_ordinal", None)
                for r in members}
    digests = {r: getattr(slot[r], "integrity_digest", None)
               for r in members}
    fold = None
    if any(d is not None for d in digests.values()):
        from ..integrity.consensus import fold_digest

        fold = fold_digest(digests)
    cache_items = {r: rl for r, rl in slot.items()
                   if isinstance(rl, CacheRequest)}
    if len(cache_items) == len(slot):
        generations = {rl.generation for rl in cache_items.values()}
        bit_lens = {len(rl.bits) for rl in cache_items.values()}
        if len(generations) == 1 and len(bit_lens) == 1:
            folded = and_bits([cache_items[r].bits for r in members])
            if all(cache_items[r].bits == folded for r in members):
                return IslandSubmission(
                    island=island, members=members,
                    cache=CacheRequest(rank=members[0], bits=folded,
                                       generation=next(iter(generations))),
                    member_ordinals=ordinals, digests=digests, fold=fold,
                    shutdown_ranks=shutdown_ranks)
        # divergent bits/generations: the root must see the per-member
        # truth — flat expands each rank's own bit set (a partial-hit
        # cycle), and generation desync has an exact flat error text
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    if cache_items:
        # mixed CacheRequest/RequestList cycle: flat handles it (some
        # ranks warm, some cold) — forward verbatim
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    merged = _congruent_requests(members, slot)
    if merged is None:
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    return IslandSubmission(
        island=island, members=members, requests=merged,
        member_ordinals=ordinals, digests=digests, fold=fold,
        shutdown_ranks=shutdown_ranks)


# -- root-side expansion ------------------------------------------------------


def expand_submission(sub: IslandSubmission) -> Dict[int, Any]:
    """Reconstruct the flat per-global-rank cycle slot an island
    submission stands for — the inverse of :func:`merge_cycle`, feeding
    the root's unchanged ``_run_cycle`` so negotiation, validation and
    caching semantics stay byte-identical with the star topology."""
    members = tuple(sub.members)
    if not members:
        raise ValueError(
            f"island {sub.island} submission names no member ranks")
    if sub.raw is not None:
        if set(sub.raw) != set(members):
            raise ValueError(
                f"island {sub.island} raw submission covers ranks "
                f"{sorted(sub.raw)} but the island roster is "
                f"{list(members)}")
        return dict(sub.raw)
    ordinals = sub.member_ordinals or {}
    digests = sub.digests or {}
    if sub.cache is not None:
        return {
            r: CacheRequest(rank=r, bits=sub.cache.bits,
                            generation=sub.cache.generation,
                            integrity_digest=digests.get(r),
                            flush_ordinal=ordinals.get(r))
            for r in members}
    if sub.requests is None:
        raise ValueError(
            f"island {sub.island} submission carries neither cache, "
            f"requests, nor raw payload")
    out: Dict[int, Any] = {}
    for r in members:
        requests: List[Request] = []
        for req in sub.requests:
            member_ranks = tuple(req.member_ranks or members)
            shape = tuple(req.tensor_shape)
            dim0s = getattr(req, "gather_dim0s", None)
            if dim0s is not None:
                shape = (dim0s[member_ranks.index(r)],) + shape[1:]
            requests.append(Request(
                request_rank=r, request_type=req.request_type,
                tensor_name=req.tensor_name,
                tensor_type=req.tensor_type, tensor_shape=shape,
                root_rank=req.root_rank, device=req.device,
                codec=getattr(req, "codec", "none"),
                apply_fingerprint=getattr(req, "apply_fingerprint", "")))
        out[r] = RequestList(rank=r, requests=requests,
                             shutdown=r in sub.shutdown_ranks,
                             integrity_digest=digests.get(r),
                             flush_ordinal=ordinals.get(r))
    return out


def check_fold(sub: IslandSubmission) -> Optional[str]:
    """Per-level consensus fold cross-check (docs/hierarchy.md): the head
    stamped a digest-of-digests over the member windows it forwarded; the
    root recomputes it over what ARRIVED. A mismatch means the windows
    were corrupted between the levels — the per-rank judge could then
    blame the wrong rank, so the error names the ISLAND instead. Returns
    the error text, or None (including when nothing digested)."""
    if sub.fold is None or sub.digests is None:
        return None
    from ..integrity.consensus import fold_digest

    actual = fold_digest(sub.digests)
    if actual == sub.fold:
        return None
    return (f"island {sub.island} consensus digest fold mismatch: head "
            f"stamped {sub.fold}, root recomputed {actual} over the "
            f"windows that arrived for ranks "
            f"{', '.join(map(str, sub.members))} — the digest windows "
            f"were corrupted between the island head and the root, so "
            f"per-rank consensus attribution cannot be trusted this "
            f"cycle")


# -- the sub-coordinator service ----------------------------------------------


class SubCoordinatorService(ControllerService):
    """One island's head: a ControllerService whose rendezvous collects
    the island's members, but whose cycle/payload/sentry computes FORWARD
    upstream instead of negotiating/combining locally.

    Subclassing buys the entire connection discipline for free — hello
    binding and supersede, the PR 4 reconnect window and heal, watch
    parking, bye/deregister, flush-ordinal cross-check — so a member
    rank's client speaks to its head EXACTLY as it would to the root
    (rank-side code has no hierarchy branch at all). The inherited
    negotiator is never fed (``_run_cycle`` is overridden); the inherited
    cache/autotuner/consensus state stays disabled — the ROOT owns all
    global decisions, this node only aggregates and fans back out.

    Payloads forward UNSUMMED ({rank: bytes}): float addition is
    non-associative and only the root's single sorted-global-rank combine
    is bit-identical with flat. Sentry bits forward per-member for the
    same reason (the fold must run over the WORLD's items exactly once).
    Anonymous traffic (metrics, flightrec, metrics_pull, clock_probe)
    relays verbatim on a dedicated leaf-locked connection, so member
    clock probes measure the ROOT's timebase (one global clock) and
    member incident pushes land in the root's single merged dump."""

    def __init__(self, island: int, members, upstream_addr,
                 secret: Optional[bytes] = None, port: int = 0,
                 bind_host: str = "127.0.0.1", world_id: str = "",
                 listen_fd: Optional[int] = None,
                 reconnect_window_s: Optional[float] = None,
                 straggler_detector=None,
                 head_rank: Optional[int] = None,
                 standby: bool = False) -> None:
        members = tuple(sorted(int(r) for r in members))
        if not members:
            raise ValueError("an island needs at least one member rank")
        self._island = int(island)
        self._members = members
        self._head_rank = members[0] if head_rank is None else int(head_rank)
        self._upstream_addr = upstream_addr
        self._up_secret = secret
        self._up_world_id = world_id
        self._standby = bool(standby)
        # A standby starts with None ordinal and resyncs from its
        # members' own ordinals on its first served cycle — the root
        # skips None island ordinals for exactly this window.
        self._up_cycle_no: Optional[int] = None if standby else 0
        self._ordinal_resync = standby
        self._cycles_seen = 0
        self._headstop_cycle, self._partition_fault = \
            (None, None) if standby else self._parse_recovery_faults()
        self._up = self._up_data = self._up_sentry = None
        self._up_relay = None
        self._up_lock = _witness_wrap(
            threading.Lock(), "ops.hierarchy.SubCoordinatorService._up")
        self._up_data_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._up_data")
        self._up_sentry_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._up_sentry")
        self._relay_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._relay")
        self._activate_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._activate")
        if not standby:
            # Upstream channels BEFORE the local service goes live:
            # members may dial the pre-bound listener the instant
            # BasicService starts accepting, and their first cycle must
            # find the uplink ready. A STANDBY deliberately skips this —
            # it must cost the root nothing until activation
            # (docs/recovery.md), so its channels build lazily on the
            # first member request that fails over to it.
            self._connect_upstream()
        super().__init__(
            size=len(members),
            negotiator=Negotiator(len(members), 64 << 20),
            secret=secret, port=port, bind_host=bind_host,
            world_id=world_id, stall_shutdown_s=0.0,
            listen_fd=listen_fd, cache_capacity=0,
            reconnect_window_s=reconnect_window_s,
            straggler_detector=straggler_detector,
            consensus_interval_steps=0)
        if not standby:
            self._start_upstream_watch()

    def _connect_upstream(self) -> None:
        hello = ("hello_island", self._head_rank, self._island,
                 self._members, self._up_world_id)

        def _hello(client) -> None:
            client.request(hello)

        def _rehello(client) -> None:
            # superseding re-identify after a transparent reconnect —
            # the PR 4 heal, same contract as ControllerClient
            client.bare_request(hello)

        # Four separate connections because their parking domains differ:
        # a cycle parked at the root (straggler wait) must never hold the
        # connection a payload, a sentry verdict, or an abort relay needs
        # — the same two-channel inversion PR 9 solved rank-side.
        self._up = connect_with_hello(
            self._upstream_addr, self._up_secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_data = connect_with_hello(
            self._upstream_addr, self._up_secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_sentry = connect_with_hello(
            self._upstream_addr, self._up_secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_relay = BasicClient(self._upstream_addr,
                                     secret=self._up_secret,
                                     timeout_s=None, attempts=100)

    def _start_upstream_watch(self) -> None:
        world_id = self._up_world_id

        def _request_reason(client) -> Optional[str]:
            resp = client.request(("watch", world_id))
            if resp and resp[0] == "abort" and resp[1]:
                return resp[1]
            return None  # clean stop: nothing to deliver

        # Root-abort fan-out: ONE parked watch per island (not per rank)
        # — the root's abort reason re-parks here and every member
        # watcher inherits it from the head's own watch event.
        spawn_watch_thread(self._upstream_addr, self._up_secret,
                           _request_reason, self._deliver_upstream_abort)

    def _ensure_upstream(self) -> None:
        """Standby activation (docs/recovery.md): the first member
        request that fails over here builds the upstream channels, whose
        ``hello_island`` under THIS head's rank supersedes the dead
        head at the root (its reconnect-window verdict is cancelled —
        the island lives on under its successor)."""
        if self._up is not None:
            return
        with self._activate_lock:
            if self._up is not None:
                return
            LOG.warning(
                "island %d standby head (rank %d) activating: members "
                "failed over from the dead primary", self._island,
                self._head_rank)
            self._connect_upstream()
            self._start_upstream_watch()
            SUCCESSIONS.inc()
            from ..obs import flightrec as _flightrec

            _flightrec.record(_flightrec.EV_SUCCESSION, self._island,
                              detail=f"rank {self._head_rank}")
            # Failover deadline: activation proves the primary's service
            # is dead, and the succession hello just cancelled the old
            # head's reconnect-window verdict at the root — so every
            # member now owes THIS service a registration within the
            # window. A live member's failover hello heals the parked
            # verdict (the headstop drill, where the old head survives
            # as a plain member); a member that never arrives died WITH
            # the primary and must still abort the world, or its death
            # has no attribution path left (docs/recovery.md).
            window = max(self._reconnect_window_s, 0.5)
            with self._lock:
                deadline = time.monotonic() + window
                missing = [r for r in self._members
                           if r not in self._rank_conns
                           and r not in self._pending_reconnect]
                for r in missing:
                    self._pending_reconnect[r] = deadline
            for r in missing:
                timer = threading.Timer(window + 0.05,
                                        self._reconnect_deadline,
                                        args=(r, deadline))
                timer.daemon = True
                timer.start()

    def _parse_recovery_faults(self):
        """Fault-injection hooks for the recovery chaos grid
        (docs/recovery.md): ``HOROVOD_RECOVERY_FAULT=headstop@cycleK``
        (or ``headstop@islandN:cycleK`` to aim at one island) stops THIS
        island's service at upstream cycle K (primaries only; members
        then fail over to the standby), and a
        ``partition@islandN:cycleK:durS`` rule in ``HOROVOD_CHAOS``
        blackholes the island<->root hop for durS seconds. Both are
        epoch-0-only, re-checked at fire time: a warm-recovered process
        carries the new epoch in-process and must not re-fire the fault
        it just survived."""
        headstop = None
        raw = os.environ.get(_config.HOROVOD_RECOVERY_FAULT, "")
        if raw.startswith("headstop@"):
            body = raw[len("headstop@"):]
            if body.startswith("island"):
                isl, _, rest = body.partition(":")
                try:
                    target = int(isl[len("island"):])
                except ValueError:
                    target = None
                body = rest if target == self._island else ""
            if body.startswith("cycle"):
                try:
                    headstop = int(body[len("cycle"):])
                except ValueError:
                    headstop = None
        partition = None
        try:
            from ..chaos import partition_for_island

            partition = partition_for_island(self._island)
        except Exception:  # noqa: BLE001 - a bad spec fails engine init,
            # not here; this parse is only for the head's own trigger
            partition = None
        return headstop, partition

    # -- downward abort fan-out ------------------------------------------------

    def _deliver_upstream_abort(self, reason: str) -> None:
        """The root's watch channel fired: fan the structured reason down
        to every member parked in this head's rendezvous/watch."""
        exc = RuntimeError(reason)
        self._cycles.abort(exc)
        self._payloads.abort(exc)
        self._sentry_rv.abort(exc)
        with self._lock:
            self._abort_fired = True
            if self._watch_reason is None:
                self._watch_reason = reason
        self._watch_event.set()

    def _abort_for_rank(self, rank: int) -> None:
        """A MEMBER died: escalate upstream (the root tears the world
        down with the flat attribution text and owns the single blackbox
        dump + world-abort count — an island must not double-count
        either), then unpark this island's own rendezvous."""
        with self._lock:
            first = not self._abort_fired
            self._abort_fired = True
        exc = RuntimeError(
            f"rank {rank} exited mid-job. {SHUT_DOWN_ERROR} "
            f"{format_aborted_ranks([rank])}")
        if first and self._up_relay is not None:
            LOG.warning(
                "island %d: rank %d disconnected before shutdown; "
                "escalating the death to the root coordinator",
                self._island, rank)
            try:
                with self._relay_lock:
                    self._up_relay.bare_request(
                        ("abort_island", self._head_rank, self._island,
                         rank, str(exc)))
            except Exception as up_exc:  # noqa: BLE001 - best effort
                LOG.warning(
                    "island %d: abort escalation to the root failed "
                    "(%s); the root will detect the island via its own "
                    "connection teardown", self._island, up_exc)
        self._cycles.abort(exc)
        self._payloads.abort(exc)
        self._sentry_rv.abort(exc)
        with self._lock:
            if self._watch_reason is None:
                self._watch_reason = str(exc)
        self._watch_event.set()

    def _flightrec_incident(self, reason: str) -> None:
        """No-op by design: the ROOT owns the one merged blackbox dump
        (docs/blackbox.md). Member incident pushes relay upstream
        verbatim, so the head collecting too would tear the world's
        single incident into per-island fragments."""
        del reason

    # -- the forwarding dispatch -----------------------------------------------

    def _handle(self, req: Any, _sock: Any) -> Any:
        kind = req[0]
        if self._up is None:
            # A STANDBY's first member traffic: a member only dials here
            # after the primary refused every reconnect round, so the
            # arrival IS the succession verdict. Activate BEFORE
            # dispatch — a cycle parked in the rendezvous below can only
            # be unparked by the root's abort fan-out, which needs the
            # upstream watch live NOW, not at the (possibly never-
            # arriving) merged-cycle compute. An activation failure
            # propagates as this request's error: the member's transport
            # retry then classifies the world fault loudly instead of
            # parking forever under a root-less standby.
            self._ensure_upstream()
        if kind in ("metrics", "flightrec", "metrics_pull",
                    "clock_probe"):
            # verbatim relay: the root stays the single store for
            # metrics snapshots and incident tails, and the single
            # clock-probe timebase (the min-RTT filter rank-side absorbs
            # the extra hop's latency like any other network jitter)
            self._ensure_upstream()
            RELAYED.inc()
            with self._relay_lock:
                return self._up_relay.request(req)
        if kind == "payload":
            _, rank, cycle_no, idx, data = req
            self._bind_connection(rank, _sock)
            return self._payloads.submit(
                ("payload", cycle_no, idx), rank, data,
                lambda slot: self._forward_payload(cycle_no, idx, slot))
        if kind == "sentry":
            _, rank, ordinal, bits = req
            self._bind_connection(rank, _sock)
            return self._sentry_rv.submit(
                ("sentry", ordinal), rank, bits,
                lambda slot: self._forward_sentry(ordinal, slot),
                timeout_s=60.0,
                timeout_hint=(
                    "HOROVOD_GRAD_SENTRY must resolve identically on "
                    "every rank — a disarmed rank never joins the "
                    "verdict exchange."))
        # hello / bye / watch / cycle: the inherited protocol verbatim
        # (cycle reaches the rendezvous whose compute is the OVERRIDDEN
        # _run_cycle below)
        return super()._handle(req, _sock)

    def _forward_payload(self, cycle_no: int, idx: int,
                         slot: Dict[int, bytes]) -> Preserialized:
        self._ensure_upstream()
        with self._up_data_lock:
            combined = self._up_data.request(
                ("payload_island", self._head_rank, self._island,
                 cycle_no, idx, dict(slot)))
        # one frame serves every member (identical combined bytes)
        return Preserialized(self._service.wire.frame(combined))

    def _forward_sentry(self, ordinal: int,
                        slot: Dict[int, bytes]) -> bytes:
        self._ensure_upstream()
        with self._up_sentry_lock:
            return self._up_sentry.request(
                ("sentry_island", self._head_rank, self._island,
                 ordinal, dict(slot)))

    def _maybe_fire_recovery_faults(self) -> None:
        """Fire any armed recovery-grid fault at the matching upstream
        cycle (docs/recovery.md). Epoch gating happens HERE, not at
        parse: a warm-recovered survivor carries the successor epoch
        in-process, so the fault it already survived stays dark."""
        cycle = self._cycles_seen
        self._cycles_seen += 1
        if self._headstop_cycle is None and self._partition_fault is None:
            return
        if int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0") or 0):
            return  # epoch-0 only
        if self._headstop_cycle is not None and \
                cycle >= self._headstop_cycle:
            self._headstop_cycle = None
            LOG.warning(
                "island %d head (rank %d): HOROVOD_RECOVERY_FAULT "
                "headstop firing at cycle %d — stopping the island "
                "service (members fail over to the standby)",
                self._island, self._head_rank, cycle)
            # Farewell upstream FIRST (the root deregisters this head
            # cleanly — a succession drill is not a death), then kill the
            # local service and hard-close member connections so parked
            # responses die on the wire: members see a transport fault,
            # retry under the same seq, and fall over to the standby.
            self.shutdown()
            self._service.close_connections()
            raise RuntimeError(
                "recovery fault injection: island head service stopped "
                "(headstop)")
        if self._partition_fault is not None and \
                cycle >= self._partition_fault[0]:
            _, dur_s = self._partition_fault
            self._partition_fault = None
            from ..chaos import note_injection

            note_injection("partition",
                           f"island{self._island}:dur{dur_s}")
            LOG.warning(
                "island %d head (rank %d): chaos partition firing at "
                "cycle %d — blackholing the island<->root hop for %.1fs",
                self._island, self._head_rank, cycle, dur_s)
            # Bidirectional blackhole: sever every upstream socket (the
            # root sees EOF and starts this head's reconnect window) and
            # hold every uplink lock for the duration so NOTHING flows on
            # the hop — not even a member's relayed metrics push. The
            # next upstream request after the window reconnects +
            # re-hellos: durS inside the root's reconnect window heals
            # bit-exact; past it the root aborts the island's members
            # and the world warm-recovers from the last sealed epoch.
            with self._up_lock, self._up_data_lock, \
                    self._up_sentry_lock, self._relay_lock:
                for client in (self._up, self._up_data, self._up_sentry,
                               self._up_relay):
                    try:
                        client.sever()
                    except Exception:  # noqa: BLE001 - broken is the goal
                        pass
                deadline = time.monotonic() + dur_s
                while time.monotonic() < deadline:
                    with self._lock:
                        aborted = self._abort_fired
                    if aborted:
                        raise RuntimeError(
                            f"island {self._island} partitioned from the "
                            f"root past the reconnect window. "
                            f"{SHUT_DOWN_ERROR} "
                            f"{format_aborted_ranks(list(self._members))}")
                    time.sleep(0.05)

    def _run_cycle(self, slot: Dict[int, Any],
                   key: Any = None) -> Preserialized:
        """The head's cycle compute: cross-check member ordinals, charge
        island-local straggler blame, merge, forward ONE submission, and
        re-frame the root's answer once for every member."""
        self._ensure_upstream()
        self._maybe_fire_recovery_faults()
        try:
            self._check_flush_ordinals(slot, key)
        except RuntimeError as exc:
            # the island id turns a per-rank desync diagnosis into one
            # that names WHERE in the tree it happened
            raise RuntimeError(f"island {self._island}: {exc}") from exc
        with self._lock:
            self._cycle_t0.pop(key, None)
            arrivals = self._cycle_arrivals.pop(key, None)
        if arrivals is not None and len(arrivals) == self._size > 1:
            last_rank, last_t = max(arrivals.items(),
                                    key=lambda kv: kv[1])
            spread = last_t - min(arrivals.values())
            _STRAGGLER_LAST.labels(rank=last_rank,
                                   island=self._island).inc()
            _STRAGGLER_BLAME_S.labels(rank=last_rank,
                                      island=self._island).inc(spread)
            _ARRIVAL_SPREAD.observe(spread)
            if self._straggler is not None:
                self._straggler.observe_cycle(last_rank, spread)
        sub = merge_cycle(self._island, self._members, slot)
        (RAW_CYCLES if sub.raw is not None else MERGED_CYCLES).inc()
        with self._lock:
            if self._ordinal_resync:
                # succession: this standby never saw the island's earlier
                # upstream cycles — adopt the count from the members' own
                # ordinals (each member cycle was one island cycle). With
                # nothing to adopt, stay None: the root skips None island
                # ordinals rather than fail a healthy successor.
                cand = [o for o in (getattr(slot[r], "flush_ordinal", None)
                                    for r in self._members)
                        if o is not None]
                self._up_cycle_no = max(cand) if cand else None
                self._ordinal_resync = False
            # the per-LEVEL flush ordinal: this head's own count of
            # upstream cycles, cross-checked island-vs-island at the root
            sub.flush_ordinal = self._up_cycle_no
            if self._up_cycle_no is not None:
                self._up_cycle_no += 1
        with self._up_lock:
            resp = self._up.request(
                ("island_cycle", self._head_rank, self._island, sub))
        if getattr(resp, "shutdown", False):
            # negotiated drain (or abort) reached this island: member
            # disconnects after this cycle are expected teardown
            with self._lock:
                self._world_shutdown = True
        with self._lock:
            self._cycle_no += 1
        return Preserialized(self._service.wire.frame(resp))

    def shutdown(self) -> None:
        for lock, client in ((self._up_lock, self._up),
                             (self._up_data_lock, self._up_data),
                             (self._up_sentry_lock, self._up_sentry),
                             (self._relay_lock, self._up_relay)):
            if client is None:
                continue  # never-activated standby has no uplink
            try:
                with lock:
                    client.farewell(("bye", self._head_rank))
                    client.close()
            except Exception:  # noqa: BLE001 - root may already be gone
                pass
        super().shutdown()
