"""Hierarchical negotiation tree: per-island sub-coordinators under one root.

The flat controller is the reference Horovod coordinator star — O(world)
messages into one socket loop on rank 0 every cycle, fine at 8 ranks and
the dominant control-plane cost at thousand-rank scale (the MPI
characterization study, arXiv 1810.11112, measures exactly this collapse;
tree reductions scale sub-linearly). This module breaks the star into the
two-level tree `parallel/hierarchical.py` already factors the DATA plane
over: one sub-coordinator per DCN island accepts its members'
RequestList/CacheRequest traffic, merges it locally — steady-state
cache-bit vectors as a fixed-size AND (the PR 3 path), cold-path
RequestLists by per-position congruence with codec and apply_fingerprint
negotiated at the island level exactly like dtypes (PR 13) — and forwards
ONE submission per cycle to the root, which expands it back into the flat
per-rank path. Expansion-at-root is the load-bearing design decision:
the root keeps a WORLD-size negotiator and runs the unchanged
``_run_cycle``, so responses, validation errors, stall warnings,
consensus verdicts and cache bookkeeping stay byte-identical with flat —
the tree only changes WHO CARRIES the messages, never what they say.

Interior nodes ride the existing wire machinery unchanged: PR 4
reconnect/dedup envelopes heal head-to-root drops, PR 9's second
identified data channel carries the payload forwarding, a per-LEVEL
flush-ordinal cross-check fails a desynced island loudly by name, PR 8's
consensus judge receives every member's digest windows through its head
(with a per-level fold cross-check), and PR 14's blackbox collector sees
relayed incident pushes so a world abort still yields ONE classified
dump. Flat topology remains the byte-identical default; the native C++
controller wire predates all of it (deterministic flat degrade, warned
once — wire-registry rows per HVL401). See docs/hierarchy.md.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..analysis.witness import maybe_wrap as _witness_wrap
from ..core.logging import LOG
from ..core.status import SHUT_DOWN_ERROR, format_aborted_ranks
from ..obs.registry import registry as _metrics
from ..parallel.hierarchical import island_partition
from ..runner.network import BasicClient, Preserialized
from .controller import (
    _ARRIVAL_SPREAD,
    _STRAGGLER_BLAME_S,
    _STRAGGLER_LAST,
    ControllerService,
    Negotiator,
    connect_with_hello,
    spawn_watch_thread,
)
from .messages import (
    CacheRequest,
    IslandSubmission,
    Request,
    RequestList,
    RequestType,
)
from .response_cache import and_bits

# Observability plane (docs/metrics.md §hierarchy plane): the numbers the
# tree exists to move — root messages per cycle is the scaling headline
# (~O(islands), not O(world)), merged-vs-raw is the head-side merge hit
# rate (a raw cycle forwards every member's submission verbatim and buys
# no fan-in), relayed counts the anonymous traffic heads pass through.
HIER_ISLANDS = _metrics().gauge(
    "horovod_hier_islands",
    "Islands in the negotiation tree (0 = flat topology)")
MERGED_CYCLES = _metrics().counter(
    "horovod_hier_merged_cycles_total",
    "Island cycles forwarded as ONE merged submission (cache-bit AND or "
    "congruent RequestList merge)")
RAW_CYCLES = _metrics().counter(
    "horovod_hier_raw_cycles_total",
    "Island cycles forwarded verbatim per-member (merge ineligible: "
    "divergent names, codecs, fingerprints, shapes or generations)")
ROOT_MESSAGES = _metrics().counter(
    "horovod_hier_root_messages_total",
    "Island cycle submissions received by the root coordinator")
RELAYED = _metrics().counter(
    "horovod_hier_relayed_total",
    "Anonymous control messages (metrics/flightrec/clock) relayed "
    "upstream by island heads")


# -- topology planner ---------------------------------------------------------


@dataclass(frozen=True)
class Topology:
    """Resolved control-plane topology: ``islands`` maps island id to its
    sorted global member ranks ({} = flat star), ``island_of`` inverts
    it. The head of an island is its lowest rank (deterministic on every
    process with no extra negotiation)."""

    mode: str
    islands: Dict[int, Tuple[int, ...]]
    island_of: Dict[int, int]

    @property
    def flat(self) -> bool:
        return len(self.islands) <= 1

    @property
    def n_islands(self) -> int:
        return len(self.islands)

    def head_of(self, island: int) -> int:
        return min(self.islands[island])

    def is_head(self, rank: int) -> bool:
        island = self.island_of.get(rank)
        return island is not None and self.head_of(island) == rank

    @property
    def heads(self) -> List[int]:
        return [self.head_of(i) for i in sorted(self.islands)]


FLAT = Topology(mode="flat", islands={}, island_of={})


def plan_topology(size: int, mode: Optional[str],
                  cross_size: int = 1) -> Topology:
    """Resolve ``HOROVOD_HIERARCHY`` into a Topology.

    ``flat`` (or unset) keeps the star. ``auto`` derives one island per
    host from the launcher's cross_size — a single-host world has no DCN
    boundary to split on and stays flat. ``islands:N`` forces N
    contiguous near-equal islands (capped at one rank per island). Any
    resolved split of <= 1 island degrades to flat: a 1-island tree is
    the star plus a pointless hop. Typos fail loudly — a silently-flat
    "islnds:4" would erase the scaling the knob was set for."""
    mode = (mode or "flat").strip()
    if size <= 1 or mode in ("", "flat"):
        return FLAT
    if mode == "auto":
        n = cross_size if cross_size and cross_size > 1 else 1
    elif mode.startswith("islands:"):
        try:
            n = int(mode.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"HOROVOD_HIERARCHY={mode!r} is not a valid topology: "
                f"expected flat, auto, or islands:<N>") from None
        if n <= 0:
            raise ValueError(
                f"HOROVOD_HIERARCHY={mode!r}: island count must be "
                f"positive")
    else:
        raise ValueError(
            f"HOROVOD_HIERARCHY={mode!r} is not a valid topology: "
            f"expected flat, auto, or islands:<N>")
    n = min(n, size)
    if n <= 1:
        return FLAT
    islands = island_partition(size, n)
    island_of = {r: i for i, mem in islands.items() for r in mem}
    return Topology(mode=f"islands:{n}", islands=islands,
                    island_of=island_of)


# -- head-side merge ----------------------------------------------------------


def _congruent_requests(members: Tuple[int, ...],
                        lists: Dict[int, RequestList]
                        ) -> Optional[List[Request]]:
    """Merge congruent member RequestLists into one request sequence, or
    None when ANY member deviates (the raw fallback then lets the root's
    flat negotiator produce its byte-identical error naming the actual
    global ranks — island-level merging must never invent new error
    surfaces). Congruent means: same LENGTH and same per-position
    (name, op, dtype, codec, apply_fingerprint, root_rank, device) —
    order matters, the negotiation table's ready-list ordering follows
    arrival order within a list. Shapes must match exactly except
    allgather, where members legally differ in dim0 (recorded per member
    in ``gather_dim0s``, aligned to sorted members)."""
    first = lists[members[0]].requests
    length = len(first)
    for r in members[1:]:
        if len(lists[r].requests) != length:
            return None
    merged: List[Request] = []
    for pos in range(length):
        row = [lists[r].requests[pos] for r in members]
        base = row[0]
        for req in row[1:]:
            if (req.tensor_name != base.tensor_name
                    or req.request_type != base.request_type
                    or req.tensor_type != base.tensor_type
                    or getattr(req, "codec", "none")
                    != getattr(base, "codec", "none")
                    or getattr(req, "apply_fingerprint", "")
                    != getattr(base, "apply_fingerprint", "")
                    or req.root_rank != base.root_rank
                    or req.device != base.device):
                return None
        gather_dim0s = None
        if base.request_type == RequestType.ALLGATHER:
            shapes = [tuple(req.tensor_shape) for req in row]
            if any(len(s) != len(shapes[0]) or not s for s in shapes):
                return None
            if any(s[1:] != shapes[0][1:] for s in shapes):
                return None
            gather_dim0s = tuple(s[0] for s in shapes)
        else:
            if any(tuple(req.tensor_shape)
                   != tuple(base.tensor_shape) for req in row):
                return None
        merged.append(Request(
            request_rank=members[0], request_type=base.request_type,
            tensor_name=base.tensor_name, tensor_type=base.tensor_type,
            tensor_shape=tuple(base.tensor_shape),
            root_rank=base.root_rank, device=base.device,
            codec=getattr(base, "codec", "none"),
            apply_fingerprint=getattr(base, "apply_fingerprint", ""),
            member_ranks=members, gather_dim0s=gather_dim0s))
    return merged


def merge_cycle(island: int, members: Tuple[int, ...],
                slot: Dict[int, Any]) -> IslandSubmission:
    """Fold one island's cycle slot ({global rank -> RequestList or
    CacheRequest}) into its upstream submission. Three outcomes:

    * every member sent the SAME cache-bit vector under the same
      generation → one CacheRequest whose bits are the (trivially equal)
      fixed-size AND — the PR 3 steady state shrinks to one message;
    * every member sent a congruent RequestList → one merged request
      sequence (codec/apply_fingerprint negotiated at this level exactly
      like dtypes: any mismatch is merge-ineligible);
    * anything else → ``raw``: the members' submissions travel verbatim
      and the root's flat path handles divergence with byte-identical
      error texts (mixed cache generations, ragged bit vectors, codec
      mismatches all land on their flat diagnostics).

    Member flush ordinals and consensus digest windows always travel —
    merged forms carry them in side maps (plus the head's fold over the
    digests, the per-level PR 8 cross-check); raw items carry their own.
    """
    shutdown_ranks = tuple(
        r for r in members
        if getattr(slot[r], "shutdown", False))
    ordinals = {r: getattr(slot[r], "flush_ordinal", None)
                for r in members}
    digests = {r: getattr(slot[r], "integrity_digest", None)
               for r in members}
    fold = None
    if any(d is not None for d in digests.values()):
        from ..integrity.consensus import fold_digest

        fold = fold_digest(digests)
    cache_items = {r: rl for r, rl in slot.items()
                   if isinstance(rl, CacheRequest)}
    if len(cache_items) == len(slot):
        generations = {rl.generation for rl in cache_items.values()}
        bit_lens = {len(rl.bits) for rl in cache_items.values()}
        if len(generations) == 1 and len(bit_lens) == 1:
            folded = and_bits([cache_items[r].bits for r in members])
            if all(cache_items[r].bits == folded for r in members):
                return IslandSubmission(
                    island=island, members=members,
                    cache=CacheRequest(rank=members[0], bits=folded,
                                       generation=next(iter(generations))),
                    member_ordinals=ordinals, digests=digests, fold=fold,
                    shutdown_ranks=shutdown_ranks)
        # divergent bits/generations: the root must see the per-member
        # truth — flat expands each rank's own bit set (a partial-hit
        # cycle), and generation desync has an exact flat error text
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    if cache_items:
        # mixed CacheRequest/RequestList cycle: flat handles it (some
        # ranks warm, some cold) — forward verbatim
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    merged = _congruent_requests(members, slot)
    if merged is None:
        return IslandSubmission(island=island, members=members,
                                raw={r: slot[r] for r in members})
    return IslandSubmission(
        island=island, members=members, requests=merged,
        member_ordinals=ordinals, digests=digests, fold=fold,
        shutdown_ranks=shutdown_ranks)


# -- root-side expansion ------------------------------------------------------


def expand_submission(sub: IslandSubmission) -> Dict[int, Any]:
    """Reconstruct the flat per-global-rank cycle slot an island
    submission stands for — the inverse of :func:`merge_cycle`, feeding
    the root's unchanged ``_run_cycle`` so negotiation, validation and
    caching semantics stay byte-identical with the star topology."""
    members = tuple(sub.members)
    if not members:
        raise ValueError(
            f"island {sub.island} submission names no member ranks")
    if sub.raw is not None:
        if set(sub.raw) != set(members):
            raise ValueError(
                f"island {sub.island} raw submission covers ranks "
                f"{sorted(sub.raw)} but the island roster is "
                f"{list(members)}")
        return dict(sub.raw)
    ordinals = sub.member_ordinals or {}
    digests = sub.digests or {}
    if sub.cache is not None:
        return {
            r: CacheRequest(rank=r, bits=sub.cache.bits,
                            generation=sub.cache.generation,
                            integrity_digest=digests.get(r),
                            flush_ordinal=ordinals.get(r))
            for r in members}
    if sub.requests is None:
        raise ValueError(
            f"island {sub.island} submission carries neither cache, "
            f"requests, nor raw payload")
    out: Dict[int, Any] = {}
    for r in members:
        requests: List[Request] = []
        for req in sub.requests:
            member_ranks = tuple(req.member_ranks or members)
            shape = tuple(req.tensor_shape)
            dim0s = getattr(req, "gather_dim0s", None)
            if dim0s is not None:
                shape = (dim0s[member_ranks.index(r)],) + shape[1:]
            requests.append(Request(
                request_rank=r, request_type=req.request_type,
                tensor_name=req.tensor_name,
                tensor_type=req.tensor_type, tensor_shape=shape,
                root_rank=req.root_rank, device=req.device,
                codec=getattr(req, "codec", "none"),
                apply_fingerprint=getattr(req, "apply_fingerprint", "")))
        out[r] = RequestList(rank=r, requests=requests,
                             shutdown=r in sub.shutdown_ranks,
                             integrity_digest=digests.get(r),
                             flush_ordinal=ordinals.get(r))
    return out


def check_fold(sub: IslandSubmission) -> Optional[str]:
    """Per-level consensus fold cross-check (docs/hierarchy.md): the head
    stamped a digest-of-digests over the member windows it forwarded; the
    root recomputes it over what ARRIVED. A mismatch means the windows
    were corrupted between the levels — the per-rank judge could then
    blame the wrong rank, so the error names the ISLAND instead. Returns
    the error text, or None (including when nothing digested)."""
    if sub.fold is None or sub.digests is None:
        return None
    from ..integrity.consensus import fold_digest

    actual = fold_digest(sub.digests)
    if actual == sub.fold:
        return None
    return (f"island {sub.island} consensus digest fold mismatch: head "
            f"stamped {sub.fold}, root recomputed {actual} over the "
            f"windows that arrived for ranks "
            f"{', '.join(map(str, sub.members))} — the digest windows "
            f"were corrupted between the island head and the root, so "
            f"per-rank consensus attribution cannot be trusted this "
            f"cycle")


# -- the sub-coordinator service ----------------------------------------------


class SubCoordinatorService(ControllerService):
    """One island's head: a ControllerService whose rendezvous collects
    the island's members, but whose cycle/payload/sentry computes FORWARD
    upstream instead of negotiating/combining locally.

    Subclassing buys the entire connection discipline for free — hello
    binding and supersede, the PR 4 reconnect window and heal, watch
    parking, bye/deregister, flush-ordinal cross-check — so a member
    rank's client speaks to its head EXACTLY as it would to the root
    (rank-side code has no hierarchy branch at all). The inherited
    negotiator is never fed (``_run_cycle`` is overridden); the inherited
    cache/autotuner/consensus state stays disabled — the ROOT owns all
    global decisions, this node only aggregates and fans back out.

    Payloads forward UNSUMMED ({rank: bytes}): float addition is
    non-associative and only the root's single sorted-global-rank combine
    is bit-identical with flat. Sentry bits forward per-member for the
    same reason (the fold must run over the WORLD's items exactly once).
    Anonymous traffic (metrics, flightrec, metrics_pull, clock_probe)
    relays verbatim on a dedicated leaf-locked connection, so member
    clock probes measure the ROOT's timebase (one global clock) and
    member incident pushes land in the root's single merged dump."""

    def __init__(self, island: int, members, upstream_addr,
                 secret: Optional[bytes] = None, port: int = 0,
                 bind_host: str = "127.0.0.1", world_id: str = "",
                 listen_fd: Optional[int] = None,
                 reconnect_window_s: Optional[float] = None,
                 straggler_detector=None) -> None:
        members = tuple(sorted(int(r) for r in members))
        if not members:
            raise ValueError("an island needs at least one member rank")
        self._island = int(island)
        self._members = members
        self._head_rank = members[0]
        self._upstream_addr = upstream_addr
        self._up_cycle_no = 0
        hello = ("hello_island", self._head_rank, self._island, members,
                 world_id)

        def _hello(client) -> None:
            client.request(hello)

        def _rehello(client) -> None:
            # superseding re-identify after a transparent reconnect —
            # the PR 4 heal, same contract as ControllerClient
            client.bare_request(hello)

        # Upstream channels BEFORE the local service goes live: members
        # may dial the pre-bound listener the instant BasicService starts
        # accepting, and their first cycle must find the uplink ready.
        # Four separate connections because their parking domains differ:
        # a cycle parked at the root (straggler wait) must never hold the
        # connection a payload, a sentry verdict, or an abort relay needs
        # — the same two-channel inversion PR 9 solved rank-side.
        self._up = connect_with_hello(
            upstream_addr, secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_data = connect_with_hello(
            upstream_addr, secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_sentry = connect_with_hello(
            upstream_addr, secret, None, 100, hello=_hello,
            on_reconnect=_rehello)
        self._up_relay = BasicClient(upstream_addr, secret=secret,
                                     timeout_s=None, attempts=100)
        self._up_lock = _witness_wrap(
            threading.Lock(), "ops.hierarchy.SubCoordinatorService._up")
        self._up_data_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._up_data")
        self._up_sentry_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._up_sentry")
        self._relay_lock = _witness_wrap(
            threading.Lock(),
            "ops.hierarchy.SubCoordinatorService._relay")
        super().__init__(
            size=len(members),
            negotiator=Negotiator(len(members), 64 << 20),
            secret=secret, port=port, bind_host=bind_host,
            world_id=world_id, stall_shutdown_s=0.0,
            listen_fd=listen_fd, cache_capacity=0,
            reconnect_window_s=reconnect_window_s,
            straggler_detector=straggler_detector,
            consensus_interval_steps=0)

        def _request_reason(client) -> Optional[str]:
            resp = client.request(("watch", world_id))
            if resp and resp[0] == "abort" and resp[1]:
                return resp[1]
            return None  # clean stop: nothing to deliver

        # Root-abort fan-out: ONE parked watch per island (not per rank)
        # — the root's abort reason re-parks here and every member
        # watcher inherits it from the head's own watch event.
        spawn_watch_thread(upstream_addr, secret, _request_reason,
                           self._deliver_upstream_abort)

    # -- downward abort fan-out ------------------------------------------------

    def _deliver_upstream_abort(self, reason: str) -> None:
        """The root's watch channel fired: fan the structured reason down
        to every member parked in this head's rendezvous/watch."""
        exc = RuntimeError(reason)
        self._cycles.abort(exc)
        self._payloads.abort(exc)
        self._sentry_rv.abort(exc)
        with self._lock:
            self._abort_fired = True
            if self._watch_reason is None:
                self._watch_reason = reason
        self._watch_event.set()

    def _abort_for_rank(self, rank: int) -> None:
        """A MEMBER died: escalate upstream (the root tears the world
        down with the flat attribution text and owns the single blackbox
        dump + world-abort count — an island must not double-count
        either), then unpark this island's own rendezvous."""
        with self._lock:
            first = not self._abort_fired
            self._abort_fired = True
        exc = RuntimeError(
            f"rank {rank} exited mid-job. {SHUT_DOWN_ERROR} "
            f"{format_aborted_ranks([rank])}")
        if first:
            LOG.warning(
                "island %d: rank %d disconnected before shutdown; "
                "escalating the death to the root coordinator",
                self._island, rank)
            try:
                with self._relay_lock:
                    self._up_relay.bare_request(
                        ("abort_island", self._head_rank, self._island,
                         rank, str(exc)))
            except Exception as up_exc:  # noqa: BLE001 - best effort
                LOG.warning(
                    "island %d: abort escalation to the root failed "
                    "(%s); the root will detect the island via its own "
                    "connection teardown", self._island, up_exc)
        self._cycles.abort(exc)
        self._payloads.abort(exc)
        self._sentry_rv.abort(exc)
        with self._lock:
            if self._watch_reason is None:
                self._watch_reason = str(exc)
        self._watch_event.set()

    def _flightrec_incident(self, reason: str) -> None:
        """No-op by design: the ROOT owns the one merged blackbox dump
        (docs/blackbox.md). Member incident pushes relay upstream
        verbatim, so the head collecting too would tear the world's
        single incident into per-island fragments."""
        del reason

    # -- the forwarding dispatch -----------------------------------------------

    def _handle(self, req: Any, _sock: Any) -> Any:
        kind = req[0]
        if kind in ("metrics", "flightrec", "metrics_pull",
                    "clock_probe"):
            # verbatim relay: the root stays the single store for
            # metrics snapshots and incident tails, and the single
            # clock-probe timebase (the min-RTT filter rank-side absorbs
            # the extra hop's latency like any other network jitter)
            RELAYED.inc()
            with self._relay_lock:
                return self._up_relay.request(req)
        if kind == "payload":
            _, rank, cycle_no, idx, data = req
            self._bind_connection(rank, _sock)
            return self._payloads.submit(
                ("payload", cycle_no, idx), rank, data,
                lambda slot: self._forward_payload(cycle_no, idx, slot))
        if kind == "sentry":
            _, rank, ordinal, bits = req
            self._bind_connection(rank, _sock)
            return self._sentry_rv.submit(
                ("sentry", ordinal), rank, bits,
                lambda slot: self._forward_sentry(ordinal, slot),
                timeout_s=60.0,
                timeout_hint=(
                    "HOROVOD_GRAD_SENTRY must resolve identically on "
                    "every rank — a disarmed rank never joins the "
                    "verdict exchange."))
        # hello / bye / watch / cycle: the inherited protocol verbatim
        # (cycle reaches the rendezvous whose compute is the OVERRIDDEN
        # _run_cycle below)
        return super()._handle(req, _sock)

    def _forward_payload(self, cycle_no: int, idx: int,
                         slot: Dict[int, bytes]) -> Preserialized:
        with self._up_data_lock:
            combined = self._up_data.request(
                ("payload_island", self._head_rank, self._island,
                 cycle_no, idx, dict(slot)))
        # one frame serves every member (identical combined bytes)
        return Preserialized(self._service.wire.frame(combined))

    def _forward_sentry(self, ordinal: int,
                        slot: Dict[int, bytes]) -> bytes:
        with self._up_sentry_lock:
            return self._up_sentry.request(
                ("sentry_island", self._head_rank, self._island,
                 ordinal, dict(slot)))

    def _run_cycle(self, slot: Dict[int, Any],
                   key: Any = None) -> Preserialized:
        """The head's cycle compute: cross-check member ordinals, charge
        island-local straggler blame, merge, forward ONE submission, and
        re-frame the root's answer once for every member."""
        try:
            self._check_flush_ordinals(slot, key)
        except RuntimeError as exc:
            # the island id turns a per-rank desync diagnosis into one
            # that names WHERE in the tree it happened
            raise RuntimeError(f"island {self._island}: {exc}") from exc
        with self._lock:
            self._cycle_t0.pop(key, None)
            arrivals = self._cycle_arrivals.pop(key, None)
        if arrivals is not None and len(arrivals) == self._size > 1:
            last_rank, last_t = max(arrivals.items(),
                                    key=lambda kv: kv[1])
            spread = last_t - min(arrivals.values())
            _STRAGGLER_LAST.labels(rank=last_rank,
                                   island=self._island).inc()
            _STRAGGLER_BLAME_S.labels(rank=last_rank,
                                      island=self._island).inc(spread)
            _ARRIVAL_SPREAD.observe(spread)
            if self._straggler is not None:
                self._straggler.observe_cycle(last_rank, spread)
        sub = merge_cycle(self._island, self._members, slot)
        (RAW_CYCLES if sub.raw is not None else MERGED_CYCLES).inc()
        with self._lock:
            # the per-LEVEL flush ordinal: this head's own count of
            # upstream cycles, cross-checked island-vs-island at the root
            sub.flush_ordinal = self._up_cycle_no
            self._up_cycle_no += 1
        with self._up_lock:
            resp = self._up.request(
                ("island_cycle", self._head_rank, self._island, sub))
        if getattr(resp, "shutdown", False):
            # negotiated drain (or abort) reached this island: member
            # disconnects after this cycle are expected teardown
            with self._lock:
                self._world_shutdown = True
        with self._lock:
            self._cycle_no += 1
        return Preserialized(self._service.wire.frame(resp))

    def shutdown(self) -> None:
        for lock, client in ((self._up_lock, self._up),
                             (self._up_data_lock, self._up_data),
                             (self._up_sentry_lock, self._up_sentry),
                             (self._relay_lock, self._up_relay)):
            try:
                with lock:
                    client.farewell(("bye", self._head_rank))
                    client.close()
            except Exception:  # noqa: BLE001 - root may already be gone
                pass
        super().shutdown()
