"""Pallas flash-attention kernels (forward + backward) for TPU.

The reference contains no kernels at all — device math is delegated to
NCCL/MPI (SURVEY §2: "no CUDA kernels"). On TPU the hot op worth a custom
kernel in this framework's domain is attention (the long-context extension,
``parallel.ring_attention``): a fused blockwise softmax(QK^T)V that never
materializes the [T, T] score matrix in HBM and streams K/V through VMEM
one block at a time.

Design (per pallas_guide.md): 3-D grids (batch*heads, outer-blocks,
inner-blocks) with the inner dimension sequential ("arbitrary" semantics);
accumulators live in VMEM scratch and persist across the inner iterations.
Per-program VMEM footprint is O(block_q * d + block_k * d) — independent of
sequence length, so 16k+ contexts fit. Matmuls hit the MXU with f32
accumulation; masking and rescaling ride the VPU. Causal blocks skip
fully-masked work (`pl.when`), halving causal cost.

Training is first-class: ``flash_attention`` carries a ``jax.custom_vjp``
whose backward is the FlashAttention-2 recomputation scheme — the forward
saves only O(T) per-row logsumexp statistics, and two further kernels
recompute P = exp(S - lse) blockwise to produce dQ and dK/dV without ever
materializing the [T, T] matrix.

``interpret=True`` (automatic off-TPU) runs the same kernels through the
Pallas interpreter, which is how the CPU test suite validates them.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _causal_mask(s, q_pos0, k_pos0, block_q, block_k):
    """Mask future positions of a [block_q, block_k] score block to the
    _NEG_INF sentinel. Shared by forward and backward so the two can never
    disagree on what was masked."""
    q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, o_acc, m_acc, l_acc, *,
                scale: float, causal: bool, q_offset_blocks: int,
                num_k_blocks: int, block_q: int, block_k: int):
    # program_id must be read at kernel top level: inside a pl.when body it
    # escapes the interpreter's scope (breaks interpret=True on CPU)
    kk = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    def _update():
        q_block = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
        k_blk = k_ref[0].astype(jnp.float32)            # [block_k, d]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(  # [block_q, block_k] on the MXU
            q_block, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, (q_idx + q_offset_blocks) * block_q,
                             kk * block_k, block_q, block_k)
        m = m_acc[...]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        corr = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_new))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(m_new == _NEG_INF, 0.0, p)
        l_acc[...] = l_acc[...] * corr + p.sum(axis=1, keepdims=True)
        m_acc[...] = m_new
        o_acc[...] = o_acc[...] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip k-blocks that lie entirely in this q-block's future
        last_q_pos = (q_idx + q_offset_blocks + 1) * block_q - 1

        @pl.when(last_q_pos >= kk * block_k)
        def _run():
            _update()
    else:
        _update()

    @pl.when(kk == num_k_blocks - 1)
    def _finalize():
        l = l_acc[...]
        o_ref[0, ...] = (o_acc[...] /
                         jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # per-row logsumexp residual for the backward pass; fully-masked
        # rows stay at the _NEG_INF sentinel (m saturates f32 addition)
        lse_ref[0, ...] = (m_acc[...] +
                           jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _recompute_p(q_blk, k_blk, lse_col, *, scale, causal, q_pos0, k_pos0,
                 block_q, block_k):
    """Recompute the normalized probability block P = exp(S - lse) and S's
    mask; shared by both backward kernels. All f32, MXU matmul."""
    s = jax.lax.dot_general(
        q_blk * scale, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask(s, q_pos0, k_pos0, block_q, block_k)
    # fully-masked rows have lse at the sentinel; exp(s - sentinel) would
    # be exp(0) = 1 for masked s, so zero those rows explicitly
    p = jnp.exp(s - lse_col)
    return jnp.where(lse_col <= _NEG_INF / 2, 0.0, p)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale: float, causal: bool,
                   q_offset_blocks: int, num_k_blocks: int, block_q: int,
                   block_k: int):
    """dQ = (P * (dO V^T - delta)) K * scale, accumulated over k blocks.
    Grid (bh, q-block, k-block), k innermost sequential."""
    kk = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _update():
        q_blk = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_col = lse_ref[0][:, None]
        delta_col = delta_ref[0][:, None]
        p = _recompute_p(
            q_blk, k_blk, lse_col, scale=scale, causal=causal,
            q_pos0=(q_idx + q_offset_blocks) * block_q, k_pos0=kk * block_k,
            block_q=block_q, block_k=block_k)
        dp = jax.lax.dot_general(  # dO V^T  [block_q, block_k]
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_col) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last_q_pos = (q_idx + q_offset_blocks + 1) * block_q - 1

        @pl.when(last_q_pos >= kk * block_k)
        def _run():
            _update()
    else:
        _update()

    @pl.when(kk == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, ...] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float,
                    causal: bool, q_offset_blocks: int, num_q_blocks: int,
                    block_q: int, block_k: int):
    """dV = P^T dO and dK = (P * (dP - delta))^T Q, accumulated over q
    blocks. Grid (bh, k-block, q-block), q innermost sequential."""
    iq = pl.program_id(2)
    k_idx = pl.program_id(1)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _update():
        q_blk = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_col = lse_ref[0][:, None]
        delta_col = delta_ref[0][:, None]
        p = _recompute_p(
            q_blk, k_blk, lse_col, scale=scale, causal=causal,
            q_pos0=(iq + q_offset_blocks) * block_q, k_pos0=k_idx * block_k,
            block_q=block_q, block_k=block_k)
        dv_acc[...] += jax.lax.dot_general(  # P^T dO  [block_k, d]
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_col) * scale
        dk_acc[...] += jax.lax.dot_general(  # dS^T Q  [block_k, d]
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip q-blocks that lie entirely before this k-block (P == 0 there)
        last_q_pos = (iq + q_offset_blocks + 1) * block_q - 1

        @pl.when(last_q_pos >= k_idx * block_k)
        def _run():
            _update()
    else:
        _update()

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, ...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc[...].astype(dv_ref.dtype)


def _to_bh(x):
    """[B, T, H, D] -> [B*H, T, D]: grid programs own one (batch, head)."""
    batch, seq, heads, head_dim = x.shape
    return x.transpose(0, 2, 1, 3).reshape(batch * heads, seq, head_dim)


def _from_bh(x, batch, heads):
    bh, seq, head_dim = x.shape
    return x.reshape(batch, heads, seq, head_dim).transpose(0, 2, 1, 3)


_warned_vma_kwarg_missing = False


def _sds(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of ``like`` operands' vma type.

    Inside a vma-tracking ``shard_map`` (check_vma=True, the default),
    ``pallas_call`` outputs must declare how they vary over mesh axes —
    a kernel output varies exactly as much as its operands do. Outside
    shard_map (or on JAX versions without vma) fall back to the plain
    struct."""
    from .spmd import operand_vma

    vma = operand_vma(*like)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        # jax.typeof reports vma but ShapeDtypeStruct lacks the kwarg: a
        # JAX-version mismatch. The dropped vma will surface later as an
        # opaque check_vma error inside shard_map — name the cause here so
        # that error is attributable. Once per process, not per out-shape:
        # every fwd+bwd trace builds several structs.
        global _warned_vma_kwarg_missing
        if not _warned_vma_kwarg_missing:
            _warned_vma_kwarg_missing = True
            from ..core.logging import LOG

            LOG.warning(
                "this JAX version (%s) tracks vma types but "
                "jax.ShapeDtypeStruct does not accept a vma= kwarg; "
                "dropping the vma annotation on pallas_call out-shapes. "
                "If a downstream shard_map(check_vma=True) error mentions "
                "vma, this version mismatch is the cause.", jax.__version__)
        return jax.ShapeDtypeStruct(shape, dtype)


def _fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret, q_offset):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    num_k_blocks = seq_k // block_k
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        q_offset_blocks=q_offset // block_q, num_k_blocks=num_k_blocks,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(batch * heads, seq_q // block_q, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda bh, i, kk: (bh, i, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, i, kk: (bh, kk, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, i, kk: (bh, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda bh, i, kk: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i, kk: (bh, i)),
        ],
        out_shape=[
            _sds((batch * heads, seq_q, head_dim), q.dtype, q, k, v),
            _sds((batch * heads, seq_q), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb)
    return _from_bh(o, batch, heads), lse


def _bwd_impl(q, k, v, o, lse, do, causal, scale, block_q, block_k,
              interpret, q_offset):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    num_q_blocks = seq_q // block_q
    num_k_blocks = seq_k // block_k
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)
    ob, dob = _to_bh(o), _to_bh(do)
    # delta_i = sum_d dO_id O_id = sum_j dP_ij P_ij  (softmax Jacobian term)
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)  # [B*H, Tq]

    qkv_spec_q = pl.BlockSpec((1, block_q, head_dim),
                              lambda bh, i, kk: (bh, i, 0))
    qkv_spec_k = pl.BlockSpec((1, block_k, head_dim),
                              lambda bh, i, kk: (bh, kk, 0))
    row_spec = pl.BlockSpec((1, block_q), lambda bh, i, kk: (bh, i))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            q_offset_blocks=q_offset // block_q, num_k_blocks=num_k_blocks,
            block_q=block_q, block_k=block_k),
        grid=(batch * heads, num_q_blocks, num_k_blocks),
        in_specs=[qkv_spec_q, qkv_spec_k, qkv_spec_k, qkv_spec_q,
                  row_spec, row_spec],
        out_specs=qkv_spec_q,
        out_shape=_sds((batch * heads, seq_q, head_dim), q.dtype,
                       q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    # dK/dV grid: (bh, k-block, q-block) — the q dimension is innermost so
    # the (1, block_q, d) operands re-index by the LAST grid axis here
    kv_q_spec = pl.BlockSpec((1, block_q, head_dim),
                             lambda bh, kk, i: (bh, i, 0))
    kv_k_spec = pl.BlockSpec((1, block_k, head_dim),
                             lambda bh, kk, i: (bh, kk, 0))
    kv_row_spec = pl.BlockSpec((1, block_q), lambda bh, kk, i: (bh, i))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            q_offset_blocks=q_offset // block_q, num_q_blocks=num_q_blocks,
            block_q=block_q, block_k=block_k),
        grid=(batch * heads, num_k_blocks, num_q_blocks),
        in_specs=[kv_q_spec, kv_k_spec, kv_k_spec, kv_q_spec,
                  kv_row_spec, kv_row_spec],
        out_specs=[kv_k_spec, kv_k_spec],
        out_shape=[
            _sds((batch * heads, seq_k, head_dim), k.dtype, q, k, v, do),
            _sds((batch * heads, seq_k, head_dim), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, head_dim), jnp.float32),
                        pltpu.VMEM((block_k, head_dim), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    return (_from_bh(dq, batch, heads), _from_bh(dk, batch, heads),
            _from_bh(dv, batch, heads))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, q_offset):
    o, _ = _fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                     q_offset)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               q_offset):
    o, lse = _fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                       q_offset)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, q_offset, res,
               do):
    q, k, v, o, lse = res
    return _bwd_impl(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                     interpret, q_offset)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "q_offset"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None,
                    q_offset: int = 0) -> jax.Array:
    """Fused attention, shapes [batch, seq, heads, head_dim]. Differentiable
    (custom VJP with FlashAttention-2 recomputation kernels).

    ``q_offset`` shifts the global position of q (in elements) for causal
    masking — how ring attention uses a kernel per KV shard. Sequence
    lengths must be multiples of the block sizes (pad upstream; blocks
    auto-shrink to the sequence length when shorter).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    seq_q, seq_k = q.shape[1], k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be multiples of the "
            f"block sizes ({block_q}, {block_k}); pad inputs first.")
    if q_offset < 0 or q_offset % block_q:
        raise ValueError(
            "q_offset must be a non-negative multiple of block_q")
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret,
                  q_offset)
