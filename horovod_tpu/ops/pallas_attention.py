"""Pallas flash-attention forward kernel for TPU.

The reference contains no kernels at all — device math is delegated to
NCCL/MPI (SURVEY §2: "no CUDA kernels"). On TPU the hot op worth a custom
kernel in this framework's domain is attention (the long-context extension,
``parallel.ring_attention``): a fused blockwise softmax(QK^T)V that never
materializes the [T, T] score matrix in HBM and streams K/V through VMEM
one block at a time.

Design (per pallas_guide.md): 3-D grid (batch*heads, q-blocks, k-blocks)
with the k dimension innermost and sequential ("arbitrary" semantics); the
flash-attention accumulators (output, running max, running denominator)
live in VMEM scratch and persist across the k iterations of one q block.
Per-program VMEM footprint is O(block_q * d + block_k * d) — independent of
sequence length, so 16k+ contexts fit. Matmuls hit the MXU with f32
accumulation; masking and rescaling ride the VPU. Causal q-blocks skip
fully-masked k-blocks (`pl.when`), halving causal work.

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter, which is how the CPU test suite validates it.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _attention_kernel(q_ref, k_ref, v_ref, o_ref, o_acc, m_acc, l_acc, *,
                      scale: float, causal: bool, q_offset_blocks: int,
                      num_k_blocks: int, block_q: int, block_k: int):
    # program_id must be read at kernel top level: inside a pl.when body it
    # escapes the interpreter's scope (breaks interpret=True on CPU)
    kk = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    def _update():
        q_block = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
        k_blk = k_ref[0].astype(jnp.float32)            # [block_k, d]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(  # [block_q, block_k] on the MXU
            q_block, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = (q_idx + q_offset_blocks) * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
            k_pos = kk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m = m_acc[...]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        corr = jnp.where(m == _NEG_INF, 0.0, jnp.exp(m - m_new))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(m_new == _NEG_INF, 0.0, p)
        l_acc[...] = l_acc[...] * corr + p.sum(axis=1, keepdims=True)
        m_acc[...] = m_new
        o_acc[...] = o_acc[...] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip k-blocks that lie entirely in this q-block's future
        last_q_pos = (q_idx + q_offset_blocks + 1) * block_q - 1

        @pl.when(last_q_pos >= kk * block_k)
        def _run():
            _update()
    else:
        _update()

    @pl.when(kk == num_k_blocks - 1)
    def _finalize():
        o_ref[0, ...] = (o_acc[...] /
                         jnp.maximum(l_acc[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "interpret", "q_offset"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None,
                    q_offset: int = 0) -> jax.Array:
    """Fused attention, shapes [batch, seq, heads, head_dim].

    ``q_offset`` shifts the global position of q (in elements) for causal
    masking — how ring attention uses a kernel per KV shard. Sequence
    lengths must be multiples of the block sizes (pad upstream; blocks
    auto-shrink to the sequence length when shorter).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            f"sequence lengths ({seq_q}, {seq_k}) must be multiples of the "
            f"block sizes ({block_q}, {block_k}); pad inputs first.")
    if q_offset % block_q:
        raise ValueError("q_offset must be a multiple of block_q")
    num_k_blocks = seq_k // block_k

    # [B, T, H, D] -> [B*H, T, D]: grid programs own one (batch, head)
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(
            batch * heads, x.shape[1], head_dim)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)

    kernel = functools.partial(
        _attention_kernel, scale=scale, causal=causal,
        q_offset_blocks=q_offset // block_q, num_k_blocks=num_k_blocks,
        block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(batch * heads, seq_q // block_q, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda bh, i, kk: (bh, i, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, i, kk: (bh, kk, 0)),
            pl.BlockSpec((1, block_k, head_dim), lambda bh, i, kk: (bh, kk, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda bh, i, kk: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, seq_q, head_dim),
                                       q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(batch, heads, seq_q, head_dim).transpose(0, 2, 1, 3)
