"""Device data plane for the eager API: XLA collectives across processes.

This is the TPU-native analog of the reference's NCCL data plane: MPI (here:
the TCP controller) stays the *control* plane that negotiates an identical
ResponseList on every rank each cycle, and the actual bytes move as XLA
collectives over ICI/DCN (``operations.cc:1136-1207`` comm init,
``:1349-1446`` ops — all replaced by compiled ``psum``/``all_gather``
programs; there is no comm management because the JAX runtime owns it).

Legality argument (SURVEY §7 "hard parts"): XLA requires every process to
issue identical programs in identical order. The negotiated ResponseList is
byte-identical on every rank and responses are executed in list order, so
the sequence of compiled collectives — and therefore the XLA launch order —
is identical by construction. This is exactly the property the reference's
MPI_Bcast of the ResponseList guarantees for its NCCL launch order.

Eager tensors are per-*process* values (one rank == one process, the
reference's process model), so the collective world here is one lead device
per process; the SPMD path (``ops.spmd``) is where all chips of a host
participate. Fused allreduce buffers are padded to power-of-two buckets so
the number of distinct compiled programs stays O(log max-bytes) instead of
one per fused batch size (compilations are the TPU-side analog of the
reference's one-time NCCL comm setup cost).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..core.logging import LOG
from ..obs.registry import registry as _obs_metrics
from .messages import DataType, dtype_of

_MIN_BUCKET = 1024  # elements; below this padding cost is noise

# Observability plane (docs/metrics.md): the eager device plane executes
# once per negotiated batch, so these count real per-step work (unlike
# the trace-time SPMD counters). "post" charges the padded bucket at the
# negotiated wire dtype — plus scales for a quantized codec — per
# Compression.wire_cost, the single accounting definition.
_EAGER_BATCHES = _obs_metrics().counter(
    "horovod_eager_allreduce_batches_total",
    "Fused allreduce batches executed on the eager device plane",
    labels=("path",))
_EAGER_PRE = _obs_metrics().counter(
    "horovod_eager_wire_bytes_pre_total",
    "Uncompressed payload bytes entering eager device-plane allreduce",
    labels=("path",))
_EAGER_POST = _obs_metrics().counter(
    "horovod_eager_wire_bytes_post_total",
    "Estimated on-wire bytes after bucket padding and codec",
    labels=("path",))


def _next_bucket(n: int) -> int:
    return max(_MIN_BUCKET, 1 << max(0, math.ceil(math.log2(max(n, 1)))))


class XlaDataPlane:
    """Cross-process eager collectives over a one-device-per-rank mesh."""

    def __init__(self, topo) -> None:
        import jax

        if jax.process_count() != topo.size:
            raise RuntimeError(
                f"eager XLA data plane needs one JAX process per rank: world "
                f"size is {topo.size} but jax.process_count() is "
                f"{jax.process_count()}. Initialize the JAX distributed "
                f"runtime on every rank (jax.distributed.initialize) before "
                f"hvd.init(), or set HOROVOD_DATA_PLANE=host.")
        if jax.process_index() != topo.rank:
            raise RuntimeError(
                f"rank/process mismatch: HOROVOD_RANK={topo.rank} but "
                f"jax.process_index()={jax.process_index()}; the launcher "
                f"must assign ranks in JAX process order.")

        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self._jax = jax
        self._P = PartitionSpec
        self._size = topo.size
        leads: Dict[int, object] = {}
        for dev in jax.devices():
            prev = leads.get(dev.process_index)
            if prev is None or dev.id < prev.id:
                leads[dev.process_index] = dev
        devices = [leads[i] for i in range(topo.size)]
        self._mesh = Mesh(np.array(devices), ("hvd",))
        self._local_device = devices[topo.rank]
        self._platform = self._local_device.platform
        self._shard = NamedSharding(self._mesh, PartitionSpec("hvd"))
        self._replicated = NamedSharding(self._mesh, PartitionSpec())
        # Collective programs are issued from the engine's single background
        # thread, but guard anyway: launch order is the correctness invariant.
        self._lock = threading.Lock()
        self._fns: Dict[Tuple, object] = {}
        # Without x64, device_put silently demotes 64-bit arrays to 32-bit
        # (value corruption, not an error) — 64-bit wires must stay on the
        # host plane unless the user enabled x64.
        self._x64 = bool(jax.config.jax_enable_x64)
        LOG.debug("XLA eager data plane up: %d-process mesh on %s",
                  topo.size, self._platform)

    # -- dtype policy ---------------------------------------------------------

    def supports(self, dt: DataType) -> bool:
        """Deterministic per-dtype eligibility for device-plane *reduction*.
        Every rank sees the same negotiated dtype, so every rank makes the
        same choice and launch order stays identical; unsupported dtypes
        ride the host plane.

        bool is summed bytewise by the host plane (MPI_SUM semantics); XLA
        has no bool psum, so keep it off-device. uint16 has no stable XLA
        reduction on all backends, and 64-bit wires corrupt silently when
        x64 is off (see __init__)."""
        if dt in (DataType.INT64, DataType.FLOAT64) and not self._x64:
            return False
        return dt not in (DataType.BOOL, DataType.UINT16) and not (
            dt == DataType.FLOAT64 and self._platform != "cpu")

    def supports_move(self, dt: DataType) -> bool:
        """Eligibility for allgather/broadcast — data movement, so narrow
        dtypes qualify too (bool/uint16 ride as bytes; broadcast widens its
        wire, see ``broadcast``). 64-bit wires need x64 for the same
        demotion reason as ``supports``, and f64 never leaves the host on
        non-CPU backends (TPUs demote f64)."""
        if dt in (DataType.INT64, DataType.FLOAT64) and not self._x64:
            return False
        return not (dt == DataType.FLOAT64 and self._platform != "cpu")

    def supports_quantized(self, dt: DataType) -> bool:
        """Deterministic eligibility for the block-quantized (EQuARX)
        reduction wire, mirroring ``supports()``: decided from the
        NEGOTIATED dtype so every rank picks the same compiled program
        and launch order stays identical. Float reductions only — the
        quantized codec is a lossy float transform; integer/bool payloads
        must reduce exactly, so they keep the full-precision wire."""
        return self.supports(dt) and dt in (
            DataType.FLOAT32, DataType.FLOAT16, DataType.BFLOAT16)

    def supports_sparse(self, dt: DataType) -> bool:
        """Deterministic eligibility for the top-k sparse indices+values
        wire (docs/compression.md §sparse), decided from the NEGOTIATED
        dtype like ``supports_quantized``. float32 only: the wire's value
        block is f32 by layout (``ops.sparse_wire``), and widening other
        floats through it would launder precision invisibly."""
        return self.supports(dt) and dt == DataType.FLOAT32

    def _wire_parts(self, dtype) -> Tuple[object, object]:
        """(wire dtype, result dtype). CPU gloo lacks 16-bit float reductions,
        so f16/bf16 upcast to f32 on the wire — numerically strictly better
        than the reference's software fp16 MPI sum (``half.cc:43-75``); on
        TPU bf16 reduces natively on ICI."""
        import ml_dtypes

        if self._platform == "cpu" and dtype in (np.dtype(np.float16),
                                                 np.dtype(ml_dtypes.bfloat16)):
            return np.dtype(np.float32), dtype
        return dtype, dtype

    # -- compiled programs ----------------------------------------------------

    def _fn(self, kind: str, *key):
        def _build():
            import jax
            from jax import lax

            P = self._P
            if kind == "psum":
                body = lambda x: lax.psum(x, "hvd")  # noqa: E731
            elif kind == "qpsum":
                # Block-quantized fused allreduce (key = (codec,)): the
                # SAME wire math as the jit/SPMD path — shared pmax
                # scales, int8/fp8 all_to_all + all_gather, widened
                # accumulator — over the eager process mesh. The
                # per-bucket scale tensors ride inside the program as the
                # pmax wire; the fused buffer layout (bucket size, pack/
                # unpack) is identical to the psum path, so eligibility
                # (supports_quantized) is the only negotiation delta.
                from .compression import Compression
                from .spmd import quantized_allreduce

                q_codec = Compression.lookup(key[0])
                body = lambda x: quantized_allreduce(  # noqa: E731
                    x, "hvd", average=False, codec=q_codec)
            elif kind == "gather":
                body = lambda x: lax.all_gather(  # noqa: E731
                    x, "hvd", axis=0, tiled=True)
            else:  # bcast, key = (root,)
                root = key[0]

                def body(x):  # noqa: E306
                    import jax.numpy as jnp

                    # where, not multiply: non-root buffer contents are
                    # ignored by Horovod broadcast semantics, and Inf/NaN
                    # garbage there would survive a *0 mask as NaN
                    sel = lax.axis_index("hvd") == root
                    return lax.psum(
                        jnp.where(sel, x, jnp.zeros_like(x)), "hvd")

            # check_vma=False: the vma checker cannot statically infer that
            # a tiled all_gather output is replicated (psum it can); all
            # three bodies end in a collective whose output is identical on
            # every device, so declaring P() replication is sound.
            #
            # Buffer donation (docs/tensor-fusion.md, SNIPPETS [1]/[3]):
            # the fused input bucket is consumed by the reduction — it is
            # a freshly packed/padded staging buffer every call — so
            # donating it lets XLA reduce in place instead of holding
            # input + output buckets live at once. That halves the peak
            # device footprint of a flush, which is what keeps sub-buffer
            # churn (several buckets in flight per step) from doubling
            # device memory. Reduction kinds only: their per-partition
            # input and output shapes match, so the alias always lands
            # (asserted by reduce_donation_hlo); a gather's output is
            # size-times its input and could never alias.
            donate = (0,) if kind in ("psum", "qpsum", "bcast") else ()
            return jax.jit(jax.shard_map(
                body, mesh=self._mesh, in_specs=P("hvd"), out_specs=P(),
                check_vma=False), donate_argnums=donate)

        return self._local_fn((kind,) + key, _build)

    # -- fused reduce+apply (docs/tensor-fusion.md §fused apply) --------------

    def _reduce_apply_fn(self, rule, codec: str, gate: bool, denom: int):
        """The apply-fused bucket program (PAPERS 2305.06942): psum —
        or the block-quantized EQuARX decode when the negotiated codec
        asks for it — then the shared ``ApplyRule.apply_body`` (census,
        optional census gate, average divide, loss-scale unscale,
        optimizer leaf update), all in ONE compiled dispatch. Outputs
        ``(reduced, new_params, nan, inf, *new_slots)``: the raw reduced
        bucket rides along so consensus keeps digesting the bytes as
        received, PRE-apply. Donation covers the grad bucket (aliases
        the reduced output — per-partition shapes match, like the plain
        psum program) AND the param/slot buckets (alias their updated
        twins), so an apply-fused flush holds no duplicate buckets;
        ``reduce_apply_hlo`` is the audit surface."""
        def _build():
            import jax
            from jax import lax

            P = self._P
            nslots = rule.nslots

            def body(g, p, count, *slots):
                if codec != "none":
                    from .compression import Compression
                    from .spmd import quantized_allreduce

                    red = quantized_allreduce(
                        g, "hvd", average=False,
                        codec=Compression.lookup(codec))
                else:
                    red = lax.psum(g, "hvd")
                return (red,) + rule.apply_body(red, p, count, slots,
                                                gate, denom)

            in_specs = (P("hvd"), P(), P()) + (P(),) * nslots
            out_specs = (P(),) * (4 + nslots)
            donate = (0, 1) + tuple(3 + k for k in range(nslots))
            return jax.jit(jax.shard_map(
                body, mesh=self._mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False),
                donate_argnums=donate)

        return self._local_fn(
            ("rapply", rule.fingerprint, codec, gate, denom), _build)

    def _replicated_put(self, arr):
        """Host (or lead-device) value → replicated global array: the
        P() inputs of the reduce+apply program (param/slot buckets, the
        step count) — every process contributes its identical copy."""
        jax = self._jax
        a = jax.device_put(arr, self._local_device)
        return jax.make_array_from_single_device_arrays(
            a.shape, self._replicated, [a])

    def reduce_apply(self, grad_buf, param_buf, count: int, slot_bufs,
                     rule, codec: str = "none", gate: bool = False,
                     denom: int = 1):
        """Run the apply-fused program over pre-packed buckets.

        ``grad_buf`` is this rank's local fused gradient bucket (host
        numpy or device array, already padded to the negotiated power-
        of-two bucket); ``param_buf``/``slot_bufs`` are the replicated
        parameter and optimizer-slot buckets packed to the same layout.
        Returns ``(reduced, new_params, nan, inf, new_slots)`` as local
        per-process views (lead-device arrays)."""
        fn = self._reduce_apply_fn(rule, codec, gate, denom)
        args = [self._global_put(grad_buf),
                self._replicated_put(param_buf),
                self._replicated_put(np.int32(count))]
        args += [self._replicated_put(s) for s in slot_bufs]
        outs = fn(*args)
        local = [o.addressable_shards[0].data for o in outs]
        reduced, new_p, nan, inf = local[:4]
        return reduced, new_p, int(nan), int(inf), tuple(local[4:])

    def reduce_apply_hlo(self, n_elems: int, rule, dtype=np.float32,
                         codec: str = "none", gate: bool = False,
                         denom: int = 1) -> str:
        """Compiled-HLO text of the apply-fused program for an
        ``n_elems``-element batch — the donation audit surface: ONE
        module whose ``input_output_alias`` header must cover the grad
        bucket AND the param/slot buckets, or the single-dispatch flush
        silently degraded to copy-in/copy-out (the
        ``reduce_donation_hlo`` precedent)."""
        import jax

        bucket = _next_bucket(n_elems)
        wire_dt, _ = self._wire_parts(np.dtype(dtype))
        grad = jax.ShapeDtypeStruct((self._size * bucket,), wire_dt,
                                    sharding=self._shard)
        rep = lambda shape, dt: jax.ShapeDtypeStruct(  # noqa: E731
            shape, dt, sharding=self._replicated)
        args = [grad, rep((bucket,), wire_dt), rep((), np.int32)]
        args += [rep((bucket,), wire_dt)] * rule.nslots
        return self._reduce_apply_fn(rule, codec, gate, denom).lower(
            *args).compile().as_text()

    # -- ZeRO-1 sharded reduce+apply (docs/sharding.md) -----------------------

    def _reduce_scatter_apply_fn(self, rule, codec: str, gate: bool,
                                 denom: int):
        """The ZeRO-1 bucket program: reduce-scatter (or the quantized
        EQuARX scatter leg) hands each rank the reduced SUM of its OWN
        shard row, the shared ``ApplyRule.shard_apply_body`` updates the
        shard's parameters and slots from shard-resident optimizer
        state, and ONE all-gather lands the full updated parameters on
        every rank — reduce-scatter → local apply → all-gather as a
        single compiled dispatch (PAPERS 2305.06942 shape; SNIPPETS [2]
        mesh idiom). The nonfinite census runs over the reduce-scattered
        shard and is psum-med to the GLOBAL batch counts before gating,
        so the census gate fires on the identical collective verdict as
        the replicated program.

        Buffer layout is SHARD-major: each rank's local grad bucket is
        ``(size * shard_bucket,)`` with row r holding the slices rank r
        owns, so the tiled ``psum_scatter`` chunking IS the ownership
        map. Param bucket rides replicated in the same layout (its
        all-gathered update aliases it); slot buckets are SHARDED —
        each rank contributes and receives only its ``(shard_bucket,)``
        row, the 1/N memory claim. Outputs
        ``(red_full, new_params, nan, inf, *new_slot_shards)`` with
        ``red_full`` the all-gathered raw reduced bucket so consensus
        digests identical bytes on every rank, PRE-apply."""
        def _build():
            import jax
            from jax import lax

            P = self._P
            nslots = rule.nslots

            def body(g, p, count, *slot_shards):
                import jax.numpy as jnp

                if codec != "none":
                    from .compression import Compression
                    from .spmd import quantized_reducescatter

                    red = quantized_reducescatter(
                        g, "hvd", Compression.lookup(codec))
                else:
                    red = lax.psum_scatter(g, "hvd",
                                           scatter_dimension=0,
                                           tiled=True)
                nans = lax.psum(jnp.isnan(red).sum(), "hvd")
                infs = lax.psum((~jnp.isfinite(red)).sum(), "hvd") - nans
                r = lax.axis_index("hvd")
                shard = red.shape[0]
                p_sh = lax.dynamic_slice(p, (r * shard,), (shard,))
                new_p_sh, new_slots = rule.shard_apply_body(
                    red, p_sh, count, slot_shards, gate, denom,
                    nans, infs)
                new_p = lax.all_gather(new_p_sh, "hvd", axis=0,
                                       tiled=True)
                red_full = lax.all_gather(red, "hvd", axis=0, tiled=True)
                return (red_full, new_p, nans, infs) + tuple(new_slots)

            in_specs = (P("hvd"), P(), P()) + (P("hvd"),) * nslots
            out_specs = (P(), P(), P(), P()) + (P("hvd"),) * nslots
            # param aliases the gathered update (replicated in/out, same
            # shape) and every slot shard aliases its updated twin
            # (sharded in/out); the grad bucket cannot alias — its
            # per-partition input is size× the reduce-scattered shard.
            donate = (1,) + tuple(3 + k for k in range(nslots))
            return jax.jit(jax.shard_map(
                body, mesh=self._mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False),
                donate_argnums=donate)

        return self._local_fn(
            ("rsapply", rule.fingerprint, codec, gate, denom), _build)

    def reduce_scatter_apply(self, grad_rows, param_full, count: int,
                             slot_shards, rule, codec: str = "none",
                             gate: bool = False, denom: int = 1):
        """Run the ZeRO-1 program over pre-packed shard-major buckets.

        ``grad_rows`` is this rank's local ``(size * shard_bucket,)``
        grad bucket in shard-major layout; ``param_full`` the replicated
        full parameter bucket in the SAME layout; ``slot_shards`` this
        rank's ``(shard_bucket,)`` slot rows. Returns ``(red_full,
        new_params, nan, inf, new_slot_shards)`` — full reduced bucket
        and full updated params, but only the OWN slot shards."""
        shard_bucket = grad_rows.shape[0] // self._size
        self._account_zero1(shard_bucket, grad_rows.dtype.itemsize, codec)
        fn = self._reduce_scatter_apply_fn(rule, codec, gate, denom)
        args = [self._global_put(grad_rows),
                self._replicated_put(param_full),
                self._replicated_put(np.int32(count))]
        args += [self._global_put(s) for s in slot_shards]
        outs = fn(*args)
        local = [o.addressable_shards[0].data for o in outs]
        red_full, new_p, nan, inf = local[:4]
        return red_full, new_p, int(nan), int(inf), tuple(local[4:])

    def _account_zero1(self, shard_bucket: int, itemsize: int,
                       codec: str) -> None:
        """Charge one ZeRO-1 batch: the scatter leg moves the shard-major
        grad bucket (codec-compressed when negotiated), the gather leg
        the full f32 parameter bucket (parameters never quantize)."""
        full = self._size * shard_bucket
        _EAGER_BATCHES.labels(path="zero1").inc()
        _EAGER_PRE.labels(path="zero1").inc(2 * full * itemsize)
        if codec != "none":
            from .compression import Compression

            scatter = Compression.lookup(codec).wire_cost(
                full, self._size)[1]
        else:
            scatter = full * itemsize
        _EAGER_POST.labels(path="zero1").inc(scatter + full * itemsize)

    def reduce_scatter_apply_hlo(self, n_elems: int, rule,
                                 dtype=np.float32, codec: str = "none",
                                 gate: bool = False,
                                 denom: int = 1) -> str:
        """Compiled-HLO text of the ZeRO-1 program for an
        ``n_elems``-element batch — the donation audit surface: ONE
        module whose ``input_output_alias`` header must cover the param
        bucket and every slot shard, plus ``reduce-scatter``/
        ``all-gather`` (or their psum lowering on size-1 worlds) in the
        body (the ``reduce_apply_hlo`` precedent)."""
        import jax

        shard_bucket = _next_bucket(-(-n_elems // self._size))
        wire_dt, _ = self._wire_parts(np.dtype(dtype))
        full = self._size * shard_bucket
        grad = jax.ShapeDtypeStruct((self._size * full,), wire_dt,
                                    sharding=self._shard)
        rep = lambda shape, dt: jax.ShapeDtypeStruct(  # noqa: E731
            shape, dt, sharding=self._replicated)
        args = [grad, rep((full,), wire_dt), rep((), np.int32)]
        args += [jax.ShapeDtypeStruct((full,), wire_dt,
                                      sharding=self._shard)] * rule.nslots
        return self._reduce_scatter_apply_fn(rule, codec, gate, denom)\
            .lower(*args).compile().as_text()

    def reduce_donation_hlo(self, n_elems: int, dtype=np.float32,
                            codec: str = "none") -> str:
        """Compiled-HLO text of the fused-reduction program for an
        ``n_elems``-element batch — the donation audit surface: the
        module header must carry ``input_output_alias`` or the in-place
        flush silently degraded to copy-in/copy-out (tests and the
        dryrun scan for it, the docs/compression.md HLO-audit
        precedent)."""
        import jax

        bucket = _next_bucket(n_elems)
        wire_dt, _ = self._wire_parts(np.dtype(dtype))
        arg = jax.ShapeDtypeStruct((self._size * bucket,), wire_dt,
                                   sharding=self._shard)
        return self._reduce_fn(codec).lower(arg).compile().as_text()

    def _global_put(self, local):
        """Local shard (numpy or on-device array) → global array sharded
        one-block-per-process. device_put is the H2D for numpy and a no-op
        for arrays already on the lead device."""
        jax = self._jax
        arr = jax.device_put(local, self._local_device)
        shape = (self._size * local.shape[0],) + local.shape[1:]
        return jax.make_array_from_single_device_arrays(
            shape, self._shard, [arr])

    def _local_fn(self, key: Tuple, builder):
        """Double-checked compile cache: collective programs (via ``_fn``)
        and the local collective-free pack/unpack programs around the
        shared psum. Pack/unpack keys carry the fused batch's shape/dtype
        signature — stable across training steps, so steady state is all
        cache hits."""
        with self._lock:
            fn = self._fns.get(key)
        if fn is not None:
            return fn
        fn = builder()
        with self._lock:
            self._fns[key] = fn
        return fn

    # -- collectives ----------------------------------------------------------

    def _account_allreduce(self, path: str, n_elems: int,
                           in_itemsize: int, wire_dt, codec: str) -> None:
        """Charge one fused batch to the eager wire-byte families."""
        _EAGER_BATCHES.labels(path=path).inc()
        _EAGER_PRE.labels(path=path).inc(n_elems * in_itemsize)
        bucket = _next_bucket(n_elems)
        if codec != "none":
            from .compression import Compression

            post = Compression.lookup(codec).wire_cost(bucket, self._size)[1]
        else:
            post = bucket * np.dtype(wire_dt).itemsize
        _EAGER_POST.labels(path=path).inc(post)

    def _reduce_fn(self, codec: str = "none"):
        """The bucketed fused-reduction program: full-precision psum, or
        the block-quantized variant when the negotiated codec asks for it
        (callers already checked ``supports_quantized``)."""
        if codec != "none":
            return self._fn("qpsum", codec)
        return self._fn("psum")

    def allreduce_onchip(self, arrays: Sequence,
                         codec: str = "none") -> List:
        """Fused allreduce of device-resident ``jax.Array``s with ZERO host
        transfers: pack (local jit: cast+concat+pad to the bucket) → the
        SAME bucketed psum program the host-fed path issues → unpack
        (local jit: slice+reshape+cast back).

        Launch-order legality: the collective step reuses ``_fn("psum")``
        verbatim with the same bucket size the host path would compute for
        this batch, so a rank whose local tensors happened to be numpy and
        a rank holding jax arrays still execute byte-identical collective
        programs — only the collective-free pack/unpack differs per rank.
        This is the TPU analog of the reference's device tensors staying
        on-GPU through the NCCL fusion buffer (``operations.cc:1115-1208``)
        instead of staging through host memory.
        """
        jax = self._jax
        import jax.numpy as jnp
        from jax import lax

        in_dt = np.dtype(arrays[0].dtype)
        wire_dt, out_dt = self._wire_parts(in_dt)
        shapes = [tuple(int(s) for s in a.shape) for a in arrays]
        sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
        total = int(sum(sizes))
        bucket = _next_bucket(total)

        # Pack/unpack are PER-ENTRY programs keyed by the entry's shape
        # (offsets ride as dynamic scalars), NOT one program keyed by the
        # whole batch composition: fusion batches split at cycle
        # boundaries, so their composition shifts from cycle to cycle and
        # a composition-keyed program would recompile every cycle (a
        # measured 100x collapse), while per-entry programs are all cache
        # hits after the first step.
        buf = self._zeros_fn(bucket, wire_dt)()
        write = self._write_fn(in_dt, wire_dt)
        off = 0
        for a, n in zip(arrays, sizes):
            buf = write(buf, a, off)
            off += n
        self._account_allreduce("onchip", total, in_dt.itemsize, wire_dt,
                                codec)
        result = self._reduce_fn(codec)(self._global_put(buf))
        # out_specs=P(): replicated, so this process's single shard holds
        # the full reduced value, already on the lead device
        local = result.addressable_shards[0].data
        outs, off = [], 0
        for shape, n in zip(shapes, sizes):
            outs.append(
                self._read_fn(shape, n, wire_dt, out_dt, bucket)(local, off))
            off += n
        return outs

    # -- shared on-chip pack/unpack programs ----------------------------------
    # ONE definition each: the host-fed and device-resident paths must stay
    # byte-equivalent in bucket math and wire casts for cross-rank
    # launch-order legality, so the building blocks live here and nowhere
    # else.

    def _zeros_fn(self, bucket: int, wire_dt):
        def _build():
            import jax
            import jax.numpy as jnp

            return jax.jit(lambda: jnp.zeros((bucket,), wire_dt))
        return self._local_fn(("zeros", bucket, str(wire_dt)), _build)

    def _write_fn(self, in_dt, wire_dt):
        def _build():
            import jax
            from jax import lax

            def _write(buf, x, off):
                return lax.dynamic_update_slice(
                    buf, x.astype(wire_dt).reshape(-1), (off,))
            # donating the bucket keeps the chain of writes in-place on
            # backends that support donation; CPU ignores it with a
            # one-time note. One program per dtype pair — jit specializes
            # per input shape internally, so no shape in the cache key.
            return jax.jit(_write, donate_argnums=(0,))
        return self._local_fn(("pack1", str(in_dt), str(wire_dt)), _build)

    def _read_fn(self, shape, n: int, wire_dt, out_dt, bucket: int):
        def _build():
            import jax
            from jax import lax

            def _read(buf, off):
                return lax.dynamic_slice(
                    buf, (off,), (n,)).astype(out_dt).reshape(shape)
            return jax.jit(_read)
        return self._local_fn(
            ("unpack1", tuple(shape), n, str(wire_dt), str(out_dt), bucket),
            _build)

    @staticmethod
    def _bcast_wire_src(dtype) -> np.dtype:
        """Pre-wire widening for broadcast: the psum wire needs a dtype
        with a stable XLA reduction, so bool and sub-32-bit ints widen to
        int32 (lossless, cast back exact). Shared by the host-fed and
        on-chip paths — they must agree or mixed-input ranks diverge."""
        dtype = np.dtype(dtype)
        if dtype == np.bool_ or dtype in (
                np.dtype(np.uint8), np.dtype(np.int8),
                np.dtype(np.uint16), np.dtype(np.int16)):
            return np.dtype(np.int32)
        return dtype

    @staticmethod
    def _gather_rows(tail_shape, sizes: Sequence[int]) -> int:
        """Row bucket for ragged allgather: power-of-two over the largest
        contribution, with the floor scaled by row width so it stays
        ~_MIN_BUCKET *elements* (a flat 1024-row floor would blow up wide
        rows: (8, 65536) would pad 2 MB to 256 MB). Shared by the
        host-fed and on-chip paths — they must agree or mixed-input ranks
        issue different gather programs."""
        row_elems = max(1, int(np.prod(tail_shape, dtype=np.int64)))
        min_rows = max(1, -(-_MIN_BUCKET // row_elems))
        return max(min_rows,
                   1 << max(0, math.ceil(math.log2(max(max(sizes), 1)))))

    def broadcast_onchip(self, arr, root: int):
        """Device-resident broadcast of one ``jax.Array``: cast/pad on
        device, then the SAME root-keyed masked-psum program the host-fed
        ``broadcast`` issues (same widening policy, same bucket), then
        cast back — launch-compatible with ranks feeding numpy."""
        out_np = np.dtype(arr.dtype)
        wire_dt, _ = self._wire_parts(self._bcast_wire_src(out_np))
        shape = tuple(int(s) for s in arr.shape)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        bucket = _next_bucket(n)
        buf = self._write_fn(out_np, wire_dt)(
            self._zeros_fn(bucket, wire_dt)(), arr, 0)
        result = self._fn("bcast", root)(self._global_put(buf))
        local = result.addressable_shards[0].data
        return self._read_fn(shape, n, wire_dt, out_np, bucket)(local, 0)

    def allgather_onchip(self, arr, sizes: Sequence[int]):
        """Device-resident ragged allgather of one ``jax.Array``: pad rows
        on device, run the SAME tiled all_gather program the host-fed path
        issues (same row bucket), then slice+concat the valid blocks on
        device. The trim program is keyed by the negotiated ``sizes``
        tuple — stable across the steps of a training loop."""
        jax = self._jax
        import jax.numpy as jnp

        shape = tuple(int(s) for s in arr.shape)
        dt = np.dtype(arr.dtype)
        rows = self._gather_rows(shape[1:], sizes)
        sizes = tuple(int(s) for s in sizes)

        def _build_pad():
            def _pad(x):
                return jnp.zeros((rows,) + shape[1:], dt).at[
                    :x.shape[0]].set(x)
            return jax.jit(_pad)

        def _build_trim():
            def _trim(g):
                blocks = [g[r * rows:r * rows + valid]
                          for r, valid in enumerate(sizes)]
                return blocks[0] if len(blocks) == 1 else \
                    jnp.concatenate(blocks, axis=0)
            return jax.jit(_trim)

        pad = self._local_fn(("padrows", shape, str(dt), rows), _build_pad)
        gathered = self._fn("gather")(self._global_put(pad(arr)))
        local = gathered.addressable_shards[0].data
        trim = self._local_fn(
            ("trimrows", shape[1:], str(dt), rows, sizes), _build_trim)
        return trim(local)

    # -- sparse top-k wire (docs/compression.md §sparse) ----------------------

    def _sparse_select_fn(self, n: int, k: int, feedback: bool):
        """Per-ENTRY compiled top-k select (collective-free): corrected =
        x (+ residual), ``lax.top_k`` over |corrected| → (idx, vals) and,
        with error feedback, the new residual (corrected with the selected
        rows zeroed). Keyed (n, k) — per-entry like the pack/unpack
        programs, NOT per batch composition, so steady state is all cache
        hits (the measured-100x-collapse precedent)."""
        def _build():
            import jax
            import jax.numpy as jnp
            from jax import lax

            if feedback:
                def _sel(x, res):
                    corrected = x.reshape(-1).astype(jnp.float32) + res
                    _, idx = lax.top_k(jnp.abs(corrected), k)
                    return (idx, corrected[idx],
                            corrected.at[idx].set(0.0))
            else:
                def _sel(x):
                    corrected = x.reshape(-1).astype(jnp.float32)
                    _, idx = lax.top_k(jnp.abs(corrected), k)
                    return idx, corrected[idx]
            return jax.jit(_sel)
        return self._local_fn(("sptopk", n, k, feedback), _build)

    def _sparse_decode_fn(self, n: int, shape, out_dt):
        """Per-ENTRY compiled scatter-add decode of the gathered pairs:
        ``zeros(n).at[clip(idx)].add(vals)`` — the SAME clipping rule as
        the host decode (``sparse_wire.scatter_sum``): a corrupt index
        diverges, it never raises asymmetrically."""
        def _build():
            import jax
            import jax.numpy as jnp

            def _dec(g_idx, g_vals):
                dense = jnp.zeros((n,), jnp.float32).at[
                    jnp.clip(g_idx, 0, n - 1)].add(g_vals)
                return dense.astype(out_dt).reshape(shape)
            return jax.jit(_dec)
        return self._local_fn(
            ("spdec", n, tuple(shape), str(out_dt)), _build)

    def sparse_allreduce_onchip(self, arrays: Sequence, residuals,
                                codec, feedback: bool):
        """Fused sparse allreduce with ZERO full-buffer host transfers:
        per entry, the compiled select program picks the top-k pairs on
        device, the pairs ride the SAME tiled all_gather program the
        dense allgather path issues (idx then vals — two gathers per
        entry, launch-order identical on every rank because k and n are
        functions of the negotiated shapes), and the compiled scatter-add
        decodes back to the dense SUM.  Residuals stay device-resident.

        Returns ``(results, new_residuals, stats)`` where stats carries
        the batch's selected/dropped/wire-byte/residual-norm² tallies
        for the ``horovod_sparse_*`` families."""
        jax = self._jax
        import jax.numpy as jnp

        results, new_residuals = [], []
        total_k = total_n = wire = 0
        res_norm2 = 0.0
        for a, res in zip(arrays, residuals):
            shape = tuple(int(s) for s in a.shape)
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            k = codec.k_of(n)
            dev = jax.device_put(a, self._local_device)
            if feedback:
                r = res if res is not None else np.zeros((n,), np.float32)
                r_dev = jax.device_put(r, self._local_device)
                idx_a, vals_a, nres = self._sparse_select_fn(
                    n, k, True)(dev, r_dev)
                new_residuals.append(nres)
                res_norm2 += float(jnp.vdot(nres, nres))
            else:
                idx_a, vals_a = self._sparse_select_fn(n, k, False)(dev)
                new_residuals.append(None)
            g_idx = self._fn("gather")(self._global_put(idx_a))
            g_vals = self._fn("gather")(self._global_put(vals_a))
            results.append(self._sparse_decode_fn(
                n, shape, np.dtype(a.dtype))(
                g_idx.addressable_shards[0].data,
                g_vals.addressable_shards[0].data))
            total_k += k
            total_n += n
            wire += k * 8
        # Direct accounting, not _account_allreduce: the sparse gathers
        # are exact-size (k per entry), never bucket-padded, so charging
        # a power-of-two bucket would overstate the wire.
        _EAGER_BATCHES.labels(path="sparse").inc()
        _EAGER_PRE.labels(path="sparse").inc(total_n * 4)
        _EAGER_POST.labels(path="sparse").inc(wire)
        stats = {"selected": total_k, "dropped": total_n - total_k,
                 "wire_bytes": wire, "residual_norm2": res_norm2}
        return results, new_residuals, stats

    def tensorwatch_stats(self, arr) -> dict:
        """Device-side per-tensor numerics census for the gradient
        observatory (docs/tensorwatch.md): ONE compiled collective-free
        program per dtype computing norm², max|g|, nonzero count, the
        coarse log₂-magnitude occupancy histogram, and the top-k
        mass-coverage curve — so a sampled device-resident batch syncs
        a handful of scalars (plus the fixed 32-bin histogram) instead
        of pulling buffers to host (the ``nonfinite_counts`` two-scalar
        census pattern). Sampled steps only; never fused into the
        reduce program itself, which is what keeps the disabled-path
        HLO audit trivially clean."""
        def _build():
            import jax
            import jax.numpy as jnp

            from ..obs.tensorwatch import (
                LOG2_HIST_BINS,
                LOG2_HIST_MIN,
                TOPK_FRACTIONS,
            )

            def _stats(x):
                flat = x.reshape(-1).astype(jnp.float32)
                a = jnp.abs(flat)
                absmax = jnp.max(a) if flat.shape[0] else jnp.float32(0)
                # Scaled accumulation: the host twin sums squares in
                # float64 ("norm² of an fp16-ish tensor must not
                # overflow the measurement") but x64 is off in-program,
                # so divide by absmax first — every term ≤ 1, the f32
                # accumulator cannot overflow — and the host recombines
                # absmax²·Σ in Python float64. The top-k fractions are
                # ratios of the SAME scaled sums, so scaling cancels.
                denom = jnp.where(absmax > 0, absmax, jnp.float32(1))
                s = a / denom
                a2 = s * s
                norm2_scaled = jnp.sum(a2)
                nnz = jnp.count_nonzero(flat)
                e = jnp.clip(
                    jnp.floor(jnp.log2(jnp.where(a > 0, a, 1.0))),
                    LOG2_HIST_MIN, LOG2_HIST_MIN + LOG2_HIST_BINS - 1)
                bins = jnp.where(a > 0,
                                 (e - LOG2_HIST_MIN).astype(jnp.int32),
                                 LOG2_HIST_BINS)
                hist = jnp.bincount(bins,
                                    length=LOG2_HIST_BINS + 1)[
                    :LOG2_HIST_BINS]
                order = jnp.sort(a2)[::-1]
                cum = jnp.cumsum(order)
                total = jnp.maximum(cum[-1], jnp.float32(1e-30))
                n = flat.shape[0]
                fracs = []
                for _, q in TOPK_FRACTIONS:
                    # n is trace-time static, so the top-k index is too
                    k = max(0, min(n - 1, int(math.ceil(q * n)) - 1))
                    fracs.append(cum[k] / total)
                return (norm2_scaled, absmax, nnz, hist) + tuple(fracs)
            return jax.jit(_stats)

        from ..obs.tensorwatch import TOPK_FRACTIONS

        fn = self._local_fn(
            ("twstats", str(np.dtype(arr.dtype))), _build)
        out = fn(arr)
        norm2_scaled, absmax, nnz, hist = out[:4]
        fracs = out[4:]
        n = int(np.prod([int(s) for s in arr.shape] or [1],
                        dtype=np.int64))
        return {
            "elems": n,
            # recombined in Python float64 (see _stats)
            "norm2": float(absmax) * float(absmax)
            * float(norm2_scaled),
            "absmax": float(absmax),
            "nnz": int(nnz),
            "log2_hist": [int(c) for c in np.asarray(hist)],
            "topk": {key: float(f)
                     for (key, _), f in zip(TOPK_FRACTIONS, fracs)},
        }

    def tensorwatch_norm2(self, arr) -> float:
        """Device-side norm² alone — the observatory's PRE-reduce local
        measurement needs only this scalar (the skew detector's input),
        so a sampled step must not pay the full stats program twice per
        tensor (docs/tensorwatch.md)."""
        def _build():
            import jax
            import jax.numpy as jnp

            def _norm2(x):
                flat = x.reshape(-1).astype(jnp.float32)
                a = jnp.abs(flat)
                absmax = jnp.max(a) if flat.shape[0] else jnp.float32(0)
                # scaled accumulation against f32 overflow, recombined
                # on the host in float64 (see tensorwatch_stats)
                denom = jnp.where(absmax > 0, absmax, jnp.float32(1))
                s = a / denom
                return absmax, jnp.sum(s * s)
            return jax.jit(_norm2)

        fn = self._local_fn(
            ("twnorm2", str(np.dtype(arr.dtype))), _build)
        absmax, scaled = fn(arr)
        return float(absmax) * float(absmax) * float(scaled)

    def codec_snr(self, arr, codec: str) -> Tuple[float, float]:
        """Device-side decode-error measurement for the observatory:
        the compiled ``ops.spmd.codec_roundtrip`` (collective-free,
        local block scales) over this rank's contribution, returning
        ``(signal_power, error_power)`` — two scalars synced, no D2H of
        the buffer (docs/tensorwatch.md)."""
        def _build():
            import jax
            import jax.numpy as jnp
            from jax import lax

            from .compression import Compression
            from .spmd import codec_roundtrip

            c = Compression.lookup(codec)
            size = self._size
            if getattr(c, "sparse", False):
                # Sparse "decode error" is SELECTION error: the energy
                # the top-k pass drops. k is static at trace time (the
                # jit re-specializes per input shape), so top_k compiles
                # exact-size — no roundtrip buffer needed.
                def _rt(x):
                    flat = x.reshape(-1).astype(jnp.float32)
                    k = max(c.k_of(flat.shape[0]), 1)
                    sig = jnp.sum(flat * flat)
                    vals, _ = lax.top_k(jnp.abs(flat), k)
                    return sig, jnp.maximum(
                        sig - jnp.sum(vals * vals), 0.0)
                return jax.jit(_rt)
            return jax.jit(lambda x: codec_roundtrip(x, c, size))

        fn = self._local_fn(("twsnr", codec), _build)
        sp, ep = fn(arr)
        return float(sp), float(ep)

    def nonfinite_counts(self, arr) -> Tuple[int, int]:
        """Device-side non-finite census for the gradient sentry
        (docs/integrity.md): one compiled ``(nan_count, inf_count)``
        program per dtype, so screening a device-resident reduced batch
        syncs two scalars instead of pulling the whole buffer to host.
        Collective-free — safe to run on any rank at any time."""
        def _build():
            import jax
            import jax.numpy as jnp

            def _counts(x):
                nans = jnp.isnan(x).sum()
                return nans, (~jnp.isfinite(x)).sum() - nans
            return jax.jit(_counts)

        fn = self._local_fn(("nonfinite", str(np.dtype(arr.dtype))),
                            _build)
        n_nan, n_inf = fn(arr)
        return int(n_nan), int(n_inf)

    def allreduce(self, buf: np.ndarray, codec: str = "none") -> np.ndarray:
        """Sum a flat (possibly fused) buffer across all ranks."""
        wire_dt, out_dt = self._wire_parts(buf.dtype)
        n = buf.size
        self._account_allreduce("host", n, buf.dtype.itemsize, wire_dt,
                                codec)
        padded = np.zeros((_next_bucket(n),), dtype=wire_dt)
        padded[:n] = buf
        result = self._reduce_fn(codec)(self._global_put(padded))
        # always copy: np.asarray of a jax Array is a read-only view of its
        # host cache, and callers (torch front-end in-place grads) need a
        # writable result — the host plane copies for the same reason
        return np.array(np.asarray(result)[:n], dtype=out_dt)

    def allgather(self, arr: np.ndarray,
                  sizes: Sequence[int]) -> np.ndarray:
        """Concatenate per-rank arrays with ragged first dims (the
        recvcounts/displacements logic of ``operations.cc:843-927``, done as
        pad → tiled all_gather → trim)."""
        rows = self._gather_rows(arr.shape[1:], sizes)
        padded = np.zeros((rows,) + arr.shape[1:], dtype=arr.dtype)
        padded[:arr.shape[0]] = arr
        gathered = np.asarray(self._fn("gather")(self._global_put(padded)))
        blocks: List[np.ndarray] = []
        for r, valid in enumerate(sizes):
            blocks.append(gathered[r * rows:r * rows + valid])
        return np.concatenate(blocks, axis=0)

    def broadcast(self, arr: np.ndarray, root: int) -> np.ndarray:
        """Masked psum from root: only root's slot is selected, so the sum
        IS the root's bytes — one compiled program per root instead of a
        ppermute chain. Pre-wire widening per ``_bcast_wire_src``; f16/bf16
        widen on CPU via ``_wire_parts``."""
        out_dt = arr.dtype
        wire_src = self._bcast_wire_src(arr.dtype)
        if wire_src != arr.dtype:
            arr = arr.astype(wire_src)
        wire_dt, _ = self._wire_parts(arr.dtype)
        flat = np.ascontiguousarray(arr, dtype=wire_dt).reshape(-1)
        out = self.allreduce_masked(flat, root)
        return out.astype(out_dt, copy=False).reshape(arr.shape)

    def allreduce_masked(self, buf: np.ndarray, root: int) -> np.ndarray:
        n = buf.size
        padded = np.zeros((_next_bucket(n),), dtype=buf.dtype)
        padded[:n] = buf
        result = self._fn("bcast", root)(self._global_put(padded))
        return np.array(np.asarray(result)[:n])  # writable, see allreduce
