"""Steady-state negotiation bypass: a capacity-bounded response cache.

Upstream Horovod's answer to the coordinator metadata cycle being the
latency floor of the design (the 1802.05799 paper's own observation; the
MVAPICH characterization in 1810.11112 measures negotiation/launch overhead
dominating small-tensor allreduce): once training reaches steady state,
every rank submits the *same* tensor set in the *same* order every step, so
re-shipping the full ``RequestList``/``ResponseList`` and re-running table
insertion + fusion planning every cycle is pure overhead. Upstream grew a
``response_cache.cc`` keyed by a per-cycle bitvector; this module is that
design on our TCP control plane.

One class serves both roles:

* **rank side** (the engine): ``plan_cycle`` decides whether the cycle's
  submissions are fully covered by cached fused responses — if so the rank
  ships a fixed-size cache-bit vector (``messages.CacheRequest``) instead of
  its ``RequestList``; ``accept_ack`` replays the cached fused responses a
  ``messages.CacheHitAck`` references by position.
* **coordinator side** (the ``ControllerService``): a mirror of the same
  cache expands cache-bit cycles back into requests when any rank missed,
  and materializes the effective ``ResponseList`` (for the payload exchange
  and autotuner) when every rank hit.

Coherence is by construction, not by synchronization: every state
transition derives ONLY from data that is identical on all ranks — the
broadcast ``ResponseList`` (insert/touch in response order) and the
``CacheHitAck`` (touch in position order). Lookups never touch LRU state
(a rank-local touch would diverge: ranks submit the same tensor in
different cycles around a partial step). With identical transition streams,
insert order, LRU order, and eviction choices — and therefore bit
POSITIONS — stay identical everywhere, which is what makes the bitvector
meaningful without ever shipping cache contents.

Invalidation is generation-stamped: the coordinator owns an integer
generation seeded from the elastic world epoch
(``HOROVOD_ELASTIC_EPOCH`` — a relaunched world can never validate against
a predecessor's cache state) and bumps it on any event that stales cached
FUSED LAYOUTS (the autotuner moving ``HOROVOD_FUSION_THRESHOLD`` is the
one that bites: repacking changes which batches exist). The new generation
rides the next cycle response (list or ack); a rank seeing a generation it
does not hold clears its cache, adopts, and skips inserting from that
response (it was planned pre-bump). Codec switches (``HOROVOD_COMPRESSION``)
and shape/dtype changes need no generation: the codec and shape are part of
the request identity, so they simply miss.

Fault tolerance (docs/chaos.md): every cache state transition rides the
request/response wire, so exactly-once delivery is load-bearing — a resent
cycle whose response frame was lost to a transport fault must not re-apply
its insert/touch on the coordinator mirror, or positions diverge silently.
That guarantee lives in the wire layer: ``BasicClient.request`` retries
under a per-request sequence number and ``BasicService`` replays the stored
response instead of re-invoking the cycle handler, so ``insert_cycle``/
``touch`` run exactly once per logical cycle no matter how many times its
frames were dropped, delayed, or corrupted in transit.

Only ALLREDUCE responses are cached: their request identity is equal on
every rank (the negotiator errors on dtype/shape/codec divergence), so one
coordinator mirror can reconstruct any rank's requests. Allgather's ragged
first dim and broadcast's root-relative shapes are per-rank — they take the
full path, which steady-state training does not care about (the hot loop is
gradient allreduce).

``HOROVOD_CACHE_CAPACITY`` (default 1024) bounds entries; ``0`` disables
the bypass entirely. See docs/response-cache.md.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..obs.registry import registry as _metrics
from .messages import (
    DataType,
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
)

# Observability plane (docs/metrics.md): rank-side bypass counters. The
# per-cache hit_cycles/miss_cycles attributes stay (cache_stats(), the
# timeline counter track); these aggregate process-wide for exposition.
_HIT_CYCLES = _metrics().counter(
    "horovod_cache_hit_cycles_total",
    "Negotiation cycles bypassed via the response-cache bit vector")
_MISS_CYCLES = _metrics().counter(
    "horovod_cache_miss_cycles_total",
    "Negotiation cycles that shipped a full RequestList")

# A generation namespace per elastic world epoch: epochs are small ints
# (restart counts), generations bump at autotune cadence — 2^32 bumps per
# epoch is unreachable, so stamped generations never collide across epochs.
_EPOCH_STRIDE = 1 << 32


def _default_epoch() -> int:
    import os

    from ..core import config as _config

    return int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))


def request_identity(req: Request) -> Tuple:
    """The full identity a cached response is keyed by: name, op, dtype,
    shape (which fixes payload bytes), codec, and the fused-apply rule
    fingerprint (docs/tensor-fusion.md §fused apply — an optimizer
    hyperparameter change is a new fingerprint and must MISS, never
    replay a layout negotiated under a different apply program).
    ``request_rank`` is excluded — allreduce identities are
    rank-invariant by negotiation contract."""
    return (req.tensor_name, int(req.request_type), int(req.tensor_type),
            tuple(req.tensor_shape), getattr(req, "codec", "none"),
            getattr(req, "apply_fingerprint", ""))


def bits_of(positions: List[int], capacity: int) -> bytes:
    """Fixed-size bitvector (``capacity`` bits) with ``positions`` set —
    the whole per-cycle negotiation payload of a steady-state rank."""
    out = bytearray((capacity + 7) // 8)
    for pos in positions:
        out[pos >> 3] |= 1 << (pos & 7)
    return bytes(out)


def and_bits(chunks: List[bytes]) -> bytes:
    """Fixed-size AND over equal-length cache-bit vectors — the island
    head's steady-state merge (docs/hierarchy.md): positions EVERY member
    hit. Raises on ragged inputs (capacity desync is a loud error on the
    flat path too, never a silent truncation)."""
    if not chunks:
        return b""
    length = len(chunks[0])
    for chunk in chunks[1:]:
        if len(chunk) != length:
            raise ValueError(
                f"cache-bit vectors differ in size ({len(chunk)} vs "
                f"{length} bytes); HOROVOD_CACHE_CAPACITY must be "
                f"identical on every rank")
    out = bytearray(chunks[0])
    for chunk in chunks[1:]:
        for i, byte in enumerate(chunk):
            out[i] &= byte
    return bytes(out)


def positions_of(bits: bytes) -> List[int]:
    out: List[int] = []
    for byte_idx, byte in enumerate(bits):
        while byte:
            low = byte & -byte
            out.append((byte_idx << 3) + low.bit_length() - 1)
            byte &= byte - 1
    return out


@dataclass
class _Entry:
    """One cached FUSED response: the ordered identities it covers (one per
    tensor in ``response.tensor_names``) plus the replayable Response."""

    identities: Tuple[Tuple, ...]
    response: Response = field(repr=False)


class ResponseCache:
    """Deterministic capacity-bounded LRU over fused allreduce responses.

    Not thread-safe by itself: the engine drives its copy from the
    background-loop thread only, the service from inside the cycle
    rendezvous' single compute call.
    """

    def __init__(self, capacity: int, epoch: Optional[int] = None) -> None:
        self.capacity = max(int(capacity), 0)
        if epoch is None:
            epoch = _default_epoch()
        self.generation = epoch * _EPOCH_STRIDE
        # position -> entry, in LRU order (first = least recently used)
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._by_identity: Dict[Tuple, int] = {}
        self._by_batch: Dict[Tuple, int] = {}
        self._free: List[int] = []  # heap of reusable position slots
        self._next_pos = 0
        # observability (rank side): cycles bypassed vs negotiated
        self.hit_cycles = 0
        self.miss_cycles = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- rank side ------------------------------------------------------------

    def plan_cycle(self, requests: List[Request]) -> Optional[List[int]]:
        """Positions (sorted) whose cached batches EXACTLY cover this
        cycle's submissions, or None when any request misses or covers a
        batch only partially (a partial batch cannot replay: the fused
        response names tensors this cycle did not submit). An empty
        submission list is a (trivial) hit — idle ticks ride the bitvector
        too. Read-only: never touches LRU state (see module docstring)."""
        if not self.enabled:
            return None
        covered: Dict[int, int] = {}
        for req in requests:
            pos = self._by_identity.get(request_identity(req))
            if pos is None:
                return None
            covered[pos] = covered.get(pos, 0) + 1
        for pos, count in covered.items():
            if count != len(self._entries[pos].identities):
                return None
        return sorted(covered)

    def accept_ack(self, ack) -> List[Response]:
        """Replay the fused responses an all-ranks-hit ack references, then
        apply its LRU touch — or, when the ack carries a NEW generation
        (the coordinator invalidated mid-cycle), clear instead: the replay
        itself is still valid (it was planned under the generation this
        rank sent), but nothing after it may hit the stale state."""
        responses = [self.response_at(pos) for pos in ack.positions]
        if ack.generation == self.generation:
            self.touch(ack.positions)
        else:
            self.clear(ack.generation)
        self.hit_cycles += 1
        _HIT_CYCLES.inc()
        return responses

    def accept_response_list(self, response_list: ResponseList,
                             requests_by_name: Dict[str, Request]) -> None:
        """Apply a full negotiated cycle: adopt a bumped generation (clear,
        skip insertion — those responses were planned pre-bump) or insert/
        touch the cycle's cacheable responses. ``requests_by_name`` supplies
        the identities (the rank's own in-flight requests; the coordinator
        passes the union of the cycle's expanded request lists — equal for
        allreduce by negotiation contract)."""
        if not self.enabled:
            return
        generation = getattr(response_list, "cache_generation", None)
        if generation is None:
            # Pre-cache coordinator (native controller wire, or a service
            # built without a cache): nothing to stay coherent WITH. The
            # engine disables its cache when it sees this.
            return
        self.miss_cycles += 1
        _MISS_CYCLES.inc()
        if generation != self.generation:
            self.clear(generation)
            return
        if response_list.shutdown:
            return  # the world is over; keep state untouched for waiters
        self.insert_cycle(requests_by_name, response_list.responses)

    # -- coordinator side -----------------------------------------------------

    def expand(self, rank: int, positions: List[int]) -> RequestList:
        """Reconstruct the RequestList a cache-bit cycle stands for (the
        miss/partial path: some OTHER rank missed, so this rank's compact
        submission must re-enter normal negotiation)."""
        requests: List[Request] = []
        for pos in sorted(positions):
            entry = self._entries.get(pos)
            if entry is None:
                raise RuntimeError(
                    f"response cache desync: rank {rank} referenced cache "
                    f"position {pos} the coordinator does not hold; "
                    f"HOROVOD_CACHE_CAPACITY must be identical on every "
                    f"rank")
            for name, rtype, dtype, shape, codec, apply_fp in \
                    entry.identities:
                requests.append(Request(
                    request_rank=rank, request_type=RequestType(rtype),
                    tensor_name=name, tensor_type=DataType(dtype),
                    tensor_shape=shape, codec=codec,
                    apply_fingerprint=apply_fp))
        return RequestList(rank=rank, requests=requests)

    def response_at(self, position: int) -> Response:
        entry = self._entries.get(position)
        if entry is None:
            raise RuntimeError(
                f"response cache desync: no entry at position {position}")
        return entry.response

    # -- shared state transitions (identical stream on every rank) -----------

    def touch(self, positions: List[int]) -> None:
        for pos in sorted(positions):
            self._entries.move_to_end(pos)

    def clear(self, generation: int) -> None:
        self._entries.clear()
        self._by_identity.clear()
        self._by_batch.clear()
        self._free = []
        self._next_pos = 0
        self.generation = generation

    def bump(self) -> None:
        """Invalidate everything under a fresh generation (fusion knob
        moved, membership changed): coordinator-side; ranks follow via the
        generation stamped on the next cycle response."""
        self.clear(self.generation + 1)

    def insert_cycle(self, requests_by_name: Dict[str, Request],
                     responses: List[Response]) -> None:
        """Insert/touch this cycle's cacheable responses, in response
        order. Non-allreduce and ERROR responses, and responses naming a
        tensor without a known request (an escalation-injected error names
        tensors only SOME ranks submitted), are skipped — identically
        everywhere, since the skip conditions read only shared data."""
        if not self.enabled:
            return
        for resp in responses:
            if resp.response_type != ResponseType.ALLREDUCE:
                continue
            identities = []
            for name in resp.tensor_names:
                req = requests_by_name.get(name)
                if req is None:
                    identities = None
                    break
                identities.append(request_identity(req))
            if not identities:
                continue
            self._put(tuple(identities), resp)

    def _put(self, batch_key: Tuple[Tuple, ...], response: Response) -> None:
        pos = self._by_batch.get(batch_key)
        if pos is not None:
            # Re-negotiated identical batch: refresh the replayed object
            # and touch — no new slot, no eviction.
            self._entries[pos].response = response
            self._entries.move_to_end(pos)
            return
        while len(self._entries) >= self.capacity:
            evicted_pos, evicted = self._entries.popitem(last=False)
            for ident in evicted.identities:
                if self._by_identity.get(ident) == evicted_pos:
                    del self._by_identity[ident]
            self._by_batch.pop(evicted.identities, None)
            heapq.heappush(self._free, evicted_pos)
        if self._free:
            pos = heapq.heappop(self._free)
        else:
            pos = self._next_pos
            self._next_pos += 1
        self._entries[pos] = _Entry(identities=batch_key, response=response)
        self._by_batch[batch_key] = pos
        for ident in batch_key:
            # Remap: an identity that lived in an older (differently fused)
            # batch now resolves here; the old entry can no longer be fully
            # covered and ages out through the LRU.
            self._by_identity[ident] = pos

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries), "capacity": self.capacity,
                "generation": self.generation,
                "hit_cycles": self.hit_cycles,
                "miss_cycles": self.miss_cycles}
