"""Coordinator: negotiation, fusion planning, error construction, stalls.

Rebuild of the rank-0 "coordinator" half of ``horovod/common/operations.cc``:

* ``Negotiator`` is the message-table state machine — ``IncrementTensorCount``
  (``operations.cc:287-319``) plus ``ConstructResponse`` (``:321-523``) plus
  the fusion-packing loop (``:2154-2266``) plus ``CheckForStalledTensors``
  (``:1625-1672``). It is pure logic with no I/O, so the same object serves
  the in-process single-rank world and the TCP controller service.
* ``ControllerService`` wraps a ``Negotiator`` behind the authenticated TCP
  wire for multi-process worlds — the role MPI_Gather/MPI_Bcast of
  Request/ResponseLists plays each cycle in the reference
  (``operations.cc:2088-2134``, ``:2281-2287``). It also hosts the host-mode
  payload exchange (gather-reduce-scatter of tensor bytes over the same
  connections), which replaces the MPI data plane for CPU test worlds; on a
  real pod the data plane is XLA collectives and only the metadata cycle
  goes through here.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.witness import maybe_wrap as _witness_wrap
from ..core.logging import LOG
from ..core.status import (
    CONTROLLER_RESTARTING,
    SHUT_DOWN_ERROR,
    WORLD_MISMATCH,
    format_aborted_ranks,
)
from ..obs import flightrec as _flightrec
from ..obs.registry import Counter, registry as _metrics
from ..runner.network import (
    BasicClient,
    BasicService,
    ConnectionClosedError,
    Preserialized,
    WireError,
)
from .messages import (
    CacheHitAck,
    CacheRequest,
    DataType,
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
)

_DTYPE_BYTES = {
    DataType.UINT8: 1, DataType.INT8: 1, DataType.UINT16: 2,
    DataType.INT16: 2, DataType.INT32: 4, DataType.INT64: 8,
    DataType.FLOAT16: 2, DataType.FLOAT32: 4, DataType.FLOAT64: 8,
    DataType.BOOL: 1, DataType.BFLOAT16: 2,
}

# Observability plane (docs/metrics.md): control-plane families. The
# worker-side cycle histogram times the full client round trip (straggler
# wait included); the coordinator-side one times the service's ACTIVE
# window (first arrival → response framed), the same number the autotuner
# scores.
_NEG_CYCLES = _metrics().counter(
    "horovod_negotiation_cycles_total",
    "Negotiation round trips completed by this rank's controller client")
_NEG_TX = _metrics().counter(
    "horovod_negotiation_tx_bytes_total",
    "Cycle-metadata bytes sent by this rank (payload exchanges excluded)")
_NEG_RX = _metrics().counter(
    "horovod_negotiation_rx_bytes_total",
    "Cycle-metadata bytes received by this rank (payloads excluded)")
_NEG_CYCLE_SECONDS = _metrics().histogram(
    "horovod_negotiation_cycle_seconds",
    "Client-observed negotiation cycle latency (includes straggler wait)")
_COORD_CYCLE_SECONDS = _metrics().histogram(
    "horovod_coordinator_cycle_seconds",
    "Coordinator-side active cycle window (first arrival to response)")
_STALL_WARNINGS = _metrics().counter(
    "horovod_stall_warnings_total",
    "Stalled-tensor warnings produced by the coordinator's stall check")
_STALL_ESCALATIONS = _metrics().counter(
    "horovod_stall_escalations_total",
    "Stalls escalated into a structured world abort "
    "(HOROVOD_STALL_SHUTDOWN_TIME_S)")
_WORLD_ABORTS = _metrics().counter(
    "horovod_world_aborts_total",
    "Worlds aborted after a rank death (first attribution only; "
    "cascading teardown disconnects are not re-counted)")
_RECONNECT_WINDOW_HEALS = _metrics().counter(
    "horovod_reconnect_window_heals_total",
    "Dropped rank connections forgiven by an in-window reconnect")
# Straggler attribution (docs/tracing.md): the coordinator is the one
# place arrival ORDER is observable, so it charges each cycle's spread
# (last arrival - first arrival) to the rank that arrived last. Count
# AND seconds per blamed rank: counts answer "who is late", seconds
# answer "who is costing the world time" — a rank late by microseconds
# on every cycle must not outrank one late by 50 ms on a tenth of them.
# rank labels are low-cardinality by the registry's contract (a world's
# rank set, not tensor names). The island label (docs/hierarchy.md) rides
# the same families: flat worlds stamp island=0, hierarchy worlds stamp
# the id of the DCN island the blamed rank lives in — the root charges
# whole islands (rank = the island's head), heads charge their members —
# so the report tool can name the slow ISLAND before the slow rank.
_STRAGGLER_LAST = _metrics().counter(
    "horovod_straggler_last_arriver_total",
    "Negotiation cycles in which this rank arrived last at the "
    "coordinator", labels=("rank", "island"))
_STRAGGLER_BLAME_S = _metrics().counter(
    "horovod_straggler_blame_seconds_total",
    "Arrival-spread seconds charged to this rank as the cycle's last "
    "arriver", labels=("rank", "island"))
_ARRIVAL_SPREAD = _metrics().histogram(
    "horovod_arrival_spread_seconds",
    "Per-cycle coordinator arrival spread (last arrival - first)")

def _nbytes(req: Request) -> int:
    n = _DTYPE_BYTES[req.tensor_type]
    for d in req.tensor_shape:
        n *= d
    return n


def make_negotiator(size: int, cfg) -> "Negotiator":
    """Prefer the native (C++) negotiation core; fall back to Python.

    The reference's negotiation logic is C++ only (operations.cc); here the
    two implementations share one behavior contract and one test suite, with
    ``HOROVOD_NATIVE_CORE=0`` forcing the Python path."""
    import os

    from ..core.config import HOROVOD_NATIVE_CORE

    if os.environ.get(HOROVOD_NATIVE_CORE, "1") != "0":
        from .. import cc

        if cc.available():
            return cc.NativeNegotiator(
                size, cfg.fusion_threshold_bytes,
                stall_warning_s=cfg.stall_warning_time_s,
                stall_check_disable=cfg.stall_check_disable)
        LOG.warning("native core unavailable (%s); using Python negotiator",
                    cc.load_error())
    return Negotiator(size, cfg.fusion_threshold_bytes,
                      stall_warning_s=cfg.stall_warning_time_s,
                      stall_check_disable=cfg.stall_check_disable)


@dataclass
class _TableEntry:
    """Per-tensor negotiation state (the message_table of
    ``operations.cc:271-285``)."""

    requests: Dict[int, Request] = field(default_factory=dict)
    first_seen: float = field(default_factory=time.monotonic)
    arrival: int = 0  # order of readiness for deterministic response order


class Negotiator:
    """Tracks which ranks have submitted which named tensors; when all
    ``size`` ranks have submitted a name, emits a Response for it (fused
    where legal) or a coordinator-constructed error."""

    def __init__(self, size: int, fusion_threshold_bytes: int,
                 stall_warning_s: float = 60.0,
                 stall_check_disable: bool = False) -> None:
        self._size = size
        self._fusion_threshold = fusion_threshold_bytes
        self._stall_warning_s = stall_warning_s
        self._stall_check_disable = stall_check_disable
        self._table: Dict[str, _TableEntry] = {}
        self._ready: List[Tuple[int, str]] = []
        self._arrivals = 0
        self._last_stall_check = time.monotonic()
        self._shutdown = False
        self._lock = _witness_wrap(threading.Lock(),
                                   "ops.controller.Negotiator._lock")

    def add_request_list(self, rl: RequestList) -> None:
        """IncrementTensorCount for every request (``operations.cc:287-319``)."""
        with self._lock:
            if rl.shutdown:
                self._shutdown = True
            for req in rl.requests:
                entry = self._table.setdefault(req.tensor_name, _TableEntry())
                entry.requests[req.request_rank] = req
                if len(entry.requests) == self._size:
                    self._arrivals += 1
                    entry.arrival = self._arrivals
                    self._ready.append((entry.arrival, req.tensor_name))

    def set_fusion_threshold(self, threshold_bytes: int) -> None:
        """Autotuner hook (``parameter_manager.cc`` Tune/SyncParams)."""
        with self._lock:
            self._fusion_threshold = threshold_bytes

    def construct_response_list(self) -> ResponseList:
        """Drain ready tensors into a deterministic, fused ResponseList
        (``ConstructResponse`` + the fusion loop of ``:2154-2266``)."""
        with self._lock:
            ready = [name for _, name in sorted(self._ready)]
            self._ready.clear()
            responses: List[Response] = []
            for name in ready:
                entry = self._table.pop(name)
                resp = self._construct_response(name, entry)
                first = entry.requests[min(entry.requests)]
                resp.tensor_dtype = first.tensor_type
                resp.tensor_codec = getattr(first, "codec", "none")
                if resp.response_type == ResponseType.ALLREDUCE:
                    resp.fused_apply = getattr(first, "apply_fingerprint",
                                               "")
                resp.payload_bytes = _nbytes(first)
                responses.append(resp)
            warnings = self._maybe_check_stalls()
            out = ResponseList(responses=self._fuse(responses),
                               shutdown=self._shutdown,
                               stall_warnings=warnings or [],
                               stall_check=warnings is not None)
            return out

    @property
    def shutdown_requested(self) -> bool:
        return self._shutdown

    def request_shutdown(self) -> None:
        """Force shutdown=True on every subsequent response list (the
        stall-escalation path; a negotiated shutdown arrives via
        ``RequestList.shutdown`` instead)."""
        with self._lock:
            self._shutdown = True

    # -- response construction -----------------------------------------------

    def _construct_response(self, name: str, entry: _TableEntry) -> Response:
        reqs = [entry.requests[r] for r in sorted(entry.requests)]
        first = reqs[0]

        def error(msg: str) -> Response:
            return Response(ResponseType.ERROR, tensor_names=[name],
                            error_message=msg)

        for req in reqs[1:]:
            if req.request_type != first.request_type:
                return error(
                    f"Mismatched collective operations: rank "
                    f"{first.request_rank} requested "
                    f"{first.request_type.name}, but rank {req.request_rank} "
                    f"requested {req.request_type.name} for tensor {name}.")
            if req.tensor_type != first.tensor_type:
                return error(
                    f"Mismatched data types: rank {first.request_rank} sent "
                    f"{first.tensor_type.name}, but rank {req.request_rank} "
                    f"sent {req.tensor_type.name} for tensor {name}.")
            if getattr(req, "codec", "none") != \
                    getattr(first, "codec", "none"):
                # a quantized wire changes the collective program itself;
                # divergent codecs would desynchronize XLA launch order
                return error(
                    f"Mismatched compression codecs: rank "
                    f"{first.request_rank} sent "
                    f"{getattr(first, 'codec', 'none')!r}, but rank "
                    f"{req.request_rank} sent "
                    f"{getattr(req, 'codec', 'none')!r} for tensor {name}.")
            if getattr(req, "apply_fingerprint", "") != \
                    getattr(first, "apply_fingerprint", ""):
                # the fused reduce+apply program is part of the
                # negotiated identity exactly like the codec: divergent
                # rules (or apply-vs-plain divergence) would land
                # different parameters on different ranks
                return error(
                    f"Mismatched fused-apply rules: rank "
                    f"{first.request_rank} sent "
                    f"{getattr(first, 'apply_fingerprint', '')!r}, but "
                    f"rank {req.request_rank} sent "
                    f"{getattr(req, 'apply_fingerprint', '')!r} for "
                    f"tensor {name}.")

        op = first.request_type
        if op == RequestType.ALLREDUCE:
            for req in reqs[1:]:
                if req.tensor_shape != first.tensor_shape:
                    return error(
                        f"Mismatched allreduce tensor shapes: rank "
                        f"{first.request_rank} sent shape "
                        f"{list(first.tensor_shape)}, but rank "
                        f"{req.request_rank} sent shape "
                        f"{list(req.tensor_shape)} for tensor {name}.")
            return Response(ResponseType.ALLREDUCE, tensor_names=[name])

        if op == RequestType.BROADCAST:
            for req in reqs[1:]:
                if req.root_rank != first.root_rank:
                    return error(
                        f"Mismatched broadcast root ranks: rank "
                        f"{first.request_rank} specified root "
                        f"{first.root_rank}, but rank {req.request_rank} "
                        f"specified root {req.root_rank} for tensor {name}.")
            if not (0 <= first.root_rank < self._size):
                return error(
                    f"Invalid broadcast root rank {first.root_rank} for a "
                    f"world of size {self._size} (tensor {name}).")
            root_shape = entry.requests[first.root_rank].tensor_shape \
                if first.root_rank in entry.requests else first.tensor_shape
            for req in reqs:
                if req.tensor_shape != root_shape:
                    return error(
                        f"Mismatched broadcast tensor shapes: root sent "
                        f"shape {list(root_shape)}, but rank "
                        f"{req.request_rank} has shape "
                        f"{list(req.tensor_shape)} for tensor {name}.")
            resp = Response(ResponseType.BROADCAST, tensor_names=[name])
            resp.tensor_sizes = [first.root_rank]
            return resp

        # ALLGATHER: ragged first dim allowed; all other dims must agree
        # (``operations.cc:382-430``). tensor_sizes carries per-rank dim0 in
        # rank order — the recvcounts of the reference.
        for req in reqs[1:]:
            if len(req.tensor_shape) != len(first.tensor_shape) or \
                    req.tensor_shape[1:] != first.tensor_shape[1:]:
                return error(
                    f"Mismatched allgather tensor shapes: every dimension "
                    f"except the first must match; rank {first.request_rank} "
                    f"sent {list(first.tensor_shape)}, rank "
                    f"{req.request_rank} sent {list(req.tensor_shape)} for "
                    f"tensor {name}.")
        if len(first.tensor_shape) == 0:
            return error(
                f"Rank zero tried to allgather a rank-zero tensor "
                f"({name}); allgather requires at least one dimension.")
        sizes = [req.tensor_shape[0] for req in reqs]
        return Response(ResponseType.ALLGATHER, tensor_names=[name],
                        tensor_sizes=sizes)

    # -- fusion ---------------------------------------------------------------

    def _fuse(self, responses: List[Response]) -> List[Response]:
        """Greedily join adjacent ALLREDUCE responses of identical dtype up
        to the fusion threshold (reference lookahead loop
        ``operations.cc:2154-2266``; only allreduces are buffer-fused)."""
        fused: List[Response] = []
        i = 0
        while i < len(responses):
            resp = responses[i]
            if resp.response_type != ResponseType.ALLREDUCE:
                fused.append(resp)
                i += 1
                continue
            batch = Response(ResponseType.ALLREDUCE,
                             tensor_names=list(resp.tensor_names),
                             tensor_dtype=resp.tensor_dtype,
                             payload_bytes=resp.payload_bytes,
                             tensor_codec=resp.tensor_codec,
                             fused_apply=resp.fused_apply)
            dtype = resp.tensor_dtype
            total = resp.payload_bytes
            j = i + 1
            while j < len(responses):
                nxt = responses[j]
                if nxt.response_type != ResponseType.ALLREDUCE or \
                        nxt.tensor_dtype != dtype or \
                        nxt.tensor_codec != resp.tensor_codec or \
                        nxt.fused_apply != resp.fused_apply:
                    break
                if total + nxt.payload_bytes > self._fusion_threshold:
                    break
                batch.tensor_names.extend(nxt.tensor_names)
                total += nxt.payload_bytes
                j += 1
            batch.payload_bytes = total
            fused.append(batch)
            i = j
        return fused

    # -- stall detection ------------------------------------------------------

    def _maybe_check_stalls(self) -> Optional[List[str]]:
        """WARN about tensors some ranks submitted >stall_warning_s ago
        that other ranks never did (``CheckForStalledTensors``,
        ``operations.cc:1625-1672``). Returns the warning strings so the
        controller can ship them to every rank on the response list —
        the input the stall-shutdown escalation watches. ``None`` means
        the interval-gated check did NOT run this cycle; an empty list
        means it ran and found nothing stalled (authoritative recovery
        signal for the escalation tracker)."""
        if self._stall_check_disable:
            return None
        now = time.monotonic()
        if now - self._last_stall_check < self._stall_warning_s:
            return None
        self._last_stall_check = now
        warnings: List[str] = []
        for name, entry in self._table.items():
            if now - entry.first_seen <= self._stall_warning_s:
                continue
            missing = sorted(set(range(self._size)) - set(entry.requests))
            ready = sorted(entry.requests)
            warning = (
                "One or more tensors were submitted to be reduced, gathered "
                "or broadcasted by subset of ranks and are waiting for "
                "remainder of ranks for more than "
                f"{int(self._stall_warning_s)} seconds. This may indicate "
                "that different ranks are trying to submit different tensors "
                "or that only subset of ranks is submitting tensors, which "
                "will cause deadlock. Stalled ops: "
                f"{name} [missing ranks: {', '.join(map(str, missing))}] "
                f"[ready ranks: {', '.join(map(str, ready))}]")
            LOG.warning("%s", warning)
            warnings.append(warning)
        return warnings


def numpy_dtype(dt: DataType):
    """Wire DataType → numpy dtype; bfloat16 comes from ml_dtypes (the same
    library JAX itself uses for host-side bf16 arrays)."""
    import ml_dtypes

    return {
        DataType.UINT8: np.uint8, DataType.INT8: np.int8,
        DataType.UINT16: np.uint16, DataType.INT16: np.int16,
        DataType.INT32: np.int32, DataType.INT64: np.int64,
        DataType.FLOAT16: np.float16, DataType.FLOAT32: np.float32,
        DataType.FLOAT64: np.float64, DataType.BOOL: np.bool_,
        DataType.BFLOAT16: ml_dtypes.bfloat16,
    }[dt]


class _Rendezvous:
    """Collect one submission per rank for a key, compute a single result,
    deliver it to every rank. This is the TCP stand-in for the reference's
    MPI_Gather(+Gatherv) / MPI_Bcast pair that moves Request/ResponseLists
    each cycle (``operations.cc:2088-2134``, ``:2281-2287``)."""

    def __init__(self, size: int) -> None:
        self._size = size
        self._cond = threading.Condition()
        self._slots: Dict[Any, Dict[int, Any]] = {}
        self._results: Dict[Any, Any] = {}
        self._delivered: Dict[Any, int] = {}
        self._aborted: Optional[BaseException] = None

    def submit(self, key: Any, rank: int, item: Any,
               compute: Callable[[Dict[int, Any]], Any],
               timeout_s: Optional[float] = None,
               timeout_hint: str = "") -> Any:
        """``timeout_s`` bounds the wait for the other participants:
        rendezvous whose counterpart submissions are CONDITIONAL on every
        rank's config (the sentry verdict exchange) must fail loudly with
        ``timeout_hint`` naming the diagnosis instead of wedging a world
        whose configs drifted — cycles/payloads keep the unbounded wait
        (their participation is the protocol itself, and rank death
        already aborts them)."""
        with self._cond:
            if self._aborted is not None:
                raise RuntimeError(str(self._aborted)) from self._aborted
            slot = self._slots.setdefault(key, {})
            slot[rank] = item
            if len(slot) == self._size:
                # A compute failure must poison the result for every waiting
                # rank — swallowing it in one handler thread would leave the
                # others blocked forever.
                try:
                    self._results[key] = ("ok", compute(slot))
                except Exception as exc:  # noqa: BLE001
                    self._results[key] = ("error", exc)
                self._delivered[key] = 0
                self._cond.notify_all()
            else:
                arrived = self._cond.wait_for(
                    lambda: key in self._results or self._aborted is not None,
                    timeout=timeout_s)
                if not arrived and key not in self._results and \
                        self._aborted is None:
                    missing = sorted(set(range(self._size)) - set(slot))
                    raise RuntimeError(
                        f"rendezvous {key!r} timed out after "
                        f"{timeout_s:.0f}s waiting for ranks "
                        f"{', '.join(map(str, missing))}. {timeout_hint}")
            if key not in self._results:
                raise RuntimeError(str(self._aborted)) from self._aborted
            kind, result = self._results[key]
            self._delivered[key] += 1
            if self._delivered[key] == self._size:
                del self._slots[key], self._results[key], self._delivered[key]
            if kind == "error":
                raise RuntimeError(
                    f"coordinator-side collective failure: {result}") \
                    from result
            return result

    def submit_group(self, key: Any, items: Dict[int, Any],
                     compute: Callable[[Dict[int, Any]], Any],
                     timeout_s: Optional[float] = None,
                     timeout_hint: str = "") -> Any:
        """``submit`` for a handler thread carrying SEVERAL participants'
        items at once (a forwarded island batch, docs/hierarchy.md).
        Inserting them one ``submit()`` at a time would deadlock: the
        first call parks waiting for the rest, which are queued behind it
        on the same thread. All-or-nothing insert, ONE wait, and
        ``len(items)`` deliveries consumed toward cleanup."""
        if not items:
            raise ValueError("submit_group requires at least one item")
        with self._cond:
            if self._aborted is not None:
                raise RuntimeError(str(self._aborted)) from self._aborted
            slot = self._slots.setdefault(key, {})
            slot.update(items)
            if len(slot) >= self._size and key not in self._results:
                try:
                    self._results[key] = ("ok", compute(slot))
                except Exception as exc:  # noqa: BLE001 - poison for all
                    self._results[key] = ("error", exc)
                self._delivered[key] = 0
                self._cond.notify_all()
            elif key not in self._results:
                arrived = self._cond.wait_for(
                    lambda: key in self._results
                    or self._aborted is not None,
                    timeout=timeout_s)
                if not arrived and key not in self._results and \
                        self._aborted is None:
                    missing = sorted(set(range(self._size)) - set(slot))
                    raise RuntimeError(
                        f"rendezvous {key!r} timed out after "
                        f"{timeout_s:.0f}s waiting for ranks "
                        f"{', '.join(map(str, missing))}. {timeout_hint}")
            if key not in self._results:
                raise RuntimeError(str(self._aborted)) from self._aborted
            kind, result = self._results[key]
            self._delivered[key] += len(items)
            if self._delivered[key] >= self._size:
                del self._slots[key], self._results[key], \
                    self._delivered[key]
            if kind == "error":
                raise RuntimeError(
                    f"coordinator-side collective failure: {result}") \
                    from result
            return result

    def abort(self, exc: BaseException) -> None:
        """Wake every waiter with ``exc`` and fail all future submits —
        the rendezvous can never complete once a participant is dead.
        The first abort wins: survivors tearing down after it cascade more
        disconnects, and their exceptions must not overwrite the actual
        culprit in what every rank reports."""
        with self._cond:
            if self._aborted is None:
                self._aborted = exc
            self._cond.notify_all()

    def pending(self) -> Dict[str, List[int]]:
        """Parked-rendezvous table (docs/blackbox.md): for every key
        still short of its full rank set, the ranks that DID arrive —
        the black-box incident dump's "who was everyone waiting on"
        evidence. Keys stringified (tuples are not JSON)."""
        with self._cond:
            return {repr(key): sorted(slot)
                    for key, slot in self._slots.items()
                    if key not in self._results}


def world_id_of(members, size: int) -> str:
    """Canonical identity of a world instance on the shared controller
    port. Subset worlds are identified by their composition (launcher
    ranks in communicator order); full worlds by size — two successive
    same-identity worlds cannot overlap (every member participates in
    the negotiated shutdown before any re-inits), while co-scheduled
    DIFFERENT worlds (a subset schedule's epochs) must not
    cross-register (core.status.WORLD_MISMATCH)."""
    if members is None:
        return f"full:{size}"
    return "sub:" + ",".join(str(r) for r in members)


def world_mismatch_error(service_id: str, caller_id: str) -> str:
    """Exact-text contract with the native service (tests pin it)."""
    return (f"{WORLD_MISMATCH} (service={service_id}, caller={caller_id}); "
            f"retry against this port's successor service")


class StallEscalation:
    """Escalate persistent stalls into a structured world abort.

    The reference answers a permanently-missing rank with an infinite
    hang behind a periodic warning (``CheckForStalledTensors``). With
    ``HOROVOD_STALL_SHUTDOWN_TIME_S`` set, this tracker watches the
    warning stream: once a stalled op has kept warning for ``deadline_s``
    beyond its FIRST warning (i.e. ~``stall_warning + deadline`` after
    the stall began), it produces the abort — ERROR responses for the
    stalled tensors plus a shutdown reason naming the missing ranks, so
    healthy ranks raise :class:`core.status.RanksAbortedError` instead of
    blocking forever.

    One implementation serves every controller configuration: the Python
    ``ControllerService`` applies it coordinator-side over either
    negotiation core's warnings; the native C++ service's clients apply
    it client-side over the warnings the binary wire already carries
    (identical on every rank, so every client reaches the same verdict).
    """

    _WARNING_RE = re.compile(
        r"Stalled ops: (.*?) \[missing ranks: ([0-9, ]*)\]")

    def __init__(self, deadline_s: float,
                 warning_interval_s: float = 60.0) -> None:
        self._deadline_s = deadline_s
        # A still-stalled op re-warns every warning interval; an entry
        # whose warnings stopped for well over that recovered, and its
        # clock must not leak into the name's NEXT stall episode (fixed
        # user names like "grad" recur every step). The window tracks
        # the warning CADENCE only — mixing the (possibly much longer)
        # deadline in would keep resolved episodes alive long enough to
        # abort the next one prematurely.
        self._stale_after_s = 2.5 * max(warning_interval_s, 0.1)
        self._warned: Dict[str, Tuple[float, float]] = {}  # first, last

    def check(self, warnings: List[str], check_ran: bool = False
              ) -> Optional[Tuple[List[str], List[int], str]]:
        """Feed one cycle's warning batch (possibly empty); returns
        ``(stalled_names, missing_ranks, reason)`` when the deadline
        expired, else None. ``check_ran=True`` marks an empty batch as an
        authoritative all-clear (the coordinator's interval-gated check
        ran and found nothing) — resolved episodes retire immediately
        instead of waiting out the cadence window."""
        if self._deadline_s <= 0:
            return None
        now = time.monotonic()
        for name in list(self._warned):
            if now - self._warned[name][1] > self._stale_after_s:
                del self._warned[name]
        if not warnings:
            if check_ran:
                self._warned.clear()
            return None
        expired: List[str] = []
        missing: set = set()
        seen_now: set = set()
        for warning in warnings:
            m = self._WARNING_RE.search(warning)
            if m is None:
                continue
            name, ranks_s = m.group(1), m.group(2)
            seen_now.add(name)
            first, _last = self._warned.get(name, (now, now))
            self._warned[name] = (first, now)
            if now - first >= self._deadline_s:
                expired.append(name)
                missing.update(int(tok) for tok in
                               ranks_s.replace(",", " ").split())
        # A non-empty batch is a complete snapshot of the still-stalled
        # table: entries that completed since the last check stop warning
        # and must stop aging toward the deadline.
        for name in list(self._warned):
            if name not in seen_now:
                del self._warned[name]
        if not expired:
            return None
        reason = (
            f"collective(s) {', '.join(sorted(expired))} stalled past the "
            f"{self._deadline_s:.0f}s HOROVOD_STALL_SHUTDOWN_TIME_S "
            f"deadline; aborting the world instead of hanging. "
            f"{SHUT_DOWN_ERROR} {format_aborted_ranks(missing)}")
        return sorted(expired), sorted(missing), reason


class ControllerService:
    """Rank-0 TCP controller: cycle negotiation + host-mode payload exchange.

    Requests on the wire:
      ("cycle", rank, RequestList)            -> ResponseList
      ("payload", rank, cycle_no, idx, bytes) -> result bytes
    Every rank (including rank 0's own engine, via loopback — the reference's
    coordinator likewise participates in its own MPI_Gather) drives one
    request at a time over a persistent connection, so cycles stay lockstep.
    """

    def __init__(self, size: int, negotiator: Negotiator,
                 secret: Optional[bytes] = None, port: int = 0,
                 bind_host: str = "127.0.0.1",
                 autotuner=None, world_id: str = "",
                 stall_shutdown_s: float = 0.0,
                 stall_warning_s: float = 60.0,
                 listen_fd: Optional[int] = None,
                 cache_capacity: int = 0,
                 fusion_threshold_bytes: Optional[int] = None,
                 reconnect_window_s: Optional[float] = None,
                 straggler_detector=None,
                 codec_min_bytes: int = 4096,
                 consensus_interval_steps: Optional[int] = None,
                 islands: Optional[Dict[int, Tuple[int, ...]]] = None
                 ) -> None:
        self._negotiator = negotiator
        self._world_id = world_id
        # Hierarchical negotiation tree (docs/hierarchy.md): when the
        # world runs two-level, this service is the ROOT — it sees only
        # the per-island sub-coordinators (one merged submission per
        # island per cycle) and expands them back into the flat per-rank
        # path below, keeping responses and error texts byte-identical.
        # {island id -> sorted global member ranks}; learned from
        # "hello_island" too so tooling-built services need no kwarg.
        self._islands: Dict[int, Tuple[int, ...]] = \
            {int(i): tuple(m) for i, m in islands.items()} if islands \
            else {}
        self._island_of: Dict[int, int] = {
            r: i for i, mem in self._islands.items() for r in mem}
        # Which rank SERVES each island right now (docs/recovery.md):
        # seeded with the planned head (lowest member), updated by every
        # "hello_island" — after a standby succession the successor's
        # hello re-homes the island here, so head-death attribution in
        # _abort_for_rank tracks the LIVE head, not the plan.
        self._island_heads: Dict[int, int] = {
            i: min(mem) for i, mem in self._islands.items() if mem}
        # per-rendezvous-key island bookkeeping: arrival times (island
        # straggler attribution), the heads' own upstream flush ordinals
        # (the per-LEVEL PR 9 cross-check), and expansion/fold errors
        # deferred into the rendezvous compute so they poison the cycle
        # for every island instead of wedging the others.
        self._island_arrivals: Dict[Any, Dict[int, float]] = {}
        self._island_ordinals: Dict[Any, Dict[int, Any]] = {}
        self._island_errors: Dict[Any, List[str]] = {}
        # Self-healing grace (docs/chaos.md): a rank-bound connection that
        # drops is given this long to reconnect and supersede before the
        # drop is declared a rank death. 0 restores abort-on-first-drop.
        if reconnect_window_s is None:
            # direct construction (tests/tooling): same env default as the
            # engine's Config, parsed in exactly one place
            from ..core.config import Config

            reconnect_window_s = Config.from_env().reconnect_window_s
        self._reconnect_window_s = reconnect_window_s
        self._pending_reconnect: Dict[int, float] = {}
        # Steady-state negotiation bypass (docs/response-cache.md): the
        # coordinator's mirror of every rank's ResponseCache. None when
        # disabled — a cache-bit cycle arriving anyway is a configuration
        # desync and fails loudly in _expand_cache_cycle.
        from .response_cache import ResponseCache

        self._cache = ResponseCache(cache_capacity) \
            if cache_capacity > 0 else None
        # Invalidations are DEFERRED to the next cycle's bookkeeping point:
        # in-flight cache-bit requests were planned against the current
        # mirror, and clearing it mid-flight would make their positions
        # unresolvable. The flag is consumed inside _run_cycle, after
        # expansion and autotune, so any caller timing is safe.
        self._cache_bump_pending = False
        # Fusion repacking stales cached fused layouts: track the live
        # threshold so set_fusion_threshold only bumps the cache generation
        # on a REAL change (the autotuner re-proposes unchanged thresholds
        # whenever only the cycle time moved). Callers that skip the
        # parameter get the Python negotiator's configured threshold; an
        # opaque (native) negotiator leaves it None, and the first retune
        # then bumps conservatively — a spurious one-miss invalidation
        # beats replaying a stale layout.
        if fusion_threshold_bytes is None:
            fusion_threshold_bytes = getattr(
                negotiator, "_fusion_threshold", None)
        self._fusion_threshold = fusion_threshold_bytes
        self._stall_escalation = StallEscalation(
            stall_shutdown_s, warning_interval_s=stall_warning_s)
        # Data-plane integrity plane (docs/integrity.md): the sentry
        # verdict rendezvous (one OR-fold of per-tensor finite bits per
        # screened batch) always exists — it is two dict slots until a
        # sentry-armed rank dials in. Consensus compare state only when
        # the cadence knob arms it; None → same env default the engine's
        # Config resolves, parsed in one place (the reconnect_window
        # pattern above).
        if consensus_interval_steps is None:
            from ..core.config import Config

            consensus_interval_steps = \
                Config.from_env().consensus_interval_steps
        self._sentry_rv = _Rendezvous(size)
        self._consensus_judge = None
        self._consensus_authority = None
        if consensus_interval_steps > 0:
            from ..integrity.consensus import (
                ConsensusAuthority,
                ConsensusJudge,
            )

            # the authority digests host-plane combines as they happen —
            # it must be live BEFORE the first rank digest arrives (a
            # window's digest ships one cycle after its batches ran)
            self._consensus_authority = ConsensusAuthority(
                consensus_interval_steps)
            self._consensus_judge = ConsensusJudge(
                size, authority=self._consensus_authority)
        self._cycles = _Rendezvous(size)
        self._payloads = _Rendezvous(size)
        self._cycle_no = 0
        self._history: Dict[int, ResponseList] = {}
        # lock witness (docs/analysis.md): the service + metrics locks
        # join the global held-before graph under HOROVOD_LOCK_WITNESS=1
        self._lock = _witness_wrap(
            threading.Lock(), "ops.controller.ControllerService._lock")
        self._cycle_t0: Dict[Any, float] = {}
        # Straggler attribution (docs/tracing.md): per-cycle arrival time
        # of every rank's cycle request, popped (and charged to the last
        # arriver) when the cycle completes. Size matches in-flight
        # cycles, so an aborted world leaks at most one entry per key.
        self._cycle_arrivals: Dict[Any, Dict[int, float]] = {}
        self._size = size
        self._autotuner = autotuner
        self._tuned_cycle_ms: Optional[float] = None
        # Closed-loop tuning plane (docs/autotune.md): the latest
        # extended-knob map piggybacked on every response/ack, the tuned
        # codec applied to negotiated allreduce batches (response-side
        # rewrite: requests stay uniform, so codec retunes can never
        # desynchronize the negotiation table mid-flight), and a deferred
        # cache-capacity retune applied at the same bookkeeping point as
        # the generation bump it implies.
        self._tuned_knobs: Optional[dict] = None
        self._applied_codec: Optional[str] = None
        self._codec_min_bytes = codec_min_bytes
        self._cache_capacity_pending: Optional[int] = None
        # Straggler mitigation (horovod_tpu.tune.detector): fed one
        # (last_rank, spread) per fully-observed cycle; None = plane off.
        self._straggler = straggler_detector
        # Failure detection: map each connection to the rank it serves; a
        # connection that drops before the world reached a clean shutdown
        # means that rank died, and every peer blocked in a rendezvous with
        # it must be unblocked with SHUT_DOWN_ERROR (the reference's
        # "exception on one of the ranks" semantics, operations.cc:1942-1957).
        self._conn_ranks: Dict[int, int] = {}
        self._rank_conns: Dict[int, int] = {}  # rank -> id(sock), reverse
        self._world_shutdown = False
        self._abort_fired = False
        # Failure-push channel: "watch" requests park here until the world
        # aborts (or the service stops), giving ranks blocked inside a
        # compiled device collective — which no control-plane response can
        # reach — an asynchronous SHUT_DOWN_ERROR signal.
        self._watch_event = threading.Event()
        self._watch_reason: Optional[str] = None
        # Observability plane (docs/metrics.md): latest registry snapshot
        # per rank, pushed by each rank's metrics publisher over this same
        # wire ("metrics" requests — so aggregation inherits the dedup/
        # reconnect semantics of every other control message). Read by
        # rank 0's exposition server and by "metrics_pull" requests.
        self._metrics_lock = _witness_wrap(
            threading.Lock(),
            "ops.controller.ControllerService._metrics_lock")
        self._metrics_ranks: Dict[int, dict] = {}
        # Flight recorder (docs/blackbox.md): per-rank black-box event
        # tails pushed on abort over the anonymous "flightrec" RPC; the
        # incident collector folds them into one blackbox-*.json. The
        # once-flag keeps one incident file per world no matter how many
        # escalation paths fire.
        self._flightrec_ranks: Dict[int, dict] = {}
        self._flightrec_fired = False
        self._service = BasicService(
            "horovod-controller", self._handle, secret=secret, port=port,
            bind_host=bind_host, on_disconnect=self._on_disconnect,
            listen_fd=listen_fd)
        self.port = self._service.port

    def _deregister(self, sock: Any) -> Optional[int]:
        """Drop the connection's rank binding (caller holds ``_lock``);
        returns the rank it carried, if any."""
        rank = self._conn_ranks.pop(id(sock), None)
        if rank is not None and self._rank_conns.get(rank) == id(sock):
            del self._rank_conns[rank]
        return rank

    def _on_disconnect(self, sock: Any) -> None:
        with self._lock:
            rank = self._deregister(sock)
            if rank is None or self._world_shutdown:
                return
            window = self._reconnect_window_s
            if window > 0 and not self._abort_fired:
                # Self-healing grace: the drop may be a transient fault
                # the client is already reconnecting through
                # (BasicClient latches broken and redials with backoff).
                # Park the verdict; a superseding registration inside the
                # window heals it, expiry escalates it to a rank death.
                deadline = time.monotonic() + window
                self._pending_reconnect[rank] = deadline
            else:
                deadline = None
        if deadline is not None:
            LOG.warning(
                "rank %d connection dropped before shutdown; waiting "
                "%.1fs for a reconnect before declaring it dead", rank,
                window)
            timer = threading.Timer(window + 0.05,
                                    self._reconnect_deadline,
                                    args=(rank, deadline))
            timer.daemon = True
            timer.start()
            return
        self._abort_for_rank(rank)

    def _reconnect_deadline(self, rank: int, deadline: float) -> None:
        """Timer body: the reconnect window for ``rank`` expired."""
        with self._lock:
            if self._pending_reconnect.get(rank) != deadline:
                return  # healed, or a newer drop owns the verdict
            del self._pending_reconnect[rank]
            if self._world_shutdown or rank in self._rank_conns:
                return
        self._abort_for_rank(rank)

    def _abort_for_rank(self, rank: int) -> None:
        with self._lock:
            first = not self._abort_fired
            self._abort_fired = True
        if first:
            _WORLD_ABORTS.inc()
            LOG.warning("rank %d disconnected before shutdown; aborting "
                        "in-flight collectives on all ranks", rank)
        else:
            # Cascade: survivors tear down after the first abort; their
            # disconnects are a consequence, not the cause.
            LOG.debug("rank %d disconnected during abort teardown", rank)
        # The explicit tag makes the attribution machine-parseable even
        # from a survivor's stderr tail (strict parsing ignores the
        # bare "rank N exited" phrasing there — log text is noisy).
        # In a hierarchy world the only ranks bound HERE are the island
        # heads: a head's death takes its whole island off the wire, so
        # the structured reason names the island and every member rank
        # (docs/hierarchy.md) — the aborted-ranks tag keeps the blackbox
        # classifier and the elastic blacklist attribution working.
        island = None
        with self._lock:
            for i, mem in sorted(self._islands.items()):
                if mem and rank == self._island_heads.get(i, min(mem)):
                    island = i
                    break
        if island is not None:
            members = self._islands[island]
            exc = RuntimeError(
                f"island {island} sub-coordinator (rank {rank}) exited "
                f"mid-job; its member ranks "
                f"{', '.join(map(str, members))} are unreachable. "
                f"{SHUT_DOWN_ERROR} {format_aborted_ranks(members)}")
        else:
            exc = RuntimeError(
                f"rank {rank} exited mid-job. {SHUT_DOWN_ERROR} "
                f"{format_aborted_ranks([rank])}")
        self._cycles.abort(exc)  # first abort wins inside the rendezvous
        self._payloads.abort(exc)
        self._sentry_rv.abort(exc)  # a parked verdict can never complete
        with self._lock:
            if self._watch_reason is None:
                self._watch_reason = str(exc)
        self._watch_event.set()
        self._flightrec_incident(str(exc))

    def metrics_store(self) -> Dict[int, dict]:
        """Copy of the per-rank snapshot store (rank → registry families),
        as fresh as each rank's last publisher push."""
        with self._metrics_lock:
            return dict(self._metrics_ranks)

    def flightrec_store(self) -> Dict[int, dict]:
        """Copy of the per-rank black-box tails pushed on abort."""
        with self._metrics_lock:
            return dict(self._flightrec_ranks)

    def state_snapshot(self) -> dict:
        """Coordinator state for the black-box incident dump and
        ``hvd.health_report()`` — one definition (docs/blackbox.md):
        cycle position, live rank bindings, parked rendezvous (who is
        everyone waiting on), response-cache generation, and the last
        tuned-knob map."""
        with self._lock:
            snap = {
                "cycle_no": self._cycle_no,
                "world_shutdown": self._world_shutdown,
                "abort_fired": self._abort_fired,
                "abort_reason": self._watch_reason,
                "bound_ranks": sorted(self._rank_conns),
                "pending_reconnect": {str(r): d for r, d in
                                      self._pending_reconnect.items()},
                "tuned_knobs": dict(self._tuned_knobs)
                if self._tuned_knobs else None,
                "tuned_cycle_ms": self._tuned_cycle_ms,
                "islands": {str(i): list(m) for i, m in
                            self._islands.items()} or None,
            }
        snap["cache_generation"] = (self._cache.generation
                                    if self._cache is not None else None)
        snap["pending_rendezvous"] = {
            "cycle": self._cycles.pending(),
            "payload": self._payloads.pending(),
            "sentry": self._sentry_rv.pending(),
        }
        return snap

    def _flightrec_incident(self, reason: str) -> None:
        """Start the bounded cross-rank incident collection, once per
        world (docs/blackbox.md). The collector thread is non-daemon and
        time-bounded by construction: interpreter exit joins it, so the
        dump lands even when this process dies right after the abort."""
        with self._lock:
            if self._flightrec_fired:
                return
            self._flightrec_fired = True
        try:
            from ..basics import world_epoch

            _flightrec.coordinator_collect(
                reason, self._size, self._world_id, world_epoch(),
                store_get=self.flightrec_store,
                snapshot_fn=self.state_snapshot)
        except Exception as exc:  # noqa: BLE001 - never worsen an abort
            LOG.warning("flight recorder: incident collection failed to "
                        "start: %s", exc)

    def _handle(self, req: Any, _sock: Any) -> Any:
        kind = req[0]
        if kind == "metrics":
            # Per-rank registry push (observability plane). Handled BEFORE
            # the rank-binding block below, like "watch": the publisher's
            # connection is deliberately anonymous, so tearing it down is
            # never mistaken for a rank death. A push from a DIFFERENT
            # co-located world (subset schedules share this port) is
            # refused like "watch"/"hello" — storing it would merge
            # another world's counters into this world's /metrics.
            _, push_rank, snap = req[:3]
            push_wid = req[3] if len(req) > 3 else ""
            if push_wid and self._world_id and push_wid != self._world_id:
                raise RuntimeError(
                    world_mismatch_error(self._world_id, push_wid))
            with self._metrics_lock:
                self._metrics_ranks[int(push_rank)] = snap
            return ("ok",)
        if kind == "flightrec":
            # Flight-recorder incident push (docs/blackbox.md): one rank's
            # black-box event tail on abort. Anonymous like "metrics" —
            # handled BEFORE rank binding, the pushing connection's
            # teardown is never a rank death — and world-gated the same
            # way (a co-located different world's tail in this world's
            # incident file would send a postmortem reader down the wrong
            # world's history).
            _, push_rank, payload = req[:3]
            push_wid = req[3] if len(req) > 3 else ""
            if push_wid and self._world_id and push_wid != self._world_id:
                raise RuntimeError(
                    world_mismatch_error(self._world_id, push_wid))
            with self._metrics_lock:
                self._flightrec_ranks[int(push_rank)] = payload
            # A push IS evidence of a world abort (ranks only ship tails
            # from their failure paths): start the bounded collection now
            # — waiting for a disconnect-based abort would lose the dump
            # in worlds whose ranks all exit quickly after a structured
            # error (the service dies with this process).
            self._flightrec_incident(
                (payload or {}).get("error") or
                f"rank {push_rank} shipped a black-box incident tail")
            return ("ok",)
        if kind == "metrics_pull":
            caller_wid = req[1] if len(req) > 1 else ""
            if caller_wid and self._world_id and \
                    caller_wid != self._world_id:
                # symmetric with the push: never leak THIS world's store
                # to a co-located different world's caller
                raise RuntimeError(
                    world_mismatch_error(self._world_id, caller_wid))
            return ("metrics", self.metrics_store())
        if kind == "clock_probe":
            # Clock alignment (docs/tracing.md): answer with THIS host's
            # monotonic clock in µs — the same clock every Timeline here
            # stamps spans with — so a min-RTT-filtered battery of probes
            # lets each rank compute its offset to the coordinator's
            # timebase. Anonymous like "metrics"/"watch" (handled before
            # rank binding: a probing connection's teardown is never a
            # rank death); a co-located different world's probe is refused
            # — its reference clock lives behind its own service.
            caller_wid = req[2] if len(req) > 2 else ""
            if caller_wid and self._world_id and \
                    caller_wid != self._world_id:
                raise RuntimeError(
                    world_mismatch_error(self._world_id, caller_wid))
            return ("clock", time.monotonic_ns() / 1e3)
        if kind == "bye":
            # Clean detach for clients that leave without a negotiated
            # world shutdown (tests, tooling): de-register so the
            # subsequent connection close is not mistaken for a rank death.
            with self._lock:
                self._deregister(_sock)
            return ("ok",)
        if kind == "watch":
            # Abort push channel: the response is DEFERRED until the world
            # aborts or the service stops. Deliberately anonymous — no rank
            # registration — so tearing the watch connection down is never
            # mistaken for a rank death. (Handler threads are daemons; a
            # parked watcher cannot hang service shutdown.) A watcher from
            # a DIFFERENT world (subset schedules co-locate worlds on one
            # port) is refused before anything else — it must neither park
            # nor receive THIS world's abort; a watcher arriving AFTER the
            # world negotiated shutdown belongs to the successor: refuse
            # retryably instead of parking (a park would answer "clean
            # stop" and leave the next world silently unwatched).
            caller_wid = req[1] if len(req) > 1 else ""
            if caller_wid and self._world_id and \
                    caller_wid != self._world_id:
                raise RuntimeError(
                    world_mismatch_error(self._world_id, caller_wid))
            with self._lock:
                if self._world_shutdown and self._watch_reason is None:
                    raise RuntimeError(CONTROLLER_RESTARTING)
            self._watch_event.wait()
            with self._lock:
                reason = self._watch_reason
            return ("abort", reason) if reason else ("ok", "stopping")
        # Every other message carries the sender's rank at req[1]: bind the
        # connection to it for failure detection. "hello" exists so ranks
        # identify at connect time (a rank can die before its first cycle),
        # while anonymous connections (NIC reachability probes open and
        # close without sending) are never mistaken for dead ranks.
        rank = req[1]
        if kind in ("hello", "hello_island"):
            # "hello_island" is an island head identifying itself
            # (docs/hierarchy.md): same gates as "hello" — the head IS a
            # rank (its own global rank, never the island id, so the
            # connection-binding map below stays rank-keyed) plus the
            # island roster the root expands submissions against.
            caller_wid = ""
            if kind == "hello" and len(req) > 2:
                caller_wid = req[2]
            elif kind == "hello_island" and len(req) > 4:
                caller_wid = req[4]
            if caller_wid and self._world_id and \
                    caller_wid != self._world_id:
                # a co-scheduled different world's client (subset
                # schedules share this port): refusing is what prevents
                # its remapped rank from superseding a LIVE member here
                raise RuntimeError(
                    world_mismatch_error(self._world_id, caller_wid))
            # A hello after this world's negotiated shutdown is a
            # NEXT-world client that reached the dying service on the
            # shared port: refuse with the retryable sentinel (its
            # connect+hello loop re-dials until the successor binds).
            # Without this, the dying service served the hello and the
            # client's FIRST CYCLE hit EOF at service stop — surfacing as
            # a spurious world abort mid-epoch (re-init soak finding).
            with self._lock:
                # an aborted world's dying service is the same shared-port
                # race as a negotiated shutdown's (a current-world rank
                # re-helloing after an abort is equally over); watchers
                # keep the abort answer — an already-parked current-world
                # watcher reconnecting after a transient drop must still
                # receive the reason (spawn_watch_thread contract). The
                # abort reason rides INSIDE the retryable sentinel so a
                # rank whose retried hello lost the race is not
                # misdirected toward a re-init problem.
                if self._world_shutdown or self._abort_fired:
                    reason = CONTROLLER_RESTARTING
                    if self._abort_fired and self._watch_reason:
                        reason += (" (predecessor world aborted: "
                                   f"{self._watch_reason})")
                    raise RuntimeError(reason)
        self._bind_connection(rank, _sock)
        if kind == "hello":
            return ("ok",)
        if kind == "hello_island":
            _, _, island, members = req[:4]
            succeeded_from = None
            with self._lock:
                self._islands[int(island)] = tuple(members)
                self._island_of = {r: i for i, mem in
                                   self._islands.items() for r in mem}
                prev = self._island_heads.get(int(island))
                self._island_heads[int(island)] = rank
                if prev is not None and prev != rank:
                    # standby succession (docs/recovery.md): the island is
                    # re-homed under the successor, so the old head's
                    # pending reconnect-window verdict is superseded — it
                    # is an island MEMBER now, served behind the new head
                    # and invisible here; letting its timer expire would
                    # declare a healthy world dead.
                    self._pending_reconnect.pop(prev, None)
                    succeeded_from = prev
            if succeeded_from is not None:
                LOG.warning(
                    "island %s head succession: rank %d took over from "
                    "rank %d", island, rank, succeeded_from)
            return ("ok",)
        if kind == "cycle":
            _, _, request_list = req
            key = ("cycle", self._current_cycle(rank))
            now = time.monotonic()
            with self._lock:
                # active-window start: first rank's arrival for this cycle
                # (straggler wait + construct count toward the autotune
                # score; inter-cycle client think time does not)
                self._cycle_t0.setdefault(key, now)
                # per-rank arrival order: the input straggler attribution
                # charges the cycle's spread from (docs/tracing.md)
                self._cycle_arrivals.setdefault(key, {})[rank] = now
            return self._cycles.submit(key, rank, request_list,
                                       lambda slot: self._run_cycle(slot, key))
        if kind == "payload":
            _, _, cycle_no, idx, data = req
            resp = self._history[cycle_no].responses[idx]
            # Frame once: the combine result is identical for every rank,
            # and HMAC+pickle over a fused buffer per rank would make the
            # coordinator's serial work O(size x bytes) per cycle.
            return self._payloads.submit(
                ("payload", cycle_no, idx), rank, data,
                lambda slot: Preserialized(
                    self._service.wire.frame(
                        self._combine_payload(resp, slot))))
        if kind == "sentry":
            # Gradient-sentry verdict exchange (docs/integrity.md): one
            # OR-fold of per-tensor finite bits per screened batch, so
            # skip/zero decisions are bit-identical on every rank. The
            # batch ordinal keys the rendezvous — batches execute in
            # negotiated order, so ordinal N is the same batch everywhere.
            from ..integrity.sentry import or_bits

            _, _, ordinal, bits = req
            # Bounded wait: a rank whose HOROVOD_GRAD_SENTRY drifted to
            # "off" never submits, and the default config has no stall
            # deadline to break the wedge — convert it into a loud,
            # structured failure instead (the typos-fail-loudly bar).
            return self._sentry_rv.submit(
                ("sentry", ordinal), rank, bits,
                lambda slot: or_bits(list(slot.values())),
                timeout_s=60.0,
                timeout_hint=(
                    "HOROVOD_GRAD_SENTRY must resolve identically on "
                    "every rank — a disarmed rank never joins the "
                    "verdict exchange."))
        if kind == "island_cycle":
            # One merged submission for a WHOLE island's cycle
            # (docs/hierarchy.md): expand back into the flat per-rank
            # slot and run the unchanged _run_cycle — validation, error
            # texts, stall/consensus escalation, cache bookkeeping and
            # response construction stay byte-identical with flat.
            _, _, island, submission = req
            return self._island_cycle(int(island), submission)
        if kind == "payload_island":
            # Host-plane payload forwarding: the head ships its members'
            # raw buffers UNSUMMED ({rank: bytes}) — float addition is
            # non-associative, so only the root's single sorted-rank
            # combine keeps the result bit-identical with flat.
            _, _, island, cycle_no, idx, datas = req
            resp = self._history[cycle_no].responses[idx]
            return self._payloads.submit_group(
                ("payload", cycle_no, idx), dict(datas),
                lambda slot: Preserialized(
                    self._service.wire.frame(
                        self._combine_payload(resp, slot))))
        if kind == "sentry_island":
            # Gradient-sentry verdict forwarding: per-member finite bits
            # ({rank: bits}) folded at the root over the WORLD — the
            # verdict must be the same OR-fold every flat rank computes.
            from ..integrity.sentry import or_bits

            _, _, island, ordinal, bit_map = req
            return self._sentry_rv.submit_group(
                ("sentry", ordinal), dict(bit_map),
                lambda slot: or_bits(list(slot.values())),
                timeout_s=60.0,
                timeout_hint=(
                    "HOROVOD_GRAD_SENTRY must resolve identically on "
                    "every rank — a disarmed rank never joins the "
                    "verdict exchange."))
        if kind == "abort_island":
            # A head detected one of ITS members dying and escalates the
            # death upstream so the whole world tears down with the same
            # flat attribution text (the head stays alive long enough to
            # forward, so the root would otherwise only see the island's
            # traffic stop).
            _, _, island, dead_rank, _reason = req
            self._abort_for_rank(int(dead_rank))
            return ("ok",)
        raise ValueError(f"unknown controller request {kind!r}")

    def _bind_connection(self, rank: int, sock: Any) -> None:
        """Bind a connection to the rank it serves for failure detection.
        A NEW connection for a rank SUPERSEDES any previous one
        (de-identified, not closed): a client that reconnects — its
        hello reply lost to a transient reset — must not have the stale
        connection's close attributed as its own death."""
        with self._lock:
            old = self._rank_conns.get(rank)
            if old is not None and old != id(sock):
                self._conn_ranks.pop(old, None)
            self._rank_conns[rank] = id(sock)
            self._conn_ranks[id(sock)] = rank
            healed = self._pending_reconnect.pop(rank, None)
        if healed is not None:
            _RECONNECT_WINDOW_HEALS.inc()
            LOG.warning("rank %d reconnected within the window; the "
                        "dropped connection is forgiven", rank)

    def _island_cycle(self, island: int, submission: Any) -> Any:
        """Root half of the two-level cycle: book island arrival and
        per-level flush ordinal, expand the merged submission into the
        per-global-rank slot, and group-submit it into the SAME cycle
        rendezvous flat ranks use. Expansion or fold failures are
        DEFERRED into the rendezvous compute — raising here would wedge
        the other islands forever; poisoning the compute fails every
        island loudly with the cause."""
        from . import hierarchy as _hier

        _hier.ROOT_MESSAGES.inc()
        key = ("cycle", self._current_cycle(("island", island)))
        now = time.monotonic()
        with self._lock:
            self._cycle_t0.setdefault(key, now)
            self._island_arrivals.setdefault(key, {})[island] = now
            self._island_ordinals.setdefault(key, {})[island] = \
                getattr(submission, "flush_ordinal", None)
        try:
            expanded = _hier.expand_submission(submission)
            fold_err = _hier.check_fold(submission)
            if fold_err:
                with self._lock:
                    self._island_errors.setdefault(key, []).append(
                        fold_err)
        except Exception as exc:  # noqa: BLE001 - deferred, see above
            with self._lock:
                self._island_errors.setdefault(key, []).append(
                    f"island {island} submission could not be expanded: "
                    f"{exc}")
            expanded = {r: RequestList(rank=r)
                        for r in getattr(submission, "members", ())} \
                or {0: RequestList(rank=0)}

        def compute(slot: Dict[int, Any]) -> Any:
            with self._lock:
                errors = self._island_errors.pop(key, None)
            if errors:
                raise RuntimeError("; ".join(errors))
            self._check_island_ordinals(key)
            result = self._run_cycle(slot, key)
            self._attribute_island_straggler(key)
            return result

        return self._cycles.submit_group(key, expanded, compute)

    def _check_island_ordinals(self, key: Any) -> None:
        """Per-LEVEL cycle-alignment cross-check (docs/hierarchy.md):
        each head stamps its submission with its OWN upstream cycle
        count, and all islands joined in one root rendezvous must name
        the same cycle — relative, like the per-rank check, so a
        desynced ISLAND fails loudly by name instead of smearing into
        per-rank noise. (The members' own ordinals still ride the
        expanded lists, so the flat per-rank check runs as well.)"""
        with self._lock:
            ordinals = self._island_ordinals.pop(key, None) or {}
        stamped = {i: o for i, o in ordinals.items() if o is not None}
        if len(set(stamped.values())) <= 1:
            return
        detail = ", ".join(
            f"island {i} (ranks "
            f"{', '.join(map(str, self._islands.get(i, ())))}) "
            f"at cycle {o}" for i, o in sorted(stamped.items()))
        raise RuntimeError(
            f"negotiation cycle stream desync between islands: {detail} "
            f"joined one rendezvous; every island head must forward "
            f"every cycle exactly once and in order — a desynced island "
            f"would silently misalign sentry ordinals, consensus "
            f"windows, and cache-bit positions for all its members")

    def _attribute_island_straggler(self, key: Any) -> None:
        """Island-level straggler attribution: charge the cycle's
        arrival spread to the LAST island (blamed rank = that island's
        head) so the report tool can name the slow island before the
        slow rank. The heads attribute their members island-locally."""
        with self._lock:
            arrivals = self._island_arrivals.pop(key, None)
            n_islands = len(self._islands)
        if arrivals is None or len(arrivals) < n_islands or \
                n_islands <= 1:
            return
        last_island, last_t = max(arrivals.items(), key=lambda kv: kv[1])
        spread = last_t - min(arrivals.values())
        head = self._island_heads.get(
            last_island, min(self._islands.get(last_island, (last_island,))))
        _STRAGGLER_LAST.labels(rank=head, island=last_island).inc()
        _STRAGGLER_BLAME_S.labels(rank=head,
                                  island=last_island).inc(spread)
        _ARRIVAL_SPREAD.observe(spread)

    def _combine_payload(self, resp: Response,
                         slot: Dict[int, bytes]) -> bytes:
        """Host-plane combine, with the consensus authority fed on the
        way out: the combined allreduce buffer is the value every rank
        SHOULD receive — digesting it here is what lets a mismatch name
        the exact outlier rank instead of "someone" (docs/integrity.md)."""
        combined = _combine(resp, slot)
        if self._consensus_authority is not None and \
                resp.response_type == ResponseType.ALLREDUCE:
            observed = combined
            if _sparse_codec(getattr(resp, "tensor_codec", "none")):
                # Sparse wire: the authority digests the DECODED DENSE
                # result — what training consumes — via the SAME shared
                # decode the ranks run (bit-identical float scatter
                # order), so a corrupt pair on one rank's receive leg
                # still names that rank (docs/compression.md §sparse).
                from . import sparse_wire

                observed = sparse_wire.decode_sum(
                    combined, resp.payload_bytes // 4,
                    len(slot)).tobytes()
            self._consensus_authority.observe_combine(resp.tensor_names,
                                                      observed)
        return combined

    def _current_cycle(self, rank: int) -> int:
        # Each rank participates in every cycle exactly once, in order; a
        # per-rank counter keeps the rendezvous keys aligned without a
        # global clock.
        with self._lock:
            counters = getattr(self, "_rank_cycles", None)
            if counters is None:
                counters = self._rank_cycles = {}
            n = counters.get(rank, 0)
            counters[rank] = n + 1
            return n

    def _expand_cache_cycle(self, slot: Dict[int, Any]):
        """Classify one cycle's submissions (docs/response-cache.md).

        Returns ``(expanded_slot, hit_positions)``: when EVERY rank sent
        the SAME cache-bit set, ``expanded_slot`` is None and
        ``hit_positions`` the sorted common positions (the bypass fires);
        otherwise any ``CacheRequest`` is expanded back into the full
        ``RequestList`` it stands for and normal negotiation runs."""
        from .response_cache import positions_of

        cache_sets: Dict[int, frozenset] = {}
        for rank, rl in slot.items():
            if not isinstance(rl, CacheRequest):
                continue
            if self._cache is None:
                raise RuntimeError(
                    f"rank {rank} sent a cache-bit cycle but the "
                    f"coordinator's response cache is disabled; "
                    f"HOROVOD_CACHE_CAPACITY must resolve identically on "
                    f"every rank")
            if rl.generation != self._cache.generation:
                raise RuntimeError(
                    f"response cache generation desync: rank {rank} sent "
                    f"generation {rl.generation}, coordinator holds "
                    f"{self._cache.generation}")
            expected_bits = (self._cache.capacity + 7) // 8
            if len(rl.bits) != expected_bits:
                # The bitvector length IS the capacity: divergent
                # HOROVOD_CACHE_CAPACITY values diverge eviction choices,
                # and an all-hit cycle would then misreplay silently —
                # refuse here, not only on the expand path.
                raise RuntimeError(
                    f"response cache capacity desync: rank {rank} sent a "
                    f"{len(rl.bits)}-byte bitvector, coordinator expects "
                    f"{expected_bits}; HOROVOD_CACHE_CAPACITY must resolve "
                    f"identically on every rank")
            cache_sets[rank] = frozenset(positions_of(rl.bits))
        if len(cache_sets) == len(slot) and \
                len(set(cache_sets.values())) == 1:
            return None, sorted(next(iter(cache_sets.values())))
        expanded = {
            rank: (self._cache.expand(rank, sorted(cache_sets[rank]))
                   if rank in cache_sets else rl)
            for rank, rl in slot.items()}
        return expanded, None

    @staticmethod
    def _requests_by_name(slot: Dict[int, RequestList]) -> Dict[str, Request]:
        """Identity source for cache insertion: the union of the cycle's
        requests, first-seen by rank order. Every tensor completing this
        cycle has its size-th arrival IN this cycle, so its name is
        present; for allreduce the identity is rank-invariant (negotiation
        errors on divergence), so any rank's request serves."""
        out: Dict[str, Any] = {}
        for rank in sorted(slot):
            for req in slot[rank].requests:
                out.setdefault(req.tensor_name, req)
        return out

    def _judge_consensus(self, slot: Dict[int, Any]):
        """Feed every rank's piggybacked digest windows to the judge
        (both message types carry the field); returns the first
        ``(outlier_ranks, tensor_names)`` verdict, or None."""
        verdict = None
        for rank in sorted(slot):
            windows = getattr(slot[rank], "integrity_digest", None)
            if not windows:
                continue
            if self._consensus_judge is None:
                if not getattr(self, "_consensus_warned", False):
                    self._consensus_warned = True
                    LOG.warning(
                        "rank %d ships consensus digests but the "
                        "coordinator's judge is disarmed; "
                        "HOROVOD_CONSENSUS_INTERVAL_STEPS must resolve "
                        "identically on every rank", rank)
                continue
            v = self._consensus_judge.submit(rank, windows)
            if v is not None and verdict is None:
                verdict = v
        return verdict

    def _escalate_world(self, response_list: ResponseList,
                        reason: str) -> None:
        """Shared escalation teardown (stall deadline and consensus
        mismatch both ride it): latch shutdown + the structured reason on
        this cycle's response, stop the negotiator, and unpark every
        channel a dying world could leave blocked — the watch push and
        any half-filled sentry-verdict rendezvous. Callers construct
        their own ERROR responses first (the two paths differ there)."""
        LOG.error("%s", reason)
        response_list.shutdown = True
        response_list.abort_reason = reason
        self._negotiator.request_shutdown()
        with self._lock:
            if self._watch_reason is None:
                self._watch_reason = reason
        self._watch_event.set()
        self._sentry_rv.abort(RuntimeError(reason))
        # Flight recorder (docs/blackbox.md): every world escalation —
        # stall deadline, consensus mismatch — leaves a black-box
        # incident file; ranks push their tails when the abort_reason
        # reaches them and the bounded collector folds whatever arrives.
        _flightrec.record(_flightrec.EV_ESCALATE, detail=reason[:200])
        self._flightrec_incident(reason)

    def _check_flush_ordinals(self, slot: Dict[int, Any],
                              key: Any) -> None:
        """Cycle-alignment cross-check (docs/tensor-fusion.md): every
        message carries the sender's own cycle count, and all ranks
        joined in one rendezvous must name the SAME cycle. The invariant
        was always load-bearing (rendezvous keys, sentry ordinals,
        consensus windows, cache-bit positions all assume it) but only
        implicit; sub-buffer flushing multiplies cycles per step, so a
        desynced stream now fails loudly naming the ranks instead of
        silently misaligning batches. The check is RELATIVE (ranks vs
        each other), not against the coordinator's own counter: tooling
        legitimately drives fresh short-lived clients — whose counts
        restart — against a persistent service, and a symmetric restart
        is not a desync. None (old/native wires) skips that rank."""
        del key  # the rendezvous key is coordinator bookkeeping, see above
        stamped = {rank: o for rank, o in
                   ((rank, getattr(rl, "flush_ordinal", None))
                    for rank, rl in slot.items()) if o is not None}
        if len(set(stamped.values())) <= 1:
            return
        detail = ", ".join(f"rank {r} at cycle {o}"
                           for r, o in sorted(stamped.items()))
        raise RuntimeError(
            f"negotiation cycle stream desync: {detail} joined one "
            f"rendezvous; every rank must join every cycle exactly once "
            f"and in order — a client that skipped or double-counted a "
            f"cycle would silently misalign sentry ordinals, consensus "
            f"windows, and cache-bit positions")

    def _run_cycle(self, slot: Dict[int, Any],
                   key: Any = None) -> Preserialized:
        self._check_flush_ordinals(slot, key)
        consensus_verdict = self._judge_consensus(slot)
        slot, hit_positions = self._expand_cache_cycle(slot)
        if hit_positions is not None:
            # All-ranks cache hit: replay the cached fused responses —
            # no table insertion, no response construction, no fusion
            # planning. The negotiator is still cycled once with nothing
            # added: it drains nothing and only runs its interval-gated
            # stall check over the still-incomplete table (+ reports a
            # latched shutdown) — a cache hit must never mask a dead rank.
            response_list = ResponseList(
                responses=[self._cache.response_at(p)
                           for p in hit_positions])
            tail = self._negotiator.construct_response_list()
            if tail.responses:
                # nothing was added this cycle, so nothing can have become
                # ready; anything else means the mirror diverged — poison
                # the rendezvous loudly rather than hang ranks on
                # responses an ack cannot reference
                raise RuntimeError(
                    "response cache desync: negotiator produced responses "
                    "on an all-hit cycle")
            response_list.shutdown = tail.shutdown
            response_list.stall_warnings = tail.stall_warnings
            response_list.stall_check = getattr(tail, "stall_check", False)
        else:
            for rank in sorted(slot):
                self._negotiator.add_request_list(slot[rank])
            response_list = self._negotiator.construct_response_list()
        if response_list.stall_warnings:
            _STALL_WARNINGS.inc(len(response_list.stall_warnings))
        escalation = self._stall_escalation.check(
            response_list.stall_warnings,
            check_ran=getattr(response_list, "stall_check", False))
        if escalation is not None:
            _STALL_ESCALATIONS.inc()
            # Abort-instead-of-hang: stalled tensors become ERROR responses
            # (their submitters' handles fail with the structured reason),
            # and the shutdown+abort_reason pair tells EVERY engine —
            # including the ranks that never submitted them — to fail its
            # outstanding work naming the missing ranks.
            names, _missing, reason = escalation
            response_list.responses = list(response_list.responses) + [
                Response(ResponseType.ERROR, tensor_names=[name],
                         error_message=reason) for name in names]
            self._escalate_world(response_list, reason)
        if consensus_verdict is not None:
            # Consensus escalation (docs/integrity.md), the stall
            # escalation's shape: the world holds PROVABLY diverged state,
            # so executing further data collectives would train on
            # garbage — this cycle's data responses become ERRORs carrying
            # the structured reason (every in-flight handle raises
            # ConsensusError), and the shutdown+abort_reason pair tears
            # the world down through the same path a stall does. The
            # aborted-ranks tag rides along so the elastic driver
            # blacklists the diverged slot on relaunch-and-restore.
            from ..core.status import format_consensus

            bad_ranks, bad_names = consensus_verdict
            reason = (
                f"cross-rank consensus verification failed: post-allreduce "
                f"state diverged on rank(s) "
                f"{', '.join(map(str, bad_ranks))}; relaunching beats "
                f"training on silently corrupted state. "
                f"{format_consensus(bad_ranks, bad_names)} "
                f"{SHUT_DOWN_ERROR} {format_aborted_ranks(bad_ranks)}")
            response_list.responses = [
                r for r in response_list.responses
                if r.response_type == ResponseType.ERROR
            ] + [Response(ResponseType.ERROR,
                          tensor_names=list(r.tensor_names),
                          error_message=reason)
                 for r in response_list.responses
                 if r.response_type != ResponseType.ERROR]
            self._escalate_world(response_list, reason)
        if response_list.shutdown:
            # Clean coordinated shutdown: connection drops after this cycle
            # are expected teardown, not rank deaths.
            with self._lock:
                self._world_shutdown = True
        with self._lock:
            t0 = self._cycle_t0.pop(key, None)
            arrivals = self._cycle_arrivals.pop(key, None)
        active_us = (time.monotonic() - t0) * 1e6 if t0 is not None else None
        if active_us is not None:
            _COORD_CYCLE_SECONDS.observe(active_us / 1e6)
        if arrivals is not None and len(arrivals) == self._size > 1:
            # Straggler attribution: charge this cycle's arrival spread to
            # the last arriver. Only fully-observed cycles count — a
            # partial map (a rank's request expanded from history during
            # teardown) would misattribute the missing rank's timing.
            last_rank, last_t = max(arrivals.items(), key=lambda kv: kv[1])
            spread = last_t - min(arrivals.values())
            island = self._island_of.get(last_rank, 0)
            _STRAGGLER_LAST.labels(rank=last_rank, island=island).inc()
            _STRAGGLER_BLAME_S.labels(rank=last_rank,
                                      island=island).inc(spread)
            _ARRIVAL_SPREAD.observe(spread)
            if self._straggler is not None and not response_list.shutdown:
                # closed-loop mitigation: the detector folds the same
                # attribution stream over its sliding window and raises
                # the eviction advisory itself (off the cycle path)
                self._straggler.observe_cycle(last_rank, spread)
        self._maybe_autotune(response_list, active_us)
        if self._applied_codec not in (None, "none"):
            # Tuned-codec application is a RESPONSE rewrite, never a
            # request rule: ranks submit their default codec as always
            # (the negotiation table stays uniform — a rank-side switch
            # would race in-flight submissions into mismatch errors), and
            # the coordinator re-stamps eligible negotiated batches so
            # every rank executes the identical quantized program. Only
            # default-wire allreduces of the large tensor class are
            # eligible; explicitly quantized traffic keeps its codec.
            sparse_tuned = _sparse_codec(self._applied_codec)
            for resp in response_list.responses:
                if resp.response_type == ResponseType.ALLREDUCE and \
                        resp.tensor_codec == "none" and \
                        resp.payload_bytes >= self._codec_min_bytes and \
                        (not sparse_tuned
                         or resp.tensor_dtype == DataType.FLOAT32):
                    # the sparse wire is f32-only by layout: stamping a
                    # non-f32 batch would only trip the engine's
                    # deterministic downgrade (and its warning) per step
                    resp.tensor_codec = self._applied_codec
        ack = None
        if self._cache is not None:
            # Cache bookkeeping AFTER autotune: a threshold retune queues a
            # generation bump, and responses fusion-planned before the bump
            # must not be cached (ranks apply the same rule off the stamped
            # generation, keeping the mirrors in lockstep).
            unchanged = not self._cache_bump_pending
            if self._cache_bump_pending:
                self._cache_bump_pending = False
                if self._cache_capacity_pending is not None:
                    # capacity retune rides the same deferred point: the
                    # bump's clear() resets positions, so resizing here
                    # can never orphan a live slot; ranks adopt the new
                    # capacity from tuned_knobs alongside the new
                    # generation, keeping bitvector lengths in lockstep
                    self._cache.capacity = self._cache_capacity_pending
                    self._cache_capacity_pending = None
                self._cache.bump()
            if hit_positions is not None:
                if escalation is None and not response_list.shutdown:
                    if unchanged:
                        self._cache.touch(hit_positions)
                    ack = CacheHitAck(
                        positions=hit_positions,
                        generation=self._cache.generation,
                        tuned_cycle_ms=response_list.tuned_cycle_ms,
                        tuned_knobs=response_list.tuned_knobs,
                        stall_warnings=response_list.stall_warnings,
                        stall_check=response_list.stall_check)
                # degraded hit (escalation / latched shutdown): ranks get
                # the full materialized list; no insert — the batches are
                # already cached and the world is ending
            elif unchanged and not response_list.shutdown:
                self._cache.insert_cycle(self._requests_by_name(slot),
                                         response_list.responses)
            response_list.cache_generation = self._cache.generation
        with self._lock:
            self._history[self._cycle_no] = response_list
            # History only needs to survive until the payload exchanges of
            # that cycle finish; keep a small sliding window.
            stale = self._cycle_no - 16
            if stale in self._history:
                del self._history[stale]
            self._cycle_no += 1
        # One frame serves every rank (identical ResponseList / ack by
        # construction — the property that makes lockstep execution legal).
        return Preserialized(self._service.wire.frame(
            ack if ack is not None else response_list))

    def _maybe_autotune(self, response_list: ResponseList,
                        active_us: Optional[float] = None) -> None:
        """Apply a tuning-plane decision: fusion threshold directly on the
        negotiator (bumping the cache generation on a real change), cycle
        time and the extended knob map piggybacked to every rank on the
        response (the Params broadcast of ``parameter_manager.cc:213``,
        docs/autotune.md)."""
        if self._autotuner is None:
            return
        decision = self._autotuner.observe_cycle(response_list,
                                                 active_us=active_us)
        if decision is not None:
            knobs = decision.config
            if "fusion_threshold_bytes" in knobs:
                self.set_fusion_threshold(
                    int(knobs["fusion_threshold_bytes"]))
            if "cycle_time_ms" in knobs:
                self._tuned_cycle_ms = float(knobs["cycle_time_ms"])
            if "cache_capacity" in knobs:
                self.set_cache_capacity(int(knobs["cache_capacity"]))
            if "codec" in knobs:
                codec = str(knobs["codec"])
                # never-applied == the "none" baseline: the FIRST decision
                # can already carry a flip (codec may be the only unpinned
                # knob), and skipping its bump would leave warm cached
                # layouts replaying the full-precision wire forever
                if codec != (self._applied_codec or "none") and \
                        self._cache is not None:
                    # a codec flip re-stamps every future batch: the whole
                    # cached working set is stale AT ONCE — bump instead
                    # of letting dead entries displace through the LRU
                    self._cache_bump_pending = True
                self._applied_codec = codec
            extras = {k: knobs[k] for k in
                      ("cache_capacity", "metrics_interval_s", "codec",
                       "fusion_subbuffers", "fused_apply")
                      if k in knobs}
            if extras:
                self._tuned_knobs = extras
        response_list.tuned_cycle_ms = self._tuned_cycle_ms
        response_list.tuned_knobs = self._tuned_knobs

    def set_fusion_threshold(self, threshold_bytes: int) -> None:
        """Apply a (re)tuned fusion threshold. Repacking changes which
        fused batches exist, so every cached fused layout is stale: a REAL
        change bumps the response-cache generation, which the next cycle
        response (list or ack) carries to every rank — they clear, miss
        once, and renegotiate under the new packing. Without the bump a
        warm cache would replay the old layout forever and the knob change
        would silently never take effect (docs/response-cache.md). The
        bump is deferred to the next cycle's bookkeeping point (see
        ``_cache_bump_pending``); the new threshold itself applies to the
        negotiator immediately."""
        self._negotiator.set_fusion_threshold(threshold_bytes)
        if self._cache is not None and \
                self._fusion_threshold != threshold_bytes:
            self._cache_bump_pending = True
        self._fusion_threshold = threshold_bytes

    def set_cache_capacity(self, capacity: int) -> None:
        """Apply a (re)tuned response-cache capacity. The bitvector length
        IS the capacity, so both mirrors must move at one generation
        boundary: the resize is deferred to the cycle bookkeeping point
        (with the generation bump it implies), and ranks adopt the new
        capacity from the same response's ``tuned_knobs`` — a no-op when
        the cache is disabled or the value is unchanged."""
        capacity = max(int(capacity), 1)
        if self._cache is None or capacity == self._cache.capacity or \
                (self._cache_capacity_pending is not None and
                 capacity == self._cache_capacity_pending):
            return
        self._cache_capacity_pending = capacity
        self._cache_bump_pending = True

    def shutdown(self) -> None:
        self._watch_event.set()  # release parked watchers with a clean stop
        self._service.shutdown()

    def wait_world_shutdown(self, timeout_s: float) -> bool:
        """Poll until the world negotiated its shutdown cycle (or timeout).
        Used by a non-member subset-service host so its own exit does not
        tear the controller out from under a still-running subset."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                # An aborted world is equally final — no point waiting.
                if self._world_shutdown or self._abort_fired:
                    return True
            time.sleep(0.05)
        with self._lock:
            return self._world_shutdown or self._abort_fired


def _sparse_codec(codec: str) -> bool:
    """Whether a negotiated codec tag names the top-k sparse wire."""
    if not codec or codec == "none":
        return False
    from .compression import Compression

    return bool(getattr(Compression.lookup(codec), "sparse", False))


def _combine(resp: Response, slot: Dict[int, bytes]) -> bytes:
    """Host-mode data plane: the numpy reduction the coordinator applies to
    the gathered per-rank payloads. Only used for CPU test worlds; the TPU
    data plane is XLA collectives (SURVEY §2.10: "host fallback via numpy
    only for tests")."""
    if resp.response_type == ResponseType.ALLREDUCE:
        if _sparse_codec(getattr(resp, "tensor_codec", "none")):
            # Top-k sparse wire (docs/compression.md §sparse): equal-K
            # rank payloads concatenate rank-ordered — the reference
            # allgather shape (Horovod ``tensorflow/__init__.py:72-83``);
            # every rank scatter-adds the pairs to the dense sum itself.
            return b"".join(slot[rank] for rank in sorted(slot))
        dtype = numpy_dtype(resp.tensor_dtype)
        total: Optional[np.ndarray] = None
        for rank in sorted(slot):
            arr = np.frombuffer(slot[rank], dtype=dtype)
            total = arr.copy() if total is None else total + arr
        assert total is not None
        return total.tobytes()
    if resp.response_type == ResponseType.ALLGATHER:
        return b"".join(slot[rank] for rank in sorted(slot))
    if resp.response_type == ResponseType.BROADCAST:
        root = resp.tensor_sizes[0]
        return slot[root]
    raise ValueError(f"cannot combine payload for {resp.response_type}")


def connect_with_hello(addr, secret, timeout_s, connect_attempts,
                       hello, chaos=None, on_reconnect=None,
                       fallback=None) -> BasicClient:
    """Connect and identify, retrying the connect+hello PAIR as a unit.

    ``on_reconnect`` is armed on the client BEFORE the hello runs: if the
    hello's own response frame is lost, ``request()`` heals by reconnect
    + resend, and the service's dedup REPLAYS the stored reply without
    invoking the handler — only the hook's bare re-identify can bind the
    healed connection to the rank. Arming after this function returns
    leaves that window open (a healthy rank gets its fresh connection
    treated as anonymous and is aborted at reconnect-window expiry).

    On re-init (``shutdown(); init()`` on the same port) a connect can
    land in the DYING previous service's kernel backlog — accepted by the
    kernel, closed unserved when its event loop exits — so the hello gets
    EOF (or RST) despite a "successful" connect. Only connection-level
    failures retry; a decoded server response (error frame / RemoteError,
    e.g. protocol mismatch or an abort in progress) is deliberate and
    final. The server side tolerates the retry of a hello whose reply was
    lost: a new connection for a rank supersedes the old registration, so
    the stale close is not a rank death."""
    last: Optional[Exception] = None
    # Time-based re-dial windows, NOT a fixed iteration count. Two distinct
    # waits hide behind a refused/failed hello:
    #   * transport losses / CONTROLLER_RESTARTING — the gap between a
    #     world's negotiated shutdown and the successor service binding,
    #     bounded by a slow rank's teardown (seconds);
    #   * WORLD_MISMATCH — a non-member of world N racing ahead into world
    #     N+1 while N's service still holds the shared port, which lasts
    #     however long world N's REMAINING WORKLOAD runs (an epoch can be
    #     minutes). A fixed 100-iteration budget was terminally exhausted
    #     in exactly the race it existed to survive; this window is tied
    #     to HOROVOD_START_TIMEOUT — the same generous, user-tunable knob
    #     that governs initial connects (core.config.start_timeout_s).
    from ..core.config import Config
    start_timeout_s = max(Config.from_env().start_timeout_s, 30.0)
    deadline = time.monotonic() + 30.0  # transport-loss budget
    mismatch_deadline = time.monotonic() + start_timeout_s
    while True:
        client = None
        try:
            # Construction inside the try: the constructor's own connect
            # attempts can exhaust with OSError, and that failure must ride
            # the same time-based windows as a lost hello instead of
            # escaping them (round-4 advisor).
            client = BasicClient(addr, secret=secret, timeout_s=timeout_s,
                                 attempts=connect_attempts, chaos=chaos,
                                 fallback=fallback)
            client.on_reconnect = on_reconnect
            hello(client)
            return client
        except (WireError, OSError) as exc:
            if client is not None:
                client.close()
            # EOF (ConnectionClosedError) or RST/reset (OSError) are
            # transport losses, and a decoded CONTROLLER_RESTARTING frame
            # is the dying previous world's service explicitly telling a
            # next-world client to re-dial; any other WireError is a
            # deliberate server decision — final.
            mismatch = WORLD_MISMATCH in str(exc)
            if not (isinstance(exc, (ConnectionClosedError, OSError))
                    or CONTROLLER_RESTARTING in str(exc)
                    or mismatch):
                raise
            last = exc
            now = time.monotonic()
            if mismatch:
                # every refusal proves the old service is still up; the
                # transport-loss budget must cover the teardown gap AFTER
                # the last refusal, so it rolls forward with each one
                deadline = max(deadline, now + 30.0)
                if now >= mismatch_deadline:
                    break
            elif now >= deadline:
                break
            time.sleep(0.3)
    raise WireError(
        f"controller hello failed after retries: {last}") from last


def spawn_watch_thread(addr, secret, request_reason, on_abort,
                       fallback=None) -> None:
    """Shared scaffolding for both controller clients' failure-push
    channel: a daemon thread opens a second, anonymous connection and
    performs one deferred-response request via ``request_reason(client)``
    (returns the abort reason, or None for a clean stop). Any terminal
    outcome — abort, controller death, clean stop — invokes
    ``on_abort(reason)``; the clean-stop case is harmless by construction
    because after the negotiated shutdown cycle nothing is blocked in a
    collective.

    Resilience: the connection idles with zero traffic for the whole job
    (keepalive enabled against NAT/conntrack expiry), and a CONNECTION
    loss is retried — a transient drop must re-park, not falsely abort a
    healthy world. Only repeated reconnect failure (the controller is
    really gone, so the world is over regardless) aborts. A CLEAN
    controller stop (request_reason returns None) fires nothing: the world
    negotiated its shutdown, and a spurious abort here would race the
    engine's finalizer draining its last still-completing batches. If the
    world aborted while the channel was down, the re-sent watch request is
    answered immediately (both services check the abort state first)."""
    def _loop() -> None:
        failures = 0
        while True:
            client = None
            try:
                client = BasicClient(addr, secret=secret, timeout_s=None,
                                     attempts=10, fallback=fallback)
                client.enable_keepalive()
                failures = 0
                reason = request_reason(client)
                if reason is None:  # clean stop: no abort to deliver
                    try:
                        client.close()
                    except Exception:  # noqa: BLE001
                        pass
                    return
            except Exception as exc:  # noqa: BLE001 - channel lost
                if client is not None:
                    try:
                        client.close()
                    except Exception:  # noqa: BLE001
                        pass
                if WORLD_MISMATCH in str(exc):
                    # A watcher only dials after its own world's hello
                    # succeeded on this port, so a mismatch means that
                    # service was REPLACED: this watcher's world is over.
                    # Fire the abort path — harmless if the engine already
                    # shut down cleanly, and it unparks a rank that a
                    # missed abort (world died while the channel was down)
                    # left blocked inside a collective.
                    reason = (f"{SHUT_DOWN_ERROR} (cause: {exc})")
                elif CONTROLLER_RESTARTING in str(exc):
                    # Authoritative "your world ended by negotiated
                    # shutdown": both services answer a watch with the
                    # abort reason BEFORE this sentinel, so a watcher can
                    # only see it when there is nothing to deliver — exit
                    # cleanly like the parked clean-stop path. (A fresh
                    # watcher of a live successor world cannot reach a
                    # dying listener: the old one closes before the new
                    # one binds, and the engine's hello to the successor
                    # precedes the watch spawn.)
                    return
                else:
                    failures += 1
                    if failures < 3:
                        time.sleep(1.0)
                        continue  # transient: reconnect and re-park
                    reason = (f"{SHUT_DOWN_ERROR} (cause: watch channel "
                              f"lost: {exc})")
            try:
                on_abort(reason)
            finally:
                if client is not None:
                    try:
                        client.close()
                    except Exception:  # noqa: BLE001
                        pass
            return

    threading.Thread(target=_loop, name="horovod-abort-watch",
                     daemon=True).start()


class ControllerClient:
    """Worker-side handle on the controller (one per process)."""

    # The Python service answers "clock_probe" (docs/tracing.md); the
    # engine reads this to decide whether a ClockSync thread can run.
    clock_sync_supported = True
    # The Python service answers "sentry" verdict exchanges
    # (docs/integrity.md); the native client's binary wire predates the
    # RPC and the sentry degrades to local verdicts there (warned once).
    sentry_exchange_supported = True
    # The Python service collects "flightrec" incident pushes on abort
    # (docs/blackbox.md); the native wire predates the RPC and the dump
    # degrades to a rank-local file there (warned once).
    flightrec_supported = True

    def __init__(self, addr,  # (host, port) or {intf: (host, port)}
                 secret: Optional[bytes] = None,
                 timeout_s: Optional[float] = None,
                 connect_attempts: int = 100,
                 rank: Optional[int] = None,
                 world_id: str = "",
                 fallback=None) -> None:
        # ``fallback``: the island's standby-head candidate set
        # (docs/recovery.md) — reconnects that exhaust the primary fail
        # over to the planned successor; the standby answers the
        # re-identify hello and the request retry replays under the same
        # seq against its fresh dedup slots.
        self._addr = addr
        self._secret = secret
        self._fallback = fallback
        self._cycle_no = 0
        self._last_cycle = 0  # parity with the native client: the
        # last_cycle property must read 0 (not raise) before a first
        # cycle completes
        self._rank = rank
        self._world_id = world_id
        # cumulative + last-cycle negotiation wire bytes (cycle() only;
        # payload exchanges excluded) — registry Counter primitives, with
        # the historical attribute names kept as read-through properties
        # (tests and controller_bench read them). The process-global
        # horovod_negotiation_* families aggregate across clients.
        self._neg_tx = Counter()
        self._neg_rx = Counter()
        self.last_cycle_tx_bytes = 0
        self.last_cycle_rx_bytes = 0
        # Deterministic fault injection (docs/chaos.md): the controller
        # request channel is THE chaos target — ordinals count this
        # client's logical round trips.
        from ..chaos import injector_from_env

        self._chaos = injector_from_env(rank)
        # Generous connect window: ranks race the coordinator's service
        # startup (JAX import time dominates), like orted waiting on the
        # reference's driver registration (``util/timeout.py``). Identify
        # immediately so the controller can attribute a connection drop to
        # this rank even if the process dies before its first cycle.
        if rank is None:
            self._client = BasicClient(addr, secret=secret,
                                       timeout_s=timeout_s,
                                       attempts=connect_attempts,
                                       chaos=self._chaos)
        else:
            self._client = connect_with_hello(
                addr, secret, timeout_s, connect_attempts,
                hello=lambda c: c.request(("hello", rank, world_id)),
                chaos=self._chaos, on_reconnect=self._reconnect_hello,
                fallback=fallback)
        # Sub-buffer flush pipelining (docs/tensor-fusion.md): a second,
        # dedicated connection for the DATA-side exchanges (payload /
        # sentry) so an in-flight flush parked in a coordinator rendezvous
        # never holds the cycle connection's request lock — without it,
        # rank A's parked payload(k) and rank B's parked cycle(k+1) can
        # deadlock each other's send (the classic two-channel inversion).
        # None until the engine opens it; payload()/sentry() then route
        # over it and cycle() keeps the main connection to itself, which
        # also keeps the per-cycle negotiation-byte bracket exact.
        self._data_client: Optional[BasicClient] = None
        self._timeout_s = timeout_s
        self._connect_attempts = connect_attempts

    def open_data_channel(self) -> None:
        """Dial the flush-pipeline data channel (idempotent). Identified
        like the cycle connection — a hello binds it to the rank, and the
        service's supersede rule keeps exactly one connection attributed
        at any time, so rank-death detection is unaffected. Carries its
        own chaos injector instance (an independent ordinal domain: the
        cycle channel's replay determinism must not depend on data-plane
        interleaving)."""
        if self._data_client is not None:
            return
        from ..chaos import injector_from_env

        data_chaos = injector_from_env(self._rank)
        self._data_client = connect_with_hello(
            self._addr, self._secret, self._timeout_s,
            self._connect_attempts,
            hello=lambda c: c.request(("hello", self._rank,
                                       self._world_id)),
            chaos=data_chaos, on_reconnect=self._reconnect_hello,
            fallback=self._fallback)

    def _reconnect_hello(self, client) -> None:
        """Re-identify after a transparent reconnect: the superseding
        hello is what tells the controller the dropped connection was a
        fault, not a death (it clears the reconnect-window verdict), and
        it must precede the resent request so a dedup REPLAY — which
        bypasses the handler — cannot leave the new connection
        anonymous. Armed BEFORE the initial hello (connect_with_hello),
        which can itself lose its response frame and heal."""
        client.bare_request(("hello", self._rank, self._world_id))

    def _arm_reconnect_hello(self) -> None:
        self._client.on_reconnect = self._reconnect_hello

    @property
    def last_cycle(self) -> int:
        """Ordinal of the most recently completed negotiation cycle —
        the engine's cross-rank span stamp (docs/tracing.md: every rank
        joins every cycle in order, so ordinal N names the same
        coordinator rendezvous in every per-rank trace). Part of the
        client interface, like ``clock_sync_supported``; the native
        client carries the same contract."""
        return self._last_cycle

    @property
    def negotiation_tx_bytes(self) -> int:
        """Cumulative cycle-metadata bytes sent (back-compat read-through;
        the canonical store is the metrics registry)."""
        return self._neg_tx.value

    @property
    def negotiation_rx_bytes(self) -> int:
        return self._neg_rx.value

    def cycle(self, rank: int, request_list) -> Any:
        """One negotiation round trip. ``request_list`` is a RequestList
        or, on the steady-state bypass, a ``messages.CacheRequest``; the
        answer is a ResponseList or a ``messages.CacheHitAck``
        (docs/response-cache.md)."""
        # The controller registers this connection under ``rank`` for
        # failure detection; remember it so close() can detach cleanly even
        # when the caller did not pass rank= at construction.
        if self._rank is None:
            self._rank = rank
            self._arm_reconnect_hello()
        # Cycle-alignment stamp (docs/tensor-fusion.md): the client's own
        # cycle count; the coordinator cross-checks the ranks of one
        # rendezvous against EACH OTHER so a desynced stream fails
        # loudly (relative check — see _check_flush_ordinals).
        if hasattr(request_list, "flush_ordinal"):
            request_list.flush_ordinal = self._cycle_no
        # Negotiation-byte accounting: without a data channel, cycle() and
        # payload() share one connection but run sequentially on the
        # engine loop thread; with one, payloads ride their own wire — in
        # both cases a delta bracketed around the request counts ONLY this
        # cycle's metadata bytes (the number the response cache exists to
        # shrink).
        wire = self._client._wire
        tx0, rx0 = wire.tx_bytes, wire.rx_bytes
        # Flight recorder (docs/blackbox.md): the negotiate-submit /
        # response pair with the cycle ordinal — the cross-rank
        # alignment ground truth of every incident classification.
        _flightrec.record(_flightrec.EV_NEGOTIATE, self._cycle_no)
        t0 = time.monotonic()
        out = self._client.request(("cycle", rank, request_list))
        _NEG_CYCLE_SECONDS.observe(time.monotonic() - t0)
        _NEG_CYCLES.inc()
        if isinstance(out, CacheHitAck):
            _flightrec.record(_flightrec.EV_CACHE_HIT, self._cycle_no,
                              aux=out.generation)
        else:
            gen = getattr(out, "cache_generation", None)
            _flightrec.record(_flightrec.EV_RESPONSE, self._cycle_no,
                              aux=-1 if gen is None else gen)
        self.last_cycle_tx_bytes = wire.tx_bytes - tx0
        self.last_cycle_rx_bytes = wire.rx_bytes - rx0
        self._neg_tx.inc(self.last_cycle_tx_bytes)
        self._neg_rx.inc(self.last_cycle_rx_bytes)
        _NEG_TX.inc(self.last_cycle_tx_bytes)
        _NEG_RX.inc(self.last_cycle_rx_bytes)
        self._last_cycle = self._cycle_no
        self._cycle_no += 1
        return out

    def payload(self, rank: int, response_idx: int, data: bytes,
                cycle_no: Optional[int] = None) -> bytes:
        """Host-plane payload exchange. ``cycle_no`` names the negotiation
        cycle the response batch belongs to; the default (the most
        recently completed cycle) is only correct when execution is
        serialized behind negotiation — a pipelined flush captures the
        ordinal at negotiation time and passes it explicitly."""
        client = self._data_client or self._client
        return client.request(
            ("payload", rank,
             self._last_cycle if cycle_no is None else cycle_no,
             response_idx, data))

    def sentry(self, rank: int, ordinal: int, bits: bytes) -> bytes:
        """Gradient-sentry verdict exchange (docs/integrity.md): OR-fold
        this batch's per-tensor finite bits across every rank. Rides the
        cycle connection — the engine loop runs batches sequentially, so
        the request/response sequencing stays strict like payload() —
        unless the flush pipeline opened the data channel, in which case
        it rides there with the payloads it brackets (a verdict parked in
        the rendezvous must never hold the cycle connection)."""
        client = self._data_client or self._client
        return client.request(("sentry", rank, ordinal, bits))

    def watch(self, on_abort: Callable[[str], None]) -> None:
        """Failure-push channel for ranks that can block OUTSIDE the
        control plane (inside a compiled device collective, which no
        poisoned rendezvous response can reach): one deferred-response
        "watch" request the controller answers only on abort/stop."""

        def _request_reason(client) -> Optional[str]:
            resp = client.request(("watch", self._world_id))
            if resp and resp[0] == "abort" and resp[1]:
                return resp[1]
            return None  # clean stop

        spawn_watch_thread(self._addr, self._secret, _request_reason,
                           on_abort, fallback=self._fallback)

    def close(self, detach: bool = True) -> None:
        """``detach=True`` (tooling/tests): clean goodbye, the departure is
        not a rank death. ``detach=False`` (the engine): no goodbye — if the
        world has not negotiated shutdown yet, this close IS a rank death
        and the controller must abort the peers. An engine that sent "bye"
        on its crash path would mask its own death and deadlock the world."""
        if detach and self._rank is not None:
            try:
                # farewell, not request(): a bye must never trigger a
                # reconnect+re-hello against a possibly dying controller
                # just to announce a departure the socket close already
                # announces
                self._client.farewell(("bye", self._rank))
            except Exception:  # noqa: BLE001 - controller may already be gone
                pass
        if self._data_client is not None:
            if detach:
                try:
                    self._data_client.farewell(("bye", self._rank))
                except Exception:  # noqa: BLE001 - same as above
                    pass
            self._data_client.close()
        self._client.close()
