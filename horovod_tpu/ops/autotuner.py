"""Autotuner wiring: score cycles, retune the live knobs.

Rebuild of the runtime side of ``horovod/common/parameter_manager.cc``,
grown into the closed-loop tuning plane (docs/autotune.md): when
``HOROVOD_AUTOTUNE=1``, each completed cycle contributes (bytes processed,
elapsed microseconds) and the optimizer proposes the next knob config.
Two backends share this facade (``HOROVOD_AUTOTUNE_BACKEND``):

* ``policy`` (default) — the pure-Python coordinate-descent loop of
  ``horovod_tpu.tune.policy``: no native core required, and it tunes the
  full knob set (fusion threshold, cycle time, response-cache capacity,
  codec, metrics interval) with median-of-window scoring, cooldown, and
  the best-known-config revert guard.
* ``native`` — the C++ GP/Bayesian optimizer (``cc/autotune.cc``, the
  reference's ``optim/bayesian_optimization``), classic (fusion, cycle)
  pair only.

Placement differs from the reference by design: the reference tunes on the
coordinator and broadcasts a Params struct over MPI; here the tuner lives
wherever the negotiator lives — in-process for size-1 worlds, on the rank-0
controller service for multi-process worlds, which piggybacks decisions on
the ``ResponseList`` AND the response-cache bypass ack (``CacheHitAck``),
so a warm steady state keeps receiving retunes. A retuned FUSION THRESHOLD
(or codec) bumps the response-cache generation through
``ControllerService.set_fusion_threshold``/the codec tracker: repacking
(or re-stamping) stales every cached fused layout, and without the bump a
warm cache would replay the old packing forever (docs/response-cache.md).

Audit trail: ``HOROVOD_AUTOTUNE_LOG`` appends a CSV of per-cycle samples
(``parameter_manager.cc:255-293``; header written only when the file is
new — a restarted run APPENDS, it must not re-write the header);
``HOROVOD_AUTOTUNE_DECISIONS`` appends a JSONL decision log rendered by
``tools/tune_report.py``; retune/revert counters and knob gauges land on
the obs registry either way.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ..core.config import Config
from ..core.logging import LOG
from ..tune.policy import Decision, TuningPolicy, audit_decision, \
    default_knobs


class _NativeBackend:
    """The C++ GP behind the same observe() contract as TuningPolicy."""

    def __init__(self, cfg: Config) -> None:
        from .. import cc

        if not cc.available():
            raise RuntimeError(
                f"HOROVOD_AUTOTUNE_BACKEND=native requires the native core "
                f"(horovod_tpu/cc): {cc.load_error()}")
        self._pm = cc.NativeParameterManager(
            float(cfg.fusion_threshold_bytes), float(cfg.cycle_time_ms),
            fusion_fixed=cfg.fusion_threshold_explicit,
            cycle_fixed=cfg.cycle_time_explicit)
        self.retunes = 0
        self.reverts = 0

    def config(self) -> dict:
        return {"fusion_threshold_bytes": self.fusion_threshold_bytes,
                "cycle_time_ms": self.cycle_time_ms}

    @property
    def fusion_threshold_bytes(self) -> int:
        return self._pm.fusion_threshold_bytes

    @property
    def cycle_time_ms(self) -> float:
        return self._pm.cycle_time_ms

    @property
    def best(self) -> dict:
        return self._pm.best

    def observe(self, bytes_processed: float,
                microseconds: float) -> Optional[Decision]:
        if not self._pm.update(bytes_processed, microseconds):
            return None
        self.retunes += 1
        decision = Decision(
            action="retune", knob="native_gp",
            value=(self.fusion_threshold_bytes, self.cycle_time_ms),
            score=bytes_processed / microseconds, best_score=float(
                self._pm.best.get("score_bytes_per_us", 0.0)),
            config=self.config())
        audit_decision(decision)
        return decision


class Autotuner:
    """Feeds cycle measurements to the configured optimizer backend and
    reports knob changes. Returns None from ``record`` until the knobs
    move; a non-None return is a :class:`tune.policy.Decision` whose
    ``config`` map the caller applies."""

    def __init__(self, cfg: Config, extended: bool = False,
                 local_observatory: bool = True) -> None:
        if cfg.autotune_backend not in ("policy", "native"):
            raise ValueError(
                f"bad HOROVOD_AUTOTUNE_BACKEND "
                f"{cfg.autotune_backend!r}; expected 'policy' or 'native'")
        self._decisions = None
        self._native = cfg.autotune_backend == "native"
        self._gate = None
        try:
            self._decisions = open(cfg.autotune_decisions, "a",
                                   encoding="utf-8") \
                if cfg.autotune_decisions else None
            if self._native:
                self._backend = _NativeBackend(cfg)
                self._sink({"action": "init", "backend": "native",
                            "config": self._backend.config()})
            else:
                # Evidence gate (docs/tensorwatch.md): with the numerics
                # observatory armed, the lossy codec knob's consent
                # (HOROVOD_AUTOTUNE_CODECS) becomes evidence-backed —
                # proposals wait for the measured-SNR certification and
                # an in-flight collapse forces a revert. None when the
                # observatory is off: the PR 7 consent-only behavior,
                # byte-identically. Native backend: classic pair only,
                # no codec knob to gate. A non-member service host
                # (start_subset_service) has NO engine in its process —
                # nothing would ever feed the gate, so armed evidence
                # gating there would block the consented codec for the
                # life of the job; it degrades to consent-only, warned
                # once (the established degrade pattern).
                from ..obs import tensorwatch as _tensorwatch

                if local_observatory:
                    self._gate = _tensorwatch.policy_gate(cfg)
                elif cfg.tensorwatch_interval_steps > 0:
                    LOG.warning(
                        "autotune: numerics observatory armed but this "
                        "controller host runs no engine to feed the "
                        "evidence gate; lossy codec consent stays "
                        "consent-only here (docs/tensorwatch.md)")
                self._backend = TuningPolicy(
                    default_knobs(cfg, extended=extended),
                    window=cfg.autotune_window,
                    cooldown=cfg.autotune_cooldown,
                    tolerance=cfg.autotune_tolerance,
                    decision_sink=self._sink,
                    fault=cfg.autotune_fault,
                    propose_gate=self._gate)
            self._log = open(cfg.autotune_log, "a", encoding="utf-8") \
                if cfg.autotune_log else None
        except BaseException:
            # backend construction can refuse (missing native core, bad
            # fault/codec spec) and the CSV open can fail AFTER the sink
            # opened; under run_elastic every retried attempt would leak
            # another fd
            if self._decisions is not None:
                self._decisions.close()
                self._decisions = None
            raise
        self._last_cycle_ts = time.monotonic()
        if self._log is not None:
            # Append mode + restartable jobs: the header belongs to the
            # FILE, not the construction — only an empty/new file gets one
            # (restarted runs used to accumulate a duplicate header per
            # attempt, corrupting column-indexed readers).
            self._log.seek(0, 2)
            if self._log.tell() == 0:
                self._log.write(
                    "timestamp,fusion_threshold_bytes,cycle_time_ms,"
                    "bytes,microseconds,score_bytes_per_us\n")
                self._log.flush()

    def _sink(self, record: dict) -> None:
        if self._decisions is None:
            return
        record = dict(record, t=round(time.time(), 3))
        self._decisions.write(json.dumps(record, sort_keys=True) + "\n")
        self._decisions.flush()

    def observe_cycle(self, response_list,
                      active_us: Optional[float] = None
                      ) -> Optional[Decision]:
        """Score one completed cycle and return the Decision when the
        optimizer moved the knobs. Exactly one component owns an Autotuner
        per process — the engine in local worlds, the controller service
        on rank 0 of multi-process worlds — so the timestamp state lives
        here.

        ``active_us`` is the cycle's ACTIVE window: negotiation wait +
        execution, excluding idle sleep between cycles. The reference
        samples saturated training where wall time equals active time
        (``parameter_manager.cc:145-171``); under sparse submission the
        wall clock would mix user think-time into the score and the
        optimizer would partly tune noise, so callers pass the active
        window and the wall delta is only a fallback."""
        from .messages import ResponseType

        now = time.monotonic()
        microseconds = active_us if active_us is not None \
            else (now - self._last_cycle_ts) * 1e6
        self._last_cycle_ts = now
        bytes_processed = sum(
            r.payload_bytes for r in response_list.responses
            if r.response_type != ResponseType.ERROR)
        return self.observe(bytes_processed, microseconds)

    def observe(self, bytes_processed: float,
                microseconds: float) -> Optional[Decision]:
        """Score one (bytes, active µs) sample — the raw form the native
        controller service drains from C++ (no ResponseList exists on the
        Python side there)."""
        if self._gate is not None:
            # Evidence collapse first (docs/tensorwatch.md): when the
            # observatory measured an admitted lossy codec's SNR below
            # the floor, the forced revert outranks this cycle's score —
            # the applier reads codec="none" from the decision's config
            # and the response rewrite stops at the next cycle.
            forced = self._gate.maybe_revert(self._backend)
            if forced is not None:
                LOG.warning(
                    "autotune: numerics observatory reported an SNR "
                    "collapse on the admitted lossy codec; reverting to "
                    "the full-precision wire (decision-log audited)")
                return forced
        if bytes_processed <= 0 or microseconds <= 0:
            return None
        if self._log is not None:
            self._log.write(f"{time.time():.3f},"
                            f"{self._backend.fusion_threshold_bytes},"
                            f"{self._backend.cycle_time_ms:.3f},"
                            f"{bytes_processed:.0f},{microseconds:.1f},"
                            f"{bytes_processed / microseconds:.3f}\n")
            self._log.flush()
        decision = self._backend.observe(bytes_processed, microseconds)
        if decision is not None:
            if self._native:
                # the policy sinks its own decisions; the native GP has
                # no sink hook, so the facade keeps the JSONL audit
                # complete for it too
                self._sink({"action": decision.action,
                            "knob": decision.knob,
                            "value": decision.value,
                            "score": decision.score,
                            "best_score": decision.best_score,
                            "config": decision.config})
            LOG.debug("autotune %s: %s -> %r (score %.3f, best %.3f)",
                      decision.action, decision.knob, decision.value,
                      decision.score, decision.best_score)
        return decision

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None
        if self._decisions is not None:
            self._decisions.close()
            self._decisions = None

    @property
    def best(self) -> dict:
        return self._backend.best
