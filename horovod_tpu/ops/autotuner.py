"""Autotuner wiring: score cycles, retune fusion threshold + cycle time.

Rebuild of the runtime side of ``horovod/common/parameter_manager.cc``: when
``HOROVOD_AUTOTUNE=1``, each completed cycle contributes (bytes processed,
elapsed microseconds); the native GP/Bayesian optimizer
(``cc/autotune.cc``) scores points as bytes/us (median-of-5 windows) and
proposes the next (fusion threshold, cycle time) to try. Knobs explicitly
pinned via env stay fixed. ``HOROVOD_AUTOTUNE_LOG`` appends a CSV of
parameter/score history (``parameter_manager.cc:255-293``).

Placement differs from the reference by design: the reference tunes on the
coordinator and broadcasts a Params struct over MPI; here the tuner lives
wherever the negotiator lives — in-process for size-1 worlds, on the rank-0
controller service for multi-process worlds, which piggybacks the tuned
cycle time on the ResponseList (``messages.ResponseList.tuned_cycle_ms``)
AND on the response-cache bypass ack (``messages.CacheHitAck``), so a warm
steady state keeps receiving retunes. A retuned FUSION THRESHOLD is applied
through ``ControllerService.set_fusion_threshold``, which bumps the
response-cache generation: repacking stales every cached fused layout, and
without the bump a warm cache would replay the old packing forever
(docs/response-cache.md).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from ..core.config import Config
from ..core.logging import LOG


class Autotuner:
    """Feeds cycle measurements to the native parameter manager and reports
    knob changes. Returns None from ``record`` until the knobs move."""

    def __init__(self, cfg: Config) -> None:
        from .. import cc

        if not cc.available():
            raise RuntimeError(
                f"HOROVOD_AUTOTUNE=1 requires the native core "
                f"(horovod_tpu/cc): {cc.load_error()}")
        self._pm = cc.NativeParameterManager(
            float(cfg.fusion_threshold_bytes), float(cfg.cycle_time_ms),
            fusion_fixed=cfg.fusion_threshold_explicit,
            cycle_fixed=cfg.cycle_time_explicit)
        self._last_cycle_ts = time.monotonic()
        self._log = open(cfg.autotune_log, "a", encoding="utf-8") \
            if cfg.autotune_log else None
        if self._log is not None:
            self._log.write("timestamp,fusion_threshold_bytes,cycle_time_ms,"
                            "bytes,microseconds,score_bytes_per_us\n")
            self._log.flush()

    def observe_cycle(self, response_list,
                      active_us: Optional[float] = None
                      ) -> Optional[Tuple[int, float]]:
        """Score one completed cycle and return
        (fusion_threshold_bytes, cycle_ms) when the optimizer moved the
        knobs. Exactly one component owns an Autotuner per process — the
        engine in local worlds, the controller service on rank 0 of
        multi-process worlds — so the timestamp state lives here.

        ``active_us`` is the cycle's ACTIVE window: negotiation wait +
        execution, excluding idle sleep between cycles. The reference
        samples saturated training where wall time equals active time
        (``parameter_manager.cc:145-171``); under sparse submission the
        wall clock would mix user think-time into the score and the GP
        would partly optimize noise, so callers pass the active window
        and the wall delta is only a fallback."""
        from .messages import ResponseType

        now = time.monotonic()
        microseconds = active_us if active_us is not None \
            else (now - self._last_cycle_ts) * 1e6
        self._last_cycle_ts = now
        bytes_processed = sum(
            r.payload_bytes for r in response_list.responses
            if r.response_type != ResponseType.ERROR)
        return self.observe(bytes_processed, microseconds)

    def observe(self, bytes_processed: float,
                microseconds: float) -> Optional[Tuple[int, float]]:
        """Score one (bytes, active µs) sample — the raw form the native
        controller service drains from C++ (no ResponseList exists on the
        Python side there)."""
        if bytes_processed <= 0 or microseconds <= 0:
            return None
        if self._log is not None:
            self._log.write(f"{time.time():.3f},"
                            f"{self._pm.fusion_threshold_bytes},"
                            f"{self._pm.cycle_time_ms:.3f},"
                            f"{bytes_processed:.0f},{microseconds:.1f},"
                            f"{bytes_processed / microseconds:.3f}\n")
            self._log.flush()
        if not self._pm.update(bytes_processed, microseconds):
            return None
        new_threshold = self._pm.fusion_threshold_bytes
        new_cycle = self._pm.cycle_time_ms
        LOG.debug("autotune: fusion_threshold=%d cycle_time=%.2fms",
                  new_threshold, new_cycle)
        return new_threshold, new_cycle

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    @property
    def best(self) -> dict:
        return self._pm.best
