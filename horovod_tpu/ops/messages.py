"""Control-plane message types for the eager collective controller.

Rebuild of ``horovod/common/message.{h,cc}`` + ``wire/message.fbs``: a
``Request`` describes one named tensor a rank wants to reduce/gather/
broadcast; a ``Response`` tells every rank what to execute (possibly a fused
batch) or carries a coordinator-constructed error. The reference serializes
these with FlatBuffers for the MPI wire (``message.fbs:20-101``); our wire is
the authenticated pickle channel of ``runner.network`` — the message volume
is names and shapes at cycle frequency, far below where a zero-copy format
matters, and the payload data plane never goes through these objects.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class DataType(enum.IntEnum):
    """Wire dtype ids (``message.h:26-37``); bfloat16 added for TPU."""

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10


_NUMPY_NAMES = {
    "uint8": DataType.UINT8, "int8": DataType.INT8,
    "uint16": DataType.UINT16, "int16": DataType.INT16,
    "int32": DataType.INT32, "int64": DataType.INT64,
    "float16": DataType.FLOAT16, "float32": DataType.FLOAT32,
    "float64": DataType.FLOAT64, "bool": DataType.BOOL,
    "bfloat16": DataType.BFLOAT16,
}


def dtype_of(array) -> DataType:
    name = str(array.dtype)
    if name not in _NUMPY_NAMES:
        raise ValueError(f"unsupported tensor dtype {name!r}")
    return _NUMPY_NAMES[name]


class RequestType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2


OP_NAMES = {
    RequestType.ALLREDUCE: "allreduce",
    RequestType.ALLGATHER: "allgather",
    RequestType.BROADCAST: "broadcast",
}


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    ERROR = 3


@dataclass
class Request:
    """One rank's intent for one named tensor (``message.h:44-97``)."""

    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_type: DataType
    tensor_shape: Tuple[int, ...]
    root_rank: int = -1
    # Device kind string replaces the reference's CUDA device id
    # (``common.h:109``: CPU_DEVICE_ID=-1); on TPU all eager tensors live on
    # the process's device set, so this only distinguishes cpu/tpu paths.
    device: str = "cpu"
    # Wire-compression codec tag ("none"/"int8"/"fp8"/"topk"): quantized
    # and sparse codecs change the collective PROGRAM every rank must
    # issue, so the codec is negotiated like the dtype — mismatches
    # become coordinator errors, and fusion only batches same-codec
    # tensors. Cast codecs (fp16/bf16) stay "none" here: they already
    # changed tensor_type itself.
    codec: str = "none"
    # Fused reduce+apply fingerprint (docs/tensor-fusion.md §fused
    # apply): the ApplyRule identity this tensor's reduction should land
    # an optimizer apply for, "" for a plain allreduce. Negotiated like
    # the codec — it changes the compiled program every rank issues, so
    # mismatches become coordinator errors and fusion only batches
    # same-fingerprint tensors; a hyperparameter change is a new
    # fingerprint and therefore a response-cache identity MISS. Absent
    # on wires that predate the field (native controller): the engine
    # keeps its apply contexts rank-side and degrades to the split
    # reduce-then-apply execution there.
    apply_fingerprint: str = ""
    # Hierarchy wire (docs/hierarchy.md): when an island head merged N
    # congruent member requests into this one, the sorted global ranks it
    # stands for — the root expands it back into one per-member request
    # so the flat negotiation core (and its exact error texts) runs
    # unchanged. None on every flat-topology request and on wires that
    # predate the field.
    member_ranks: Optional[Tuple[int, ...]] = None
    # Per-member allgather first-dim sizes, aligned to ``member_ranks``
    # (allgather is the one op where congruent member requests legally
    # differ — in dim0). None for every other op and on flat wires.
    gather_dim0s: Optional[Tuple[int, ...]] = None


@dataclass
class RequestList:
    """Everything one rank submits in one cycle (``message.h:99-127``).

    ``integrity_digest`` piggybacks the rank's completed consensus digest
    windows (docs/integrity.md) — ``[(ordinal, [(kind, names, hex)])]``
    or None between windows — on the cycle it was already paying for,
    the same wire-growth precedent as the PR-3 cache bits. The native
    controller wire predates the field (deterministic local-only
    degrade)."""

    rank: int
    requests: List[Request] = field(default_factory=list)
    shutdown: bool = False
    integrity_digest: Optional[list] = None
    # Sub-buffer flush ordinal (docs/tensor-fusion.md): the client's own
    # count of negotiation cycles it has joined. Every rank joins every
    # cycle exactly once and in order — the invariant the whole cycle
    # bookkeeping (rendezvous keys, sentry ordinals, consensus windows,
    # cache-bit positions) rests on, and one that generation-ordered
    # sub-buffer flushing leans on even harder (multiple cycles per step).
    # The coordinator cross-checks the ranks of one rendezvous against
    # EACH OTHER (relative — symmetric restarts by fresh tooling clients
    # stay legal) and a mismatch fails LOUDLY instead of silently
    # misaligning batches. None on wires that predate the field.
    flush_ordinal: Optional[int] = None


@dataclass
class Response:
    """Coordinator's instruction to all ranks (``message.h:129-184``).

    ``tensor_names`` holds >1 entry when allreduces were fused into one
    batch; ``tensor_sizes`` carries per-rank first-dim sizes for allgather
    (the recvcounts of ``operations.cc:843-927``) and the root rank for
    broadcast. ``tensor_dtype``/``payload_bytes`` let the data plane and the
    fusion planner work without re-deriving tensor metadata.
    """

    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    tensor_sizes: List[int] = field(default_factory=list)
    tensor_dtype: Optional[DataType] = None
    payload_bytes: int = 0
    # negotiated wire-compression codec for the batch (see Request.codec)
    tensor_codec: str = "none"
    # Apply-capable response kind (docs/tensor-fusion.md §fused apply):
    # the negotiated ApplyRule fingerprint when every rank asked this
    # batch to land applied parameters, "" for a plain reduce. Uniform
    # across the batch by construction (fusion keys on it); wires that
    # predate the field leave it "" and the engine's rank-side apply
    # contexts run the split execution instead.
    fused_apply: str = ""


@dataclass
class ResponseList:
    """All responses for one cycle, in execution order; identical on every
    rank — the property that makes SPMD data-plane execution legal
    (``message.h:186-214``). ``tuned_cycle_ms`` piggybacks autotuner
    decisions to every rank, the role the coordinator's Params broadcast
    plays in the reference (``parameter_manager.cc:213`` SyncParams).

    ``stall_warnings`` carries the coordinator's CheckForStalledTensors
    output to every rank (the native wire already shipped these strings;
    the Python wire now does too) — the input the stall-shutdown
    escalation tracks. ``abort_reason`` is set alongside ``shutdown=True``
    when the shutdown is an ABORT rather than a negotiated drain: engines
    fail outstanding handles with this structured reason (which names the
    missing ranks, see ``core.status.RanksAbortedError``) instead of the
    generic SHUT_DOWN_ERROR."""

    responses: List[Response] = field(default_factory=list)
    shutdown: bool = False
    tuned_cycle_ms: Optional[float] = None
    # Closed-loop tuning plane (docs/autotune.md): the coordinator's
    # latest extended-knob map ({"cache_capacity": ..,
    # "metrics_interval_s": .., "codec": ..}), piggybacked like
    # tuned_cycle_ms so every rank applies retunes without a second wire.
    # None until the tuner's first extended decision (and always None on
    # the native controller wire, which predates the field).
    tuned_knobs: Optional[dict] = None
    stall_warnings: List[str] = field(default_factory=list)
    # True when the coordinator actually RAN its stall check this cycle
    # (the check is interval-gated): an empty warning list is then an
    # authoritative "nothing is stalled", letting the escalation tracker
    # retire resolved episodes exactly. The native wire cannot express
    # this (empty is ambiguous there), so it stays False and the tracker
    # falls back to warning-cadence pruning.
    stall_check: bool = False
    abort_reason: Optional[str] = None
    # Response-cache generation on the coordinator when this list was
    # finalized (docs/response-cache.md). A rank holding a DIFFERENT
    # generation clears its cache, adopts this one, and skips caching this
    # list's responses (they were fusion-planned before the bump). None
    # means the coordinator has no cache at all (capacity 0, or the native
    # controller wire, which predates the field) — ranks then disable
    # their caches rather than bypass against a coordinator that cannot
    # expand a cache-bit cycle.
    cache_generation: Optional[int] = None


@dataclass
class CacheRequest:
    """A rank's ENTIRE cycle submission when every locally-enqueued request
    hits its response cache: a fixed-size bitvector of cache positions
    instead of the full ``RequestList`` (upstream's cache-bit design;
    docs/response-cache.md). ``generation`` pins the cache state the bits
    were computed against — the coordinator refuses bits from another
    generation as a desync rather than misinterpreting positions."""

    rank: int
    bits: bytes
    generation: int
    # consensus digest windows (see RequestList.integrity_digest): the
    # steady-state bypass must keep shipping digests too, or a warm cache
    # would silently disarm the verification it rides beside
    integrity_digest: Optional[list] = None
    # sub-buffer flush ordinal (see RequestList.flush_ordinal): the warm
    # steady state keeps the cycle-alignment cross-check too
    flush_ordinal: Optional[int] = None


@dataclass
class IslandSubmission:
    """ONE island's entire negotiation cycle, forwarded by its
    sub-coordinator to the root (docs/hierarchy.md). Exactly one of the
    three payload forms is set: ``cache`` when every member sent the SAME
    cache-bit vector (the AND-merged steady state, PR 3 path), ``requests``
    when every member's cold-path RequestList was congruent (merged
    per-position, codec and apply_fingerprint negotiated at the island
    level exactly like dtypes — ``[]`` is a valid merged idle cycle), or
    ``raw`` (verbatim per-member RequestList/CacheRequest map) whenever
    ANY member deviates — the root then runs the flat per-rank path and
    produces byte-identical flat error texts naming actual global ranks.

    ``flush_ordinal`` is the HEAD's own upstream cycle count (the
    per-level PR 9 cross-check: the root compares islands against each
    other and a desynced island fails loudly naming the island).
    ``member_ordinals``/``digests`` preserve the members' own flush
    ordinals and consensus digest windows for the merged forms so the
    root's world-size cross-check and consensus judge still run per
    GLOBAL rank; the raw form leaves them None (the items carry their
    own). ``fold`` is the head's digest-of-digests over the shipped
    windows (integrity.consensus.fold_digest) — the root recomputes it
    and a mismatch escalates as island-level wire corruption."""

    island: int
    members: Tuple[int, ...]
    flush_ordinal: Optional[int] = None
    cache: Optional[CacheRequest] = None
    requests: Optional[List[Request]] = None
    raw: Optional[Dict[int, Any]] = None
    member_ordinals: Optional[Dict[int, Optional[int]]] = None
    digests: Optional[Dict[int, Any]] = None
    fold: Optional[str] = None
    shutdown_ranks: Tuple[int, ...] = ()


@dataclass
class CacheHitAck:
    """Coordinator's compact answer when EVERY rank's cycle was the same
    cache-bit set: replay the cached fused responses at ``positions`` (in
    listed order — identical on every rank, which keeps lockstep execution
    legal exactly like a broadcast ResponseList). Carries everything the
    full list would have piggybacked: the autotuner's cycle time, and the
    stall-check output — a cache hit must never mask a dead rank, so the
    ``StallEscalation`` inputs keep flowing at full cadence."""

    positions: List[int] = field(default_factory=list)
    generation: int = 0
    tuned_cycle_ms: Optional[float] = None
    # tuning-plane piggyback, mirroring ResponseList.tuned_knobs: a warm
    # steady state must keep receiving extended-knob retunes too
    tuned_knobs: Optional[dict] = None
    stall_warnings: List[str] = field(default_factory=list)
    stall_check: bool = False
