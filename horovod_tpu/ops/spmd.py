"""In-jit SPMD collectives: the hot data plane.

The reference executes collectives in a background C++ thread with
MPI/NCCL calls on fused buffers (``horovod/common/operations.cc:768-1621``).
On TPU, inside a jit-compiled SPMD program there is no negotiation problem —
every device executes the same program in the same order by construction —
so the entire controller disappears and the data plane is just XLA
collectives keyed by mesh axis name. These functions are meant to be called
inside ``shard_map``/``pjit`` (or any context with a bound axis name) and are
the building blocks the ``DistributedOptimizer`` uses.

Name/argument surface mirrors the reference op set (allreduce / allgather /
broadcast, ``operations.h:108-126``) plus ``reducescatter``, which the
reference only used internally for hierarchical allreduce
(``operations.cc:1349-1446``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def _axes(axis_name: AxisName) -> tuple:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def _axis_size(axis_name: AxisName):
    size = 1
    for a in _axes(axis_name):
        size = size * lax.axis_size(a)
    return size


def _vma_tracking_active(axis_name: AxisName) -> bool:
    """Whether the surrounding trace tracks varying-manual-axes at all.

    With ``check_rep/check_vma=False`` every value reports an empty vma set,
    which is indistinguishable from "replicated" by type alone — but in that
    mode shard_map also does NOT auto-psum cotangents, so legacy psum/pmean
    semantics are the correct ones. Probe: pvary of a fresh scalar carries
    the axis in its vma type iff tracking is on."""
    try:
        probe = lax.pcast(jnp.zeros(()), _axes(axis_name), to="varying")
        vma = jax.typeof(probe).vma
    except Exception:  # noqa: BLE001 - any failure → assume legacy tracing
        return False
    return all(a in vma for a in _axes(axis_name))


def _varies_over(x, axis_name: AxisName) -> bool:
    """Whether ``x`` is *varying* (per-shard distinct) along the axis.

    Only meaningful when vma tracking is active (see
    ``_vma_tracking_active``); callers must fall back to legacy collective
    semantics otherwise."""
    try:
        vma = jax.typeof(x).vma
    except (AttributeError, TypeError):
        return True
    return any(a in vma for a in _axes(axis_name))


def operand_vma(*xs):
    """Union of the operands' varying-manual-axes types, or ``None`` under
    legacy tracing (a JAX without vma types, or a ``check_vma=False``
    trace). The single compat point for the version-dependent
    ``jax.typeof(x).vma`` probe — pallas out-shape typing
    (``ops.pallas_attention``) and ring-attention accumulator typing
    (``parallel.ring_attention``) both key off it."""
    try:
        out = frozenset()
        for x in xs:
            out |= jax.typeof(x).vma
        return out
    except (AttributeError, TypeError):
        return None


def allreduce(x: jax.Array, axis_name: AxisName, average: bool = True) -> jax.Array:
    """Sum (or average) across the named mesh axis.

    Reference semantics: allreduce returns the *average* by default on the
    framework API layer (sum in the core, divide at the edge —
    ``torch/mpi_ops_v2.cc:66-72``). Here XLA's pmean fuses the divide.

    TPU/JAX subtlety with no reference analog: under shard_map, the
    cotangent of a *replicated* parameter is already psum-med across the
    axis by the transpose rule (JAX's varying-axes type system), i.e. the
    gradient arrives pre-summed and typed as non-varying. Issuing another
    psum would multiply by the axis size — the classic double-allreduce bug
    of naive Horovod-on-SPMD ports. We inspect the operand's vma type: a
    varying value gets the real collective; a non-varying value is treated
    as already reduced, so "sum" is the identity and "average" is a local
    divide. A replicated value that was never reduced (e.g. a constant) has
    sum == size * x under Horovod semantics; write that explicitly as
    ``x * hvd.num_devices()`` — it is not an allreduce.
    """
    if _varies_over(x, axis_name) or not _vma_tracking_active(axis_name):
        return lax.pmean(x, axis_name) if average else lax.psum(x, axis_name)
    return x / _axis_size(axis_name) if average else x


def allgather(x: jax.Array, axis_name: AxisName) -> jax.Array:
    """Concatenate along dim 0 across the axis, like the reference allgather
    (``operations.cc:843-927``: rank-ordered concat on the first dimension).

    Per-rank first-dim sizes must be equal inside a jit program (static
    shapes); the eager engine handles the ragged case by padding
    (``ops.engine``), matching the recvcounts/displacements logic of the
    reference only where shapes are dynamic.
    """
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def broadcast(x: jax.Array, root_rank: int, axis_name: AxisName) -> jax.Array:
    """Every participant receives root's value.

    Implemented as a masked psum — one collective, no gather of all shards
    (SURVEY §2.10: "broadcast = psum of masked value"). The reference uses
    MPI_Bcast / ncclBcast (``operations.cc:1593-1609``).
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def reducescatter(x: jax.Array, axis_name: AxisName, average: bool = False) -> jax.Array:
    """psum_scatter along dim 0; the ICI analog of the NCCL ReduceScatter
    stage of hierarchical allreduce (``operations.cc:1349-1380``)."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if average:
        out = out / lax.axis_size(axis_name)
    return out


def axis_rank(axis_name: AxisName) -> jax.Array:
    """This shard's index along the axis (device-level 'rank' inside jit)."""
    return lax.axis_index(axis_name)
