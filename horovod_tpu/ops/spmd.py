"""In-jit SPMD collectives: the hot data plane.

The reference executes collectives in a background C++ thread with
MPI/NCCL calls on fused buffers (``horovod/common/operations.cc:768-1621``).
On TPU, inside a jit-compiled SPMD program there is no negotiation problem —
every device executes the same program in the same order by construction —
so the entire controller disappears and the data plane is just XLA
collectives keyed by mesh axis name. These functions are meant to be called
inside ``shard_map``/``pjit`` (or any context with a bound axis name) and are
the building blocks the ``DistributedOptimizer`` uses.

Name/argument surface mirrors the reference op set (allreduce / allgather /
broadcast, ``operations.h:108-126``) plus ``reducescatter``, which the
reference only used internally for hierarchical allreduce
(``operations.cc:1349-1446``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.registry import registry as _metrics

AxisName = Union[str, Sequence[str]]

# Observability plane (docs/metrics.md): SPMD collectives execute inside
# compiled programs, so Python counters can only see TRACE time — these
# count lowerings (one per trace, not per training step) and the wire
# bytes each lowered collective moves per execution. A steady training
# loop re-traces nothing, so steps after the first leave these flat;
# compare against step counts from your training loop, not wall clock.
_SPMD_LOWERINGS = _metrics().counter(
    "horovod_spmd_lowerings_total",
    "Collective lowerings traced by the in-jit SPMD layer "
    "(per trace, not per step)", labels=("op",))
_SPMD_WIRE_PRE = _metrics().counter(
    "horovod_spmd_wire_bytes_pre_total",
    "Per-execution full-precision bytes the traced quantized allreduces "
    "would have moved")
_SPMD_WIRE_POST = _metrics().counter(
    "horovod_spmd_wire_bytes_post_total",
    "Per-execution on-wire bytes of the traced quantized allreduces "
    "(payload at wire dtype + shared block scales)")


def _axes(axis_name: AxisName) -> tuple:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def _maybe_sentry(out, operand, axis_name):
    """Numerical-health guard over the SPMD reduction result
    (docs/integrity.md): when ``HOROVOD_GRAD_SENTRY`` is armed, the
    non-finite count of the local operand is psum-med alongside the data
    and the policy applies as pure jnp ops — collective by construction,
    bit-identical on every rank. The policy is read at TRACE time (env,
    like every other knob here): a steady training loop re-traces
    nothing, so flip it before the first step. Only the real-collective
    paths guard; pre-summed cotangents (vma tracking) never ran a
    collective here and pass through untouched."""
    import os

    from ..core import config as _config

    policy = (os.environ.get(_config.HOROVOD_GRAD_SENTRY, "off")
              .strip().lower() or "off")
    if policy == "off":
        return out
    from ..integrity.sentry import spmd_guard

    return spmd_guard(out, operand, axis_name, policy)


def _axis_size(axis_name: AxisName):
    # lax.axis_size exists on every supported JAX: core.jax_compat
    # installs it (from the axis-env frame) on releases that predate it
    size = 1
    for a in _axes(axis_name):
        size = size * lax.axis_size(a)
    return size


def _vma_tracking_active(axis_name: AxisName) -> bool:
    """Whether the surrounding trace tracks varying-manual-axes at all.

    With ``check_rep/check_vma=False`` every value reports an empty vma set,
    which is indistinguishable from "replicated" by type alone — but in that
    mode shard_map also does NOT auto-psum cotangents, so legacy psum/pmean
    semantics are the correct ones. Probe: pvary of a fresh scalar carries
    the axis in its vma type iff tracking is on."""
    try:
        probe = lax.pcast(jnp.zeros(()), _axes(axis_name), to="varying")
        vma = jax.typeof(probe).vma
    except Exception:  # noqa: BLE001 - any failure → assume legacy tracing
        return False
    return all(a in vma for a in _axes(axis_name))


def _varies_over(x, axis_name: AxisName) -> bool:
    """Whether ``x`` is *varying* (per-shard distinct) along the axis.

    Only meaningful when vma tracking is active (see
    ``_vma_tracking_active``); callers must fall back to legacy collective
    semantics otherwise."""
    try:
        vma = jax.typeof(x).vma
    except (AttributeError, TypeError):
        return True
    return any(a in vma for a in _axes(axis_name))


def operand_vma(*xs):
    """Union of the operands' varying-manual-axes types, or ``None`` under
    legacy tracing (a JAX without vma types, or a ``check_vma=False``
    trace). The single compat point for the version-dependent
    ``jax.typeof(x).vma`` probe — pallas out-shape typing
    (``ops.pallas_attention``) and ring-attention accumulator typing
    (``parallel.ring_attention``) both key off it."""
    try:
        out = frozenset()
        for x in xs:
            out |= jax.typeof(x).vma
        return out
    except (AttributeError, TypeError):
        return None


def allreduce(x: jax.Array, axis_name: AxisName, average: bool = True) -> jax.Array:
    """Sum (or average) across the named mesh axis.

    Reference semantics: allreduce returns the *average* by default on the
    framework API layer (sum in the core, divide at the edge —
    ``torch/mpi_ops_v2.cc:66-72``). Here XLA's pmean fuses the divide.

    TPU/JAX subtlety with no reference analog: under shard_map, the
    cotangent of a *replicated* parameter is already psum-med across the
    axis by the transpose rule (JAX's varying-axes type system), i.e. the
    gradient arrives pre-summed and typed as non-varying. Issuing another
    psum would multiply by the axis size — the classic double-allreduce bug
    of naive Horovod-on-SPMD ports. We inspect the operand's vma type: a
    varying value gets the real collective; a non-varying value is treated
    as already reduced, so "sum" is the identity and "average" is a local
    divide. A replicated value that was never reduced (e.g. a constant) has
    sum == size * x under Horovod semantics; write that explicitly as
    ``x * hvd.num_devices()`` — it is not an allreduce.
    """
    _SPMD_LOWERINGS.labels(op="allreduce").inc()
    if _varies_over(x, axis_name) or not _vma_tracking_active(axis_name):
        out = lax.pmean(x, axis_name) if average \
            else lax.psum(x, axis_name)
        return _maybe_sentry(out, x, axis_name)
    return x / _axis_size(axis_name) if average else x


def allgather(x: jax.Array, axis_name: AxisName) -> jax.Array:
    """Concatenate along dim 0 across the axis, like the reference allgather
    (``operations.cc:843-927``: rank-ordered concat on the first dimension).

    Per-rank first-dim sizes must be equal inside a jit program (static
    shapes); the eager engine handles the ragged case by padding
    (``ops.engine``), matching the recvcounts/displacements logic of the
    reference only where shapes are dynamic.
    """
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def broadcast(x: jax.Array, root_rank: int, axis_name: AxisName) -> jax.Array:
    """Every participant receives root's value.

    Implemented as a masked psum — one collective, no gather of all shards
    (SURVEY §2.10: "broadcast = psum of masked value"). The reference uses
    MPI_Bcast / ncclBcast (``operations.cc:1593-1609``).
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def reducescatter(x: jax.Array, axis_name: AxisName, average: bool = False) -> jax.Array:
    """psum_scatter along dim 0; the ICI analog of the NCCL ReduceScatter
    stage of hierarchical allreduce (``operations.cc:1349-1380``)."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if average:
        out = out / lax.axis_size(axis_name)
    return out


def quantized_reducescatter(x: jax.Array, axis: str, codec) -> jax.Array:
    """Block-quantized reduce-scatter of a flat f32 bucket: steps 1-3 of
    the EQuARX factoring (see :func:`quantized_allreduce`) WITHOUT the
    gather leg — each rank keeps the dequantized SUM of its own chunk.
    This is the scatter half the ZeRO-1 sharded apply rides
    (``XlaDataPlane.reduce_scatter_apply``): the gradient moves as wire
    dtype, the applied parameters gather back at full f32 (parameters
    are the training state; quantizing them would change numerics).

    Skipping the gather leg's re-quantization means the per-chunk sum
    carries ONE quantization error instead of two — strictly less error
    than :func:`quantized_allreduce`, but therefore NOT bit-identical to
    the replicated quantized wire (docs/sharding.md; the bit-exact
    contract of ZeRO-1 applies to the f32 wire).

    ``x`` must be 1-D with length divisible into whole codec blocks per
    rank — the engine's power-of-two apply buckets guarantee this."""
    size = int(lax.axis_size(axis))
    wire_dt = codec.wire_dtype()
    n_elems = x.shape[0]
    block, padded = codec.block_layout(n_elems, size)
    if padded != n_elems:
        raise ValueError(
            f"quantized_reducescatter needs whole blocks per rank: "
            f"n={n_elems} pads to {padded} (block={block}, size={size})")
    pre_b, post_b = codec.wire_cost(n_elems, size)
    _SPMD_WIRE_PRE.inc(pre_b)
    _SPMD_WIRE_POST.inc(post_b)
    n_blocks = padded // block
    blocks = x.reshape(n_blocks, block)

    # 1. shared block scales (the only f32 wire, ~n/block elements)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    shared_max = lax.pmax(absmax, axis)
    scale = jnp.where(shared_max > 0, shared_max / codec.QMAX,
                      jnp.ones_like(shared_max)).astype(codec.SCALE_DTYPE)
    inv = (1.0 / scale.astype(jnp.float32))[:, None]

    # 2. quantize + scatter leg (wire dtype operand)
    if jnp.issubdtype(wire_dt, jnp.floating):  # fp8: saturating cast
        q = (blocks * inv).astype(wire_dt)
    else:
        q = jnp.clip(jnp.round(blocks * inv),
                     -codec.QMAX, codec.QMAX).astype(wire_dt)
    received = lax.all_to_all(q.reshape(size, padded // size), axis,
                              split_axis=0, concat_axis=0)

    # 3. widened accumulator (exact for int8), dequantized with THIS
    # chunk's slice of the shared scales — no gather leg
    acc_dt = jnp.float32 if jnp.issubdtype(wire_dt, jnp.floating) \
        else jnp.int32
    chunk_sum = received.astype(acc_dt).sum(axis=0)
    nb_chunk = n_blocks // size
    r = lax.axis_index(axis)
    scale_chunk = lax.dynamic_slice(
        scale.astype(jnp.float32), (r * nb_chunk,), (nb_chunk,))
    out = chunk_sum.astype(jnp.float32).reshape(nb_chunk, block) * \
        scale_chunk[:, None]
    return out.reshape(-1)


def quantized_allreduce(x: jax.Array, axis_name: AxisName,
                        average: bool = True, codec=None) -> jax.Array:
    """Allreduce whose wire payload is block-quantized int8/fp8 (EQuARX,
    arxiv 2506.17615): ~4x fewer collective bytes than f32 at a bounded,
    block-relative error (``codec.ERROR_BOUND`` of the block absmax).

    The factoring is quantized-reduce-scatter + quantized-all-gather, the
    decomposition EQuARX applies inside XLA's allreduce:

    1. *shared scales*: per-``BLOCK`` absmax is ``pmax``-ed across the
       axis (the only full-precision wire, ~|x|/BLOCK elements), so every
       rank quantizes with the SAME step and the integer payloads sum
       exactly;
    2. *scatter leg*: each rank quantizes its bucket and ``all_to_all``s
       the per-destination chunks — the collective operand is the wire
       dtype (``s8``/``f8e4m3``), the property the HLO wire-dtype tests
       pin;
    3. *widened accumulate*: received chunks are widened to an int32
       accumulator (f32 for fp8) and summed locally — exact for int8 up
       to world sizes of 2^31/127 ≈ 16M, far beyond the 4096 design
       point;
    4. *gather leg*: the per-chunk mean is re-quantized to the wire dtype
       (the mean is back in-range by construction: |sum/size| <= QMAX)
       and ``all_gather``-ed, again with a quantized operand;
    5. *dequantize*: multiply by the shared block scales.

    A multi-axis ``axis_name`` chains one quantized reduction per axis
    (sum over (a, b) == sum over b of sums over a); both hops then carry
    quantized bytes. Non-float inputs and pre-summed cotangents (vma
    tracking, see :func:`allreduce`) fall back to :func:`allreduce`
    semantics — the same operand-type determinism on every rank because
    dtype and vma type are trace-time static.
    """
    from .compression import Compression

    _SPMD_LOWERINGS.labels(op="quantized_allreduce").inc()
    codec = codec or Compression.int8
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return allreduce(x, axis_name, average=average)
    if _vma_tracking_active(axis_name) and not _varies_over(x, axis_name):
        # already reduced by the shard_map transpose (see allreduce)
        return x / _axis_size(axis_name) if average else x
    xf = x.astype(jnp.float32)
    out = xf
    for a in _axes(axis_name):
        out = _quantized_axis_sum(out, a, codec)
    if average:
        out = out / _axis_size(axis_name)
    return _maybe_sentry(out, xf, axis_name).astype(x.dtype)


def _quantized_axis_sum(x: jax.Array, axis: str, codec) -> jax.Array:
    """One-axis quantized SUM of an f32 array (steps 1-5 above)."""
    size = int(lax.axis_size(axis))
    wire_dt = codec.wire_dtype()
    orig_shape = x.shape
    flat = x.reshape(-1)
    n_elems = flat.shape[0]
    if n_elems == 0:
        # empty leaf: the sum of nothing is nothing; the block math below
        # would divide by a zero block size
        return x
    # Pad so the bucket splits into `size` equal chunks of whole blocks
    # (codec.block_layout is the single definition of this geometry,
    # shared with the tests' error-bound math and the benchmark auditor)
    block, padded = codec.block_layout(n_elems, size)
    pre_b, post_b = codec.wire_cost(n_elems, size)
    _SPMD_WIRE_PRE.inc(pre_b)
    _SPMD_WIRE_POST.inc(post_b)
    if padded != n_elems:
        # zeros_like(flat, shape=...) keeps flat's varying-axes type under
        # vma tracking (a bare zeros() is replicated and the concat would
        # be ill-typed there); identical under legacy tracing
        flat = jnp.concatenate(
            [flat, jnp.zeros_like(flat, shape=(padded - n_elems,))])
    n_blocks = padded // block
    blocks = flat.reshape(n_blocks, block)

    # 1. shared block scales: the scale wire IS the pmax (tiny, f32)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    shared_max = lax.pmax(absmax, axis)
    scale = jnp.where(shared_max > 0, shared_max / codec.QMAX,
                      jnp.ones_like(shared_max)).astype(codec.SCALE_DTYPE)
    inv = (1.0 / scale.astype(jnp.float32))[:, None]

    # 2. quantize + scatter leg (wire dtype operand)
    if jnp.issubdtype(wire_dt, jnp.floating):  # fp8: saturating cast
        q = (blocks * inv).astype(wire_dt)
    else:
        q = jnp.clip(jnp.round(blocks * inv),
                     -codec.QMAX, codec.QMAX).astype(wire_dt)
    received = lax.all_to_all(q.reshape(size, padded // size), axis,
                              split_axis=0, concat_axis=0)

    # 3. widened accumulator: int32 is EXACT for int8 payloads
    acc_dt = jnp.float32 if jnp.issubdtype(wire_dt, jnp.floating) \
        else jnp.int32
    chunk_sum = received.astype(acc_dt).sum(axis=0)

    # 4. re-quantize the chunk MEAN (back in wire range) + gather leg
    mean = chunk_sum.astype(jnp.float32) / size
    if jnp.issubdtype(wire_dt, jnp.floating):
        r = mean.astype(wire_dt)
    else:
        r = jnp.round(mean).astype(wire_dt)
    gathered = lax.all_gather(r, axis, axis=0, tiled=True)

    # 5. dequantize with the shared scales; undo the mean back to a sum
    out = gathered.reshape(n_blocks, block).astype(jnp.float32) * \
        scale.astype(jnp.float32)[:, None] * size
    return out.reshape(-1)[:n_elems].reshape(orig_shape)


def sparse_allreduce(x: jax.Array, axis_name: AxisName,
                     average: bool = True, codec=None, residual=None):
    """Allreduce whose wire is top-k (indices, values) pairs — the in-jit
    twin of the eager engine's sparse codec path (docs/compression.md
    §sparse): each shard selects its k largest-magnitude entries
    (``lax.top_k`` over |x|, k from ``codec.k_of``), all-gathers the
    pairs over the reference allgather shape (Horovod
    ``tensorflow/__init__.py:72-83``), and scatter-adds every shard's
    contribution back to the dense sum — ``k·8`` wire bytes per
    contribution instead of ``n·4``.

    ``residual`` opts into error feedback: pass the carried residual
    array (same shape as ``x``; zeros on step one) and the call returns
    ``(out, new_residual)`` — the dropped mass of ``x + residual`` —
    to thread into the next step. Without it the call returns ``out``
    alone and dropped mass is simply lost (the ablation arm).

    Non-float inputs and pre-summed cotangents (vma tracking, see
    :func:`allreduce`) fall back to dense :func:`allreduce` semantics —
    trace-time static, so every rank lowers the same program."""
    from .compression import Compression

    _SPMD_LOWERINGS.labels(op="sparse_allreduce").inc()
    codec = codec or Compression.topk
    if not jnp.issubdtype(x.dtype, jnp.floating):
        out = allreduce(x, axis_name, average=average)
        return out if residual is None else (out, residual)
    if _vma_tracking_active(axis_name) and not _varies_over(x, axis_name):
        # already reduced by the shard_map transpose (see allreduce)
        out = x / _axis_size(axis_name) if average else x
        return out if residual is None else (out, residual)
    orig_shape, orig_dt = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    if n == 0:
        out = x
        return out if residual is None else (out, residual)
    corrected = flat
    if residual is not None:
        corrected = flat + residual.reshape(-1).astype(jnp.float32)
    k = codec.k_of(n)
    pre_b, post_b = codec.wire_cost(n, 1)
    _SPMD_WIRE_PRE.inc(pre_b)
    _SPMD_WIRE_POST.inc(post_b)
    _, idx = lax.top_k(jnp.abs(corrected), k)
    vals = corrected[idx]
    g_idx, g_vals = idx, vals
    for a in _axes(axis_name):
        g_idx = lax.all_gather(g_idx, a, axis=0, tiled=True)
        g_vals = lax.all_gather(g_vals, a, axis=0, tiled=True)
    out = jnp.zeros((n,), jnp.float32).at[g_idx].add(g_vals)
    if average:
        out = out / _axis_size(axis_name)
    out = _maybe_sentry(out, flat, axis_name).astype(orig_dt).reshape(
        orig_shape)
    if residual is None:
        return out
    new_residual = corrected.at[idx].set(0.0)
    return out, new_residual.astype(orig_dt).reshape(orig_shape)


def codec_roundtrip(x: jax.Array, codec, size: int = 1):
    """Collective-free local encode→decode through ``codec``'s block
    math: quantize this contribution with its OWN block scales,
    dequantize, return ``(signal_power, error_power)`` as two f32
    scalars — the numerics observatory's decode-error measurement for
    device-resident gradients (docs/tensorwatch.md; the PR 8 two-scalar
    census pattern: a compiled probe syncs scalars, never buffers).

    In-jit twin of ``Compression.*.roundtrip_error`` — the SAME
    quantize formula as :func:`_quantized_axis_sum` step 2, with local
    absmax standing in for the pmax-shared scales (no wire here), so
    the measurement is the per-contribution floor of the wire's error.
    ``size`` sets the block geometry the wire of that world size would
    build (``codec.block_layout``); pinned equal to the numpy twin by
    the tensorwatch tests."""
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    if n == 0:
        return jnp.float32(0.0), jnp.float32(0.0)
    block, padded = codec.block_layout(n, size)
    if padded != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros_like(flat, shape=(padded - n,))])
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, absmax / codec.QMAX,
                      jnp.ones_like(absmax)).astype(codec.SCALE_DTYPE)
    inv = (1.0 / scale.astype(jnp.float32))[:, None]
    wire_dt = codec.wire_dtype()
    if jnp.issubdtype(wire_dt, jnp.floating):  # fp8: saturating cast
        q = (blocks * inv).astype(wire_dt)
    else:
        q = jnp.clip(jnp.round(blocks * inv),
                     -codec.QMAX, codec.QMAX).astype(wire_dt)
    deq = q.astype(jnp.float32) * scale.astype(jnp.float32)[:, None]
    err = deq - blocks
    return jnp.sum(blocks * blocks), jnp.sum(err * err)


def reduce_apply(grad: jax.Array, param: jax.Array, slots, rule,
                 count, axis_name: AxisName, average: bool = True,
                 codec=None):
    """Fused reduce+apply inside a compiled SPMD program: psum (or the
    block-quantized EQuARX wire when ``codec`` is given) of the gradient,
    then the shared :class:`ops.fused_apply.ApplyRule` leaf update —
    one traced expression XLA schedules as a single program, the SPMD
    companion of the eager engine's apply-fused flush
    (docs/tensor-fusion.md §fused apply).

    Returns ``(new_param, new_slots)``. ``count`` is the
    already-incremented step number (Adam bias correction); ``slots``
    is the rule's slot tuple for this leaf. Groundwork for the ZeRO
    item: a sharded-state variant composes this body with
    :func:`reducescatter` over the batch axis instead of the full psum
    (the ROADMAP's 2-D mesh + ZeRO-1 design)."""
    from .fused_apply import ApplyRule, rule_of

    rule = rule_of(rule) or rule
    if not isinstance(rule, ApplyRule):
        raise TypeError(f"rule must be an ApplyRule, got {rule!r}")
    _SPMD_LOWERINGS.labels(op="reduce_apply").inc()
    if codec is not None:
        red = quantized_allreduce(grad, axis_name, average=False,
                                  codec=codec)
    else:
        red = allreduce(grad, axis_name, average=False)
    denom = _axis_size(axis_name) if average else 1
    out = rule.apply_body(red, param, jnp.int32(count), tuple(slots),
                          gate=False, denom=denom)
    return out[0], tuple(out[3:])


def axis_rank(axis_name: AxisName) -> jax.Array:
    """This shard's index along the axis (device-level 'rank' inside jit)."""
    return lax.axis_index(axis_name)
