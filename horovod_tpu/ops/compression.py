"""Gradient compression algorithms.

Rebuild of ``horovod/torch/compression.py`` / ``tensorflow/compression.py``
(identical 74-line files): a ``Compressor`` has ``compress(tensor) ->
(compressed, ctx)`` and ``decompress(compressed, ctx)``, and ``Compression``
exposes ``none`` / ``fp16`` selectors. TPU-first addition: ``bf16``, the
native 16-bit format of the MXU — on TPU it is both faster and safer
(fp32-range exponent) than fp16, and XLA reduces it natively, so the
software fp16-sum shim of the reference (``half.cc:43-75``) has no analog
here.

Beyond the cast codecs, ``Compression.int8`` / ``Compression.fp8`` are
*quantized wire* codecs (EQuARX, arxiv 2506.17615): block-wise scaled
int8 (or fp8-e4m3) payloads on the collective wire, ~4x fewer bytes than
f32. Unlike the cast codecs these cannot quantize locally before a
generic collective — the per-block scales must be agreed across ranks
(a tiny ``pmax`` pre-pass) so the reduced payload dequantizes
consistently — so their ``compress``/``decompress`` hooks are identity
and the collective itself routes through the quantized data plane
(``ops.spmd.quantized_allreduce``, ``parallel.hierarchical``, the eager
``ops.xla_plane`` fused-buffer program). ``codec_name`` is the
negotiation tag the eager control plane carries so every rank picks the
same wire. See docs/compression.md for the codec table and error bound.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a tensor
    (``compression.py:20-33`` in the reference)."""

    # Negotiation tag + routing flags, uniform across every codec so the
    # ops layer can duck-type (the TF front-end mirrors these on its own
    # Compression classes without importing jax). ``quantized`` routes the
    # dense block-scaled wire; ``sparse`` routes the top-k indices+values
    # wire — both compress INSIDE the collective, so both ride the codec
    # negotiation tag rather than compress()/decompress().
    codec_name = "none"
    quantized = False
    sparse = False

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @classmethod
    def wire_cost(cls, n_elems: int, size: int,
                  in_itemsize: int = 4) -> tuple:
        """(pre, post) bytes one allreduce leg moves for an
        ``n_elems``-element payload over ``size`` ranks: ``pre`` is the
        uncompressed (input-dtype) cost, ``post`` the on-wire cost under
        this codec. THE single accounting definition the observability
        plane charges wire-byte counters from (``ops.xla_plane``,
        ``ops.spmd``) — the same geometry the benchmark auditor and the
        error-bound tests derive (``block_layout``). Identity for the
        base/none codec."""
        return n_elems * in_itemsize, n_elems * in_itemsize


class NoneCompressor(Compressor):
    """Default no-op compression (``compression.py:36-46``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    """Cast-down / cast-up compression (``compression.py:49-64``: compress to
    16 bits before the collective, restore the original dtype after)."""

    WIRE_DTYPE: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(ctx, jnp.floating) and ctx != cls.WIRE_DTYPE:
            return tensor.astype(cls.WIRE_DTYPE), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor

    @classmethod
    def wire_cost(cls, n_elems: int, size: int,
                  in_itemsize: int = 4) -> tuple:
        return (n_elems * in_itemsize,
                n_elems * jnp.dtype(cls.WIRE_DTYPE).itemsize)


class FP16Compressor(_CastCompressor):
    WIRE_DTYPE = jnp.float16
    codec_name = "fp16"


class BF16Compressor(_CastCompressor):
    WIRE_DTYPE = jnp.bfloat16
    codec_name = "bf16"


class _BlockQuantCompressor(Compressor):
    """Block-wise scaled quantized wire (EQuARX design): the flat payload
    is split into ``BLOCK``-element blocks, each carrying one shared scale
    ``s = pmax(absmax(block)) / QMAX`` so every rank quantizes with the
    SAME step and the wire integers sum exactly in a widened int32
    accumulator (no overflow up to world sizes of QMAX * size < 2^31,
    i.e. ~16M ranks at int8).

    ``compress``/``decompress`` are identity: the quantize → reduce →
    dequantize cycle lives inside the collective (see module docstring).
    Per-element error bound after one quantized allreduce:

        |quantized_mean - exact_mean| <= block_absmax * ERROR_BOUND

    where ``block_absmax`` is the across-ranks absolute max of the
    element's block (int8: one 1/2-step from quantization + one 1/2-step
    from re-quantizing the averaged sum → 1/127 of the block max).
    """

    quantized = True
    BLOCK = 512  # elements per scale; small leaves shrink it (see spmd)

    # subclasses pin the wire format
    WIRE_DTYPE: jnp.dtype
    QMAX: float
    SCALE_DTYPE: jnp.dtype
    ERROR_BOUND: float

    @classmethod
    def wire_dtype(cls):
        """The collective operand dtype; an accessor (not the bare class
        attribute) so codecs whose dtype may be missing on old stacks can
        resolve it lazily (see FP8Compressor)."""
        return cls.WIRE_DTYPE

    @classmethod
    def block_layout(cls, n_elems: int, size: int):
        """(block, padded): the scale-block geometry for an ``n_elems``
        bucket reduced over ``size`` ranks. THE single definition — the
        collective (``ops.spmd``), the error-bound checks in the tests,
        and the benchmark auditor all derive from it. Buckets small
        enough to fit one block per scatter chunk shrink the block to the
        chunk itself instead of paying up to ``size*BLOCK-1`` elements of
        padding; larger buckets pad to whole (size x BLOCK) tiles."""
        block = int(cls.BLOCK)
        if n_elems <= size * block:
            padded = -(-n_elems // size) * size
            block = max(1, padded // size)
        else:
            padded = -(-n_elems // (size * block)) * (size * block)
        return block, padded

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor

    @classmethod
    def wire_cost(cls, n_elems: int, size: int,
                  in_itemsize: int = 4) -> tuple:
        """Quantized wire: the padded payload at the wire dtype plus one
        shared scale per block (the pmax pre-pass bytes)."""
        block, padded = cls.block_layout(n_elems, size)
        return (n_elems * in_itemsize,
                padded * jnp.dtype(cls.wire_dtype()).itemsize
                + (padded // block) * jnp.dtype(cls.SCALE_DTYPE).itemsize)

    @classmethod
    def roundtrip_error(cls, flat, size: int = 1) -> tuple:
        """``(signal_power, error_power)`` of one LOCAL encode→decode
        leg through this codec's block math — quantize with this
        contribution's own block scales, dequantize, difference. THE
        single accounting definition of *measured* wire fidelity (the
        ``wire_cost`` precedent): the numerics observatory
        (``obs.tensorwatch``), the compression bench's measured-SNR
        column, and the SNR tests all derive from it;
        ``ops.spmd.codec_roundtrip`` is the in-jit twin for
        device-resident tensors (pinned equal by tests). ``size`` sets
        the block geometry (``block_layout``) so the measurement matches
        the wire the world of that size would actually build. One leg
        only — the real reduce pays a second re-quantization of the
        mean, so this is the per-contribution floor of wire error, not
        the end-to-end bound (docs/compression.md)."""
        import numpy as np

        flat = np.asarray(flat, dtype=np.float32).reshape(-1)
        n = int(flat.size)
        if n == 0:
            return 0.0, 0.0
        block, padded = cls.block_layout(n, size)
        if padded != n:
            flat = np.concatenate(
                [flat, np.zeros(padded - n, np.float32)])
        blocks = flat.reshape(-1, block)
        absmax = np.abs(blocks).max(axis=1)
        scale = np.where(absmax > 0, absmax / cls.QMAX,
                         np.ones_like(absmax)).astype(
            np.dtype(cls.SCALE_DTYPE))
        # multiply by the reciprocal, not divide: the wire itself does
        # (ops.spmd._quantized_axis_sum step 2), and the twins must
        # round identically to stay pinned equal
        inv = (1.0 / scale.astype(np.float32))[:, None]
        scale_f32 = scale.astype(np.float32)[:, None]
        scaled = blocks * inv
        wire_np = np.dtype(cls.wire_dtype())
        if np.issubdtype(wire_np, np.integer):
            q = np.clip(np.round(scaled),
                        -cls.QMAX, cls.QMAX).astype(wire_np)
        else:
            # fp8 wire: saturating cast through the ml_dtypes numpy
            # dtype (clip first — a plain numpy cast overflows to inf
            # where the XLA cast saturates)
            q = np.clip(scaled, -cls.QMAX, cls.QMAX).astype(wire_np)
        deq = q.astype(np.float32) * scale_f32
        err = (deq - blocks).astype(np.float64)
        sig = blocks.astype(np.float64)
        return float((sig * sig).sum()), float((err * err).sum())


class Int8Compressor(_BlockQuantCompressor):
    """Symmetric int8: values in [-127, 127], exact int32 summation."""

    codec_name = "int8"
    WIRE_DTYPE = jnp.int8
    QMAX = 127.0
    SCALE_DTYPE = jnp.float32
    ERROR_BOUND = 1.0 / 127.0


class FP8Compressor(_BlockQuantCompressor):
    """fp8-e4m3 wire with bf16 scales: coarser than int8 near the block
    max (3 mantissa bits → ulp(448) = 32) but wider dynamic range within
    a block. Accumulates in f32 after widening. Backend support is
    probed at trace time; unsupported backends raise at compile."""

    codec_name = "fp8"
    QMAX = 448.0
    SCALE_DTYPE = jnp.bfloat16
    # one e4m3 rounding (<= 2^-4 relative, <= QMAX/16 absolute at the
    # block max... conservatively ulp(448)/448 = 1/14) per leg, double it
    ERROR_BOUND = 1.0 / 7.0

    # resolved lazily: jnp may lack float8 types on old stacks, and a
    # class attribute would make `import horovod_tpu` itself fail there
    @classmethod
    def wire_dtype(cls):
        return jnp.float8_e4m3fn


class TopKCompressor(Compressor):
    """Top-k sparse wire with error feedback (docs/compression.md §sparse):
    each rank ships the ``k = max(1, ceil(f * n))`` largest-magnitude
    entries of its (residual-corrected) contribution as ``int32`` flat
    indices + ``float32`` values over the reference allgather shape
    (Horovod's only sparse path, ``tensorflow/__init__.py:72-83``), and
    every rank scatter-adds all ranks' pairs back into the dense result.
    The dropped ``n - k`` entries accumulate in a persistent per-rank
    residual buffer (``ops.engine``) and re-enter the next step's
    selection — the error-feedback memory that preserves convergence.

    Like the quantized codecs, ``compress``/``decompress`` are identity:
    selection needs the residual state and the decode needs every rank's
    pairs, so the whole cycle lives inside the collective and only the
    ``codec_name`` negotiation tag rides the control plane. The active
    fraction is NOT part of the tag — it is the ``HOROVOD_SPARSE_TOPK``
    knob, pinned process-wide via :meth:`set_fraction_key` (the launcher's
    uniform env export keeps it identical on every rank, the same
    contract as ``HOROVOD_CACHE_CAPACITY``)."""

    codec_name = "topk"
    sparse = True
    INDEX_DTYPE = jnp.int32
    VALUE_DTYPE = jnp.float32
    # percent keys match the tensorwatch sparse-readiness curve
    # (obs.tensorwatch.TOPK_FRACTIONS — cross-pinned by tests) so the
    # topk-mass coverage the observatory already measures IS the evidence
    # the gate certifies k against.
    FRACTIONS = {"0.1": 0.001, "1": 0.01, "10": 0.1}
    FRACTION_KEY = "1"

    @classmethod
    def set_fraction_key(cls, key) -> str:
        """Pin the active top-k fraction (the ``HOROVOD_SPARSE_TOPK``
        value). Unknown keys fail loudly — a silently rescaled k would
        change the wire on one rank only."""
        key = str(key).strip()
        if key not in cls.FRACTIONS:
            raise ValueError(
                f"bad HOROVOD_SPARSE_TOPK value {key!r}; expected one of "
                f"{', '.join(sorted(cls.FRACTIONS, key=float))} (percent "
                f"of entries kept)")
        cls.FRACTION_KEY = key
        return key

    @classmethod
    def fraction(cls, key=None) -> float:
        key = cls.FRACTION_KEY if key is None else str(key).strip()
        if key not in cls.FRACTIONS:
            raise ValueError(
                f"bad HOROVOD_SPARSE_TOPK value {key!r}; expected one of "
                f"{', '.join(sorted(cls.FRACTIONS, key=float))}")
        return cls.FRACTIONS[key]

    @classmethod
    def k_of(cls, n_elems: int, key=None) -> int:
        """Entries kept for an ``n_elems`` payload at the active (or
        given) fraction key; never 0 — an empty contribution would make
        the gathered wire shape degenerate."""
        n = int(n_elems)
        if n <= 0:
            return 0
        f = cls.fraction(key)
        return min(n, max(1, -(-int(round(n * f * 1000)) // 1000)))

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor

    @classmethod
    def wire_cost(cls, n_elems: int, size: int,
                  in_itemsize: int = 4) -> tuple:
        """Sparse wire: this rank's contribution leg is ``k`` (index,
        value) pairs — 8 bytes each — against the dense leg's
        ``n * in_itemsize`` (the same per-rank-contribution convention
        the dense codecs charge). The gathered RECEIVE side is ``size``
        times the post cost (the reference allgather shape); the
        benchmark's auditor measures that side directly."""
        k = cls.k_of(n_elems)
        return (n_elems * in_itemsize,
                k * (jnp.dtype(cls.INDEX_DTYPE).itemsize
                     + jnp.dtype(cls.VALUE_DTYPE).itemsize))

    @classmethod
    def roundtrip_error(cls, flat, size: int = 1) -> tuple:
        """``(signal_power, error_power)`` of one LOCAL top-k selection
        leg: the kept entries are exact, so the error power is exactly
        the dropped mass ``sum(x_dropped**2)`` and ``1 -
        err_power/sig_power`` is the codec's energy coverage — the same
        quantity the tensorwatch topk-mass curve reports at this key.
        ``size`` is accepted for signature uniformity with the quantized
        codecs (selection is per-contribution; the world size only scales
        the gathered wire, not the local error)."""
        import numpy as np

        flat = np.asarray(flat, dtype=np.float32).reshape(-1)
        n = int(flat.size)
        if n == 0:
            return 0.0, 0.0
        k = cls.k_of(n)
        mag = np.abs(flat)
        # partition, not sort: only the threshold membership matters
        keep = np.argpartition(mag, n - k)[n - k:]
        dropped = flat.astype(np.float64)
        dropped[keep] = 0.0
        sig = flat.astype(np.float64)
        return float((sig * sig).sum()), float((dropped * dropped).sum())


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (``compression.py:67-74``; ``int8``/``fp8`` extend the reference
    surface with the EQuARX quantized wire, ``topk`` with the sparse
    top-k + error-feedback wire)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor
    topk = TopKCompressor

    @staticmethod
    def lookup(name):
        """Codec by negotiation tag (the ``HOROVOD_COMPRESSION`` values):
        none / fp16 / bf16 / int8 / fp8 / topk."""
        codec = getattr(Compression, (name or "none").strip().lower(), None)
        if codec is None or not (isinstance(codec, type)
                                 and issubclass(codec, Compressor)):
            raise ValueError(
                f"unknown compression codec {name!r}; expected one of "
                f"none, fp16, bf16, int8, fp8, topk")
        return codec
