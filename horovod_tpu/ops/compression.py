"""Gradient compression algorithms.

Rebuild of ``horovod/torch/compression.py`` / ``tensorflow/compression.py``
(identical 74-line files): a ``Compressor`` has ``compress(tensor) ->
(compressed, ctx)`` and ``decompress(compressed, ctx)``, and ``Compression``
exposes ``none`` / ``fp16`` selectors. TPU-first addition: ``bf16``, the
native 16-bit format of the MXU — on TPU it is both faster and safer
(fp32-range exponent) than fp16, and XLA reduces it natively, so the
software fp16-sum shim of the reference (``half.cc:43-75``) has no analog
here.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a tensor
    (``compression.py:20-33`` in the reference)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default no-op compression (``compression.py:36-46``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    """Cast-down / cast-up compression (``compression.py:49-64``: compress to
    16 bits before the collective, restore the original dtype after)."""

    WIRE_DTYPE: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(ctx, jnp.floating) and ctx != cls.WIRE_DTYPE:
            return tensor.astype(cls.WIRE_DTYPE), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    WIRE_DTYPE = jnp.float16


class BF16Compressor(_CastCompressor):
    WIRE_DTYPE = jnp.bfloat16


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (``compression.py:67-74``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
