"""The sparse gradient home: top-k wire math + indexed-slices allgather.

ONE module holds the byte layout and the decode arithmetic of the sparse
wire, because two very different callers must agree bit-for-bit on both:

* the engine's host-fed fused path (``ops/engine.py``) decodes the
  gathered wire into the dense sum each step, and
* the coordinator's consensus authority (``ops/controller.py``) decodes
  the SAME gathered bytes to digest the *decoded dense* result — the
  integrity contract of docs/compression.md §sparse: consensus screens
  what training actually consumed, not the transport bytes.

If the two decodes ever drift (different scatter order, different
clipping), every healthy rank would disagree with the authority and a
single corrupt rank could no longer be named.  Hence: numpy only, no jax,
no imports from the ``ops`` package itself (the engine imports this
module while ``ops/__init__`` is still initializing).

Wire layout (per fused ALLREDUCE batch, float32 only):

    payload(rank) = int32 idx[K] ++ float32 vals[K]     (little-endian)

where ``K = Σᵢ k_of(nᵢ)`` over the batch's entries and each entry's
indices are OFFSET into the fused buffer.  Every rank's payload has the
same K (k is a function of the negotiated shapes), so the coordinator
combines by rank-ordered concatenation — the reference allgather shape
(Horovod ``tensorflow/__init__.py:72-83``) — and decode is a single
scatter-add of all ``size·K`` pairs into ``zeros(n_dense)``.

This module also carries the OTHER sparse path — the reference's
tf.IndexedSlices rebuild (:class:`IndexedSlices` /
:func:`allreduce_sparse`, formerly ``ops/sparse.py``, now a shim): both
defer summing to whoever applies the gathered pairs, so one module owns
"sparse gradients" end to end.  The module level stays numpy-only (the
bit-for-bit constraint above — the engine imports this file while
``ops/__init__`` is still initializing), so the indexed-slices half does
its jax / ops-package imports inside the functions that need them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from ..obs.registry import registry as _metrics

PAIR_BYTES = 8  # int32 index + float32 value

# Sparse-codec families (docs/metrics.md §sparse): how much of the wire
# the selection kept vs dropped, how much deferred mass the error-feedback
# residual is carrying, and what actually went on the wire.  Rendered as
# their own section by tools/metrics_summary.py.
_SPARSE_SELECTED = _metrics().counter(
    "horovod_sparse_selected_total",
    "Gradient entries selected into the top-k sparse wire")
_SPARSE_DROPPED = _metrics().counter(
    "horovod_sparse_dropped_total",
    "Gradient entries dropped by top-k selection (mass goes to residual)")
_SPARSE_RESIDUAL_NORM = _metrics().gauge(
    "horovod_sparse_residual_norm",
    "L2 norm of this rank's error-feedback residual after the last "
    "sparse batch")
_SPARSE_WIRE = _metrics().counter(
    "horovod_sparse_wire_bytes_total",
    "Bytes this rank contributed to the sparse indices+values wire",
    labels=("path",))


def account_batch(selected: int, dropped: int, wire_bytes: int,
                  residual_norm: float, path: str) -> None:
    """Charge one sparse fused batch to the ``horovod_sparse_*`` families."""
    _SPARSE_SELECTED.inc(selected)
    _SPARSE_DROPPED.inc(dropped)
    _SPARSE_WIRE.labels(path=path).inc(wire_bytes)
    _SPARSE_RESIDUAL_NORM.set(float(residual_norm))


def topk_select(flat: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Indices and values of the ``k`` largest-magnitude entries.

    Deterministic: descending |x| with ascending-index tie break, so a
    rank's selection is a pure function of its bytes (replayable by the
    flight recorder and stable across numpy versions — ``argpartition``'s
    boundary tie-breaking is implementation-defined)."""
    n = flat.size
    k = min(int(k), n)
    if k <= 0:
        return (np.empty((0,), np.int32), np.empty((0,), np.float32))
    mag = np.abs(flat)
    order = np.lexsort((np.arange(n), -mag))[:k]
    idx = np.asarray(order, dtype=np.int32)
    return idx, np.ascontiguousarray(flat[order], dtype=np.float32)


def pack_pairs(idx: np.ndarray, vals: np.ndarray) -> bytes:
    """One rank's wire payload: the int32 index block then the float32
    value block (little-endian, matching the dense wire's numpy bytes)."""
    return (np.ascontiguousarray(idx, dtype="<i4").tobytes()
            + np.ascontiguousarray(vals, dtype="<f4").tobytes())


def unpack_wire(combined: bytes,
                size: int) -> Tuple[np.ndarray, np.ndarray]:
    """Split the rank-ordered concatenation of ``size`` equal payloads
    back into (indices, values), both ``size·K`` long, rank-major — the
    exact order ``scatter_sum`` accumulates in."""
    if size <= 0 or len(combined) % (size * PAIR_BYTES):
        raise ValueError(
            f"sparse wire of {len(combined)} bytes does not split into "
            f"{size} equal int32+float32 payloads")
    seg = len(combined) // size
    k = seg // PAIR_BYTES
    idx_parts, val_parts = [], []
    for r in range(size):
        block = combined[r * seg:(r + 1) * seg]
        idx_parts.append(np.frombuffer(block, dtype="<i4", count=k))
        val_parts.append(
            np.frombuffer(block, dtype="<f4", offset=4 * k, count=k))
    return (np.concatenate(idx_parts) if size > 1 else idx_parts[0],
            np.concatenate(val_parts) if size > 1 else val_parts[0])


def scatter_sum(idx: np.ndarray, vals: np.ndarray,
                n_dense: int) -> np.ndarray:
    """Dense float32 sum of the gathered pairs.

    Indices are CLIPPED into range, not validated: a corrupt index (the
    chaos plane's flipbits fault, or a real wire flip) must land mass on
    the wrong row — a *divergence* every rank and the authority decode
    identically, so consensus can vote and name the culprit — rather than
    raise asymmetrically and kill one side of the exchange.

    ``np.add.at`` accumulates pairs strictly in array order, so every
    caller of this function sees the identical float addition order —
    the bit-identity the consensus digest depends on."""
    out = np.zeros((n_dense,), dtype=np.float32)
    if idx.size:
        np.add.at(out, np.clip(idx, 0, n_dense - 1),
                  vals.astype(np.float32, copy=False))
    return out


def decode_sum(combined: bytes, n_dense: int, size: int) -> np.ndarray:
    """Gathered wire bytes → dense float32 SUM over all ranks.  The ONE
    decode definition shared by the engine (training result) and the
    consensus authority (digest of the decoded dense bytes)."""
    idx, vals = unpack_wire(combined, size)
    return scatter_sum(idx, vals, n_dense)


def select_with_feedback(flat: np.ndarray, residual, k: int,
                         error_feedback: bool = True):
    """Top-k select of ``flat`` (+ carried residual) for one tensor.

    Returns ``(idx, vals, new_residual)``: the selected pairs, and the
    dropped mass to carry into the next step (``None`` when error
    feedback is off — dropped mass is simply lost, the ablation arm of
    docs/compression.md §sparse)."""
    corrected = np.asarray(flat, dtype=np.float32)
    if error_feedback and residual is not None:
        corrected = corrected + np.asarray(residual, dtype=np.float32)
    idx, vals = topk_select(corrected, k)
    if not error_feedback:
        return idx, vals, None
    new_residual = np.array(corrected, dtype=np.float32, copy=True)
    new_residual[idx] = 0.0
    return idx, vals, new_residual


# -- indexed-slices allgather path (formerly ops/sparse.py) -------------------

@dataclass
class IndexedSlices:
    """A sparse tensor: ``values[i]`` belongs to row ``indices[i]`` of a
    dense tensor of shape ``dense_shape`` (mirror of tf.IndexedSlices)."""

    indices: Any   # int array [n]
    values: Any    # array [n, ...]
    dense_shape: Tuple[int, ...]

    def to_dense(self):
        import jax.numpy as jnp

        out = jnp.zeros(self.dense_shape,
                        dtype=jnp.asarray(self.values).dtype)
        return out.at[jnp.asarray(self.indices)].add(
            jnp.asarray(self.values))


def allreduce_sparse(slices: IndexedSlices, average: bool = True,
                     name: Optional[str] = None,
                     axis_name: Any = None) -> IndexedSlices:
    """Allreduce an IndexedSlices by gathering every rank's (indices,
    values); duplicate rows sum when densified. ``average`` scales values by
    1/size, matching the dense allreduce contract
    (``tensorflow/__init__.py:76-83``)."""
    name = name or "allreduce_sparse"
    if axis_name is not None:
        import jax.numpy as jnp

        from . import spmd

        gathered_values = spmd.allgather(slices.values, axis_name)
        gathered_indices = spmd.allgather(
            jnp.asarray(slices.indices).reshape(-1, 1), axis_name).reshape(-1)
        if average:
            from jax import lax

            # Divide by the product of ALL named axis sizes: a tuple
            # axis_name gathers size(a)·size(b)·… contributions, so
            # scaling by only the first axis under-divides multi-axis
            # meshes (pinned by tests/test_zzsparse.py).
            denom = 1
            for ax in ((axis_name,) if isinstance(axis_name, str)
                       else tuple(axis_name)):
                denom = denom * lax.axis_size(ax)
            gathered_values = gathered_values / denom
        return IndexedSlices(gathered_indices, gathered_values,
                             slices.dense_shape)

    from .. import basics
    from . import allgather_async, synchronize

    values_handle = allgather_async(slices.values, name=f"{name}.values")
    indices_handle = allgather_async(
        np.asarray(slices.indices).reshape(-1, 1), name=f"{name}.indices")
    values = synchronize(values_handle)
    indices = np.asarray(synchronize(indices_handle)).reshape(-1)
    if average:
        values = values / basics.size()
    return IndexedSlices(indices, values, slices.dense_shape)
