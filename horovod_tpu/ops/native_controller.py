"""Python half of the native (C++) controller service.

The service itself lives in ``cc/controller_service.cc`` — the rank-0 hot
path (sockets, HMAC framing, cycle rendezvous, negotiation, host-plane
combine, failure detection) in C++, the reference's architectural choice
for its coordinator (``operations.cc`` is C++ precisely because negotiation
runs every ~5 ms at up to 512 ranks). This module provides:

* the little-endian binary body codec (pickle is neither parseable nor safe
  to execute from C++);
* :class:`NativeControllerClient` — same interface as
  ``controller.ControllerClient`` (hello at connect, cycle, payload, clean
  or attributed close), speaking the binary wire over the same
  HMAC + u64-length framing as ``runner.network.Wire``;
* :class:`NativeControllerService` — ctypes wrapper owning the C++ server.

The engine selects native vs Python per ``HOROVOD_NATIVE_CONTROLLER``
(auto/1/0); the decision must be identical on every rank, so it derives
only from config + library availability, never per-rank state.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

from ..core.logging import LOG
from ..core.status import SHUT_DOWN_ERROR
from ..obs import flightrec as _flightrec
from ..runner.network import WireError
# observability counters shared with the Python client (controller.py
# never imports this module, so the import is cycle-free); bound at
# module level because cycle() is the ~5 ms hot path
from .controller import (
    _NEG_CYCLE_SECONDS,
    _NEG_CYCLES,
    _NEG_RX,
    _NEG_TX,
)
from .messages import (
    DataType,
    RequestList,
    Response,
    ResponseList,
    ResponseType,
)

_HELLO, _BYE, _CYCLE, _PAYLOAD, _WATCH = 1, 2, 3, 4, 5


# -- body codec ---------------------------------------------------------------

def encode_hello(rank: int, world_id: str = "") -> bytes:
    wid = world_id.encode("utf-8")
    return struct.pack("<BiH", _HELLO, rank, len(wid)) + wid


def encode_watch(world_id: str = "") -> bytes:
    wid = world_id.encode("utf-8")
    return struct.pack("<BH", _WATCH, len(wid)) + wid


def encode_bye(rank: int) -> bytes:
    return struct.pack("<Bi", _BYE, rank)


def encode_cycle(rank: int, request_list: RequestList) -> bytes:
    parts = [struct.pack("<BiBI", _CYCLE, rank,
                         1 if request_list.shutdown else 0,
                         len(request_list.requests))]
    for req in request_list.requests:
        name = req.tensor_name.encode("utf-8")
        parts.append(struct.pack(
            "<BBiB", int(req.request_type), int(req.tensor_type),
            req.root_rank, len(req.tensor_shape)))
        for dim in req.tensor_shape:
            parts.append(struct.pack("<q", dim))
        parts.append(struct.pack("<H", len(name)))
        parts.append(name)
    return b"".join(parts)


def encode_payload(rank: int, cycle_no: int, idx: int, data: bytes) -> bytes:
    return struct.pack("<BiQIQ", _PAYLOAD, rank, cycle_no, idx,
                       len(data)) + data


class _BodyReader:
    def __init__(self, body: bytes) -> None:
        self._body = body
        self._off = 0

    def unpack(self, fmt: str):
        s = struct.Struct(fmt)
        vals = s.unpack_from(self._body, self._off)
        self._off += s.size
        return vals if len(vals) > 1 else vals[0]

    def take(self, n: int) -> bytes:
        out = self._body[self._off:self._off + n]
        if len(out) != n:
            raise WireError("truncated native controller response")
        self._off += n
        return out


def _decode_status(body: bytes) -> _BodyReader:
    if body[:1] == b"\x80":
        # A pickle protocol marker: the coordinator is running the PYTHON
        # controller service while this rank speaks the native binary
        # protocol — the HOROVOD_NATIVE_CONTROLLER decision diverged
        # across ranks (native core built here but not on the coordinator?).
        raise WireError(
            "protocol mismatch: the coordinator runs the Python controller "
            "service but this rank connected with the native client. "
            "HOROVOD_NATIVE_CONTROLLER must resolve identically on every "
            "rank; set HOROVOD_NATIVE_CONTROLLER=0 everywhere to force the "
            "Python service.")
    r = _BodyReader(body)
    status = r.unpack("<B")
    if status != 0:
        msg_len = r.unpack("<I")
        msg = r.take(msg_len).decode("utf-8", "replace")
        # parity with the Python service's RemoteError path
        raise WireError(f"service-side failure: {msg}")
    return r


def decode_cycle_response(body: bytes,
                          log_stalls: bool) -> ResponseList:
    r = _decode_status(body)
    shutdown = bool(r.unpack("<B"))
    has_tuned, tuned_ms = r.unpack("<Bd")
    nresp = r.unpack("<I")
    responses = []
    for _ in range(nresp):
        rtype, dtype, payload_bytes = r.unpack("<BBQ")
        nnames = r.unpack("<H")
        names = [r.take(r.unpack("<H")).decode("utf-8")
                 for _ in range(nnames)]
        err = r.take(r.unpack("<I")).decode("utf-8")
        nsizes = r.unpack("<I")
        sizes = [r.unpack("<q") for _ in range(nsizes)]
        responses.append(Response(
            response_type=ResponseType(rtype), tensor_names=names,
            error_message=err, tensor_sizes=sizes,
            tensor_dtype=DataType(dtype), payload_bytes=payload_bytes))
    nstalls = r.unpack("<I")
    stalls = []
    for _ in range(nstalls):
        warning = r.take(r.unpack("<I")).decode("utf-8", "replace")
        stalls.append(warning)
        if log_stalls:
            LOG.warning("%s", warning)
    # cache_generation stays None: the C++ service's binary wire predates
    # the response-cache field, so the steady-state bypass
    # (docs/response-cache.md) is disabled against it — the engine sees
    # None and never plans a cache-bit cycle (the deterministic
    # full-precision fallback pattern this wire already applies to codecs).
    return ResponseList(responses=responses, shutdown=shutdown,
                        tuned_cycle_ms=tuned_ms if has_tuned else None,
                        stall_warnings=stalls, cache_generation=None)


def decode_payload_response(body: bytes) -> bytes:
    r = _decode_status(body)
    data_len = r.unpack("<Q")
    return r.take(data_len)


# -- client -------------------------------------------------------------------

class NativeControllerClient:
    """Drop-in for ``ControllerClient`` against the C++ service.

    Connection management and framing come from ``BasicClient`` (candidate
    probing, retries, TCP_NODELAY, HMAC + u64-length frames via
    ``request_raw``); only the body codec differs from the pickle wire."""

    # Deterministic degrade (docs/tracing.md): the C++ service's fixed
    # binary wire predates the clock_probe RPC — same pattern as
    # metrics_pull and the cache-bit field — so clock alignment never
    # runs against it; per-rank traces keep their local timebase and
    # trace_merge says so instead of pretending correction happened.
    clock_sync_supported = False
    # Same pattern for the flight recorder's incident push
    # (docs/blackbox.md): the binary wire predates the "flightrec" RPC,
    # so an abort dumps a RANK-LOCAL blackbox file (warned once) instead
    # of the coordinator's merged cross-rank incident.
    flightrec_supported = False

    def __init__(self, addr, secret: Optional[bytes] = None,
                 timeout_s: Optional[float] = None,
                 connect_attempts: int = 100,
                 rank: Optional[int] = None,
                 log_stalls: bool = False, world_id: str = "",
                 stall_shutdown_s: float = 0.0,
                 stall_warning_s: float = 60.0) -> None:
        from ..runner.network import BasicClient
        from .controller import StallEscalation

        self._addr = addr
        self._secret = secret
        self._rank = rank
        self._world_id = world_id
        self._log_stalls = log_stalls
        self._cycle_no = 0
        self._last_cycle = 0
        # The C++ service's cycle wire carries the coordinator's stall
        # warnings to every rank; escalation runs CLIENT-side (the server
        # predates the knob) — identical warning stream on every rank, so
        # every client reaches the same abort verdict.
        self._escalation = StallEscalation(
            stall_shutdown_s, warning_interval_s=stall_warning_s)
        from ..chaos import injector_from_env

        self._chaos = injector_from_env(rank)
        if rank is None:
            self._client = BasicClient(addr, secret=secret,
                                       attempts=connect_attempts,
                                       timeout_s=timeout_s,
                                       chaos=self._chaos)
        else:
            # connect+hello retried as a unit against a dying previous
            # service on the same port (see connect_with_hello)
            from .controller import connect_with_hello

            self._client = connect_with_hello(
                addr, secret, timeout_s, connect_attempts,
                hello=lambda c: _decode_status(
                    c.request_raw(encode_hello(rank, world_id))),
                chaos=self._chaos, on_reconnect=self._reconnect_hello)

    @property
    def last_cycle(self) -> int:
        """Ordinal of the most recently completed negotiation cycle (the
        engine's cross-rank span stamp — same contract as
        ``ControllerClient.last_cycle``)."""
        return self._last_cycle

    def _reconnect_hello(self, client) -> None:
        """Re-identify after the client reconnects off a latched-broken
        connection. The binary wire has no request dedup, so faults that
        strike mid-request are NOT transparently resent (they surface and
        escalate); the hook covers the connect-phase heal — a refused or
        reset dial retried under backoff — and keeps a post-timeout
        reconnect from reading the dead stream's stale response. Armed
        before the initial hello (connect_with_hello) for parity with
        the Python wire, though ``request_raw`` never heals in-flight."""
        _decode_status(
            client.bare_request_raw(encode_hello(self._rank, self._world_id)))

    def _arm_reconnect_hello(self) -> None:
        self._client.on_reconnect = self._reconnect_hello

    def cycle(self, rank: int, request_list: RequestList) -> ResponseList:
        if self._rank is None:
            self._rank = rank
            self._arm_reconnect_hello()
        # same observability families as the Python client (the binary
        # wire negotiates identically; only the body codec differs)
        wire = self._client._wire
        tx0, rx0 = wire.tx_bytes, wire.rx_bytes
        # flight recorder (docs/blackbox.md): same cycle-ordinal stamps
        # as the Python client — rank-local dumps still align streams
        _flightrec.record(_flightrec.EV_NEGOTIATE, self._cycle_no)
        t0 = time.monotonic()
        out = decode_cycle_response(
            self._client.request_raw(encode_cycle(rank, request_list)),
            log_stalls=self._log_stalls)
        _NEG_CYCLE_SECONDS.observe(time.monotonic() - t0)
        _NEG_CYCLES.inc()
        _flightrec.record(_flightrec.EV_RESPONSE, self._cycle_no)
        _NEG_TX.inc(wire.tx_bytes - tx0)
        _NEG_RX.inc(wire.rx_bytes - rx0)
        escalation = self._escalation.check(out.stall_warnings)
        if escalation is not None:
            # Abort-instead-of-hang (HOROVOD_STALL_SHUTDOWN_TIME_S): fail
            # this engine's loop with the structured reason; the engine
            # flushes every outstanding handle with it (raising
            # RanksAbortedError from wait/synchronize) and its
            # non-detached close tells the C++ coordinator to abort the
            # remaining world.
            _names, _missing, reason = escalation
            raise RuntimeError(reason)
        self._last_cycle = self._cycle_no
        self._cycle_no += 1
        return out

    def payload(self, rank: int, response_idx: int, data: bytes,
                cycle_no=None) -> bytes:
        """Interface parity with ``ControllerClient.payload``; the native
        wire never pipelines flushes (the engine degrades
        HOROVOD_FUSION_SUBBUFFERS to 1 there), so the most recently
        completed cycle is always the right default."""
        return decode_payload_response(self._client.request_raw(
            encode_payload(
                rank, self._last_cycle if cycle_no is None else cycle_no,
                response_idx, data)))

    def watch(self, on_abort) -> None:
        """Failure-push channel (same contract as
        ``ControllerClient.watch``): one deferred-response kWatch request;
        the service answers only on abort (error frame carrying the
        reason) or stop."""
        from .controller import spawn_watch_thread

        def _request_reason(client) -> Optional[str]:
            try:
                _decode_status(client.request_raw(
                    encode_watch(self._world_id)))
                return None  # clean stop
            except WireError as exc:
                # Only a decoded service ERROR FRAME carries the abort
                # reason; any other WireError (EOF mid-message, HMAC) is a
                # transport loss — re-raise so the shared watch loop
                # reconnects instead of falsely aborting a healthy world.
                from ..core.status import (
                    CONTROLLER_RESTARTING,
                    WORLD_MISMATCH,
                )

                reason = str(exc)
                prefix = "service-side failure: "
                if reason.startswith(prefix):
                    reason = reason[len(prefix):]
                    # the native service answers parked watchers with this
                    # exact text on a clean Stop(); not an abort
                    if reason == "controller stopping":
                        return None
                    if CONTROLLER_RESTARTING in reason or \
                            WORLD_MISMATCH in reason:
                        # succession sentinels are NOT this world's abort
                        # reason: re-raise so the shared watch loop applies
                        # its clean-end / replaced-world semantics
                        raise
                    return reason
                raise

        spawn_watch_thread(self._addr, self._secret, _request_reason,
                           on_abort)

    def close(self, detach: bool = True) -> None:
        if detach and self._rank is not None:
            try:
                # farewell, not request_raw(): a bye must never trigger a
                # reconnect+re-hello against a possibly dying controller
                self._client.farewell_raw(encode_bye(self._rank))
            except Exception:  # noqa: BLE001 - controller may be gone
                pass
        self._client.close()


# -- service ------------------------------------------------------------------

class NativeControllerService:
    """Owns the C++ controller server (ctypes). With an ``autotuner``, a
    background thread drains the server's per-cycle (bytes, active µs)
    observations into the GP optimizer and pushes retuned knobs back —
    the fusion threshold to the negotiator, the cycle time piggybacked to
    every rank on the next cycle response."""

    def __init__(self, size: int, cfg, secret: Optional[bytes] = None,
                 port: int = 0, bind_host: str = "127.0.0.1",
                 autotuner=None, world_id: str = "",
                 collect_stats: bool = False) -> None:
        import ctypes

        from .. import cc
        from ..runner.network import default_secret

        lib = cc._load()
        if lib is None:
            raise RuntimeError(
                f"native controller unavailable: {cc.load_error()}")
        secret = secret if secret is not None else default_secret()
        err = ctypes.create_string_buffer(256)
        self._lib = lib
        # collect_stats without an autotuner: the caller (controller_bench)
        # drains the per-cycle (bytes, active µs) observations itself for a
        # direct server-side cycle-time measurement.
        self._handle = lib.htpu_controller_start(
            size, bind_host.encode(), port, secret, len(secret),
            cfg.fusion_threshold_bytes, cfg.stall_warning_time_s,
            1 if cfg.stall_check_disable else 0,
            SHUT_DOWN_ERROR.encode("utf-8"),
            1 if (autotuner is not None or collect_stats) else 0,
            world_id.encode("utf-8"), err, len(err))
        if not self._handle:
            raise RuntimeError(
                f"native controller failed to start: {err.value.decode()}")
        self.port = lib.htpu_controller_port(self._handle)
        self._tuner_stop = None
        if autotuner is not None:
            import threading

            self._tuner_stop = threading.Event()
            self._tuner_thread = threading.Thread(
                target=self._tuner_loop, args=(autotuner,),
                name="horovod-native-autotune", daemon=True)
            self._tuner_thread.start()

    def _tuner_loop(self, autotuner) -> None:
        import ctypes

        cap = 256
        bytes_buf = (ctypes.c_double * cap)()
        us_buf = (ctypes.c_double * cap)()
        while True:
            # check AFTER one more drain when stopping: observations queued
            # in the C++ stats buffer between the last tick and shutdown()
            # would otherwise be dropped (the Python service scores every
            # completed cycle; the native path must too)
            stopping = self._tuner_stop.wait(0.02)
            handle = self._handle
            if not handle:
                return
            try:
                while True:
                    n = self._lib.htpu_controller_drain_stats(
                        handle, bytes_buf, us_buf, cap)
                    for i in range(n):
                        tuned = autotuner.observe(bytes_buf[i], us_buf[i])
                        if tuned is not None:
                            # the native wire only carries the classic
                            # pair; extended knobs (cache/codec/interval)
                            # are Python-controller-only (docs/autotune.md)
                            self._lib.htpu_controller_set_tuning(
                                handle,
                                int(tuned.config["fusion_threshold_bytes"]),
                                float(tuned.config["cycle_time_ms"]))
                    # the C++ buffer holds up to 4096 samples; one
                    # cap-sized batch per tick keeps the steady state
                    # cheap, but the final pass must drain to empty
                    if n < cap or not stopping:
                        break
            except Exception as exc:  # noqa: BLE001 - keep tuning alive
                # Match the Python service's failure loudness: a tuner
                # error (log disk full, GP failure) must not silently
                # freeze the knobs without a trace.
                LOG.error("native autotune observation failed: %s", exc)
            if stopping:
                return

    def drain_stats(self, cap: int = 4096):
        """Drain the server's per-cycle (payload bytes, active µs) samples.

        Active µs is measured INSIDE the epoll loop — first rank's cycle
        request arriving to the response broadcast being queued — so it is
        a direct server-side cycle time, with no client/harness overhead in
        it. Only populated when constructed with ``collect_stats=True`` (or
        an autotuner, which then consumes the same buffer — don't mix)."""
        import ctypes

        if not self._handle:
            return []
        bytes_buf = (ctypes.c_double * cap)()
        us_buf = (ctypes.c_double * cap)()
        out = []
        while True:
            n = self._lib.htpu_controller_drain_stats(
                self._handle, bytes_buf, us_buf, cap)
            out.extend((bytes_buf[i], us_buf[i]) for i in range(n))
            if n < cap:
                return out

    def wait_world_shutdown(self, timeout_s: float) -> bool:
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._lib.htpu_controller_world_shutdown(self._handle):
                return True
            time.sleep(0.05)
        return bool(self._lib.htpu_controller_world_shutdown(self._handle))

    def shutdown(self) -> None:
        if self._tuner_stop is not None:
            self._tuner_stop.set()
            self._tuner_thread.join(timeout=5.0)
            if self._tuner_thread.is_alive():
                # A wedged tuner thread (hung log disk?) still holds the
                # raw handle; freeing it now would be a use-after-free.
                # Leak the server instead — teardown-only, bounded.
                LOG.warning("native autotune thread did not stop; leaking "
                            "the controller handle to avoid use-after-free")
                self._handle = None
                return
        handle, self._handle = self._handle, None
        if handle:
            self._lib.htpu_controller_stop(handle)

    def __del__(self) -> None:
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def native_controller_enabled(cfg) -> bool:
    """One decision per rank from config + local library availability.

    The decision MUST resolve identically on every rank — library
    availability is per-host, so a heterogeneous deployment (native core
    builds on some hosts only) must pin HOROVOD_NATIVE_CONTROLLER=0/1
    explicitly. A divergence fails loudly at the first request with a
    protocol-mismatch diagnostic on both sides, never a silent hang.
    """
    import os

    from .. import cc
    from ..core.config import HOROVOD_NATIVE_CONTROLLER

    del cfg  # knob + library only: autotune runs on both services
    knob = os.environ.get(HOROVOD_NATIVE_CONTROLLER, "auto").lower()
    if knob in ("0", "false", "off"):
        return False
    if not cc.available():
        if knob in ("1", "true", "on"):
            raise RuntimeError(
                f"HOROVOD_NATIVE_CONTROLLER=1 but the native core did not "
                f"load: {cc.load_error()}")
        return False
    return True
