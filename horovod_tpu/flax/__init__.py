"""Flax front-end — the Keras front-end of the TPU rebuild.

The reference ships a shared Keras implementation (`horovod/_keras/
__init__.py`: ``create_distributed_optimizer`` :20-70 dynamically subclasses
the user's optimizer so ``get_gradients`` allreduces, ``load_model`` :93-109
deserializes a model with every known optimizer class wrapped) plus thin
``horovod.keras`` / ``horovod.tensorflow.keras`` shims that bind it to a
backend and re-export the callbacks (``keras/__init__.py:115-148``).

Flax is the Keras of the JAX world, and its unit of "model + optimizer +
progress" is :class:`flax.training.train_state.TrainState`. So the TPU-native
front-end is TrainState-shaped:

* :func:`create_distributed_optimizer` — wrap any optax transformation so
  updates come from world-averaged gradients (the ``get_gradients`` override
  becomes a ``GradientTransformation`` wrapper; same knob surface).
* :class:`DistributedTrainState` — ``TrainState.create`` with the wrap
  applied, so ``state.apply_gradients(grads=...)`` injects averaging
  transparently, exactly how a wrapped Keras optimizer hides it inside
  ``model.fit``.
* :func:`broadcast_train_state` — rank-0 consistency push for the whole
  state (params, opt state, step), the ``BroadcastGlobalVariablesCallback``
  contract applied to a TrainState.
* :func:`save_model` / :func:`load_model` — rank-0 checkpoint + restore with
  the optimizer wrap intact (carried by the template) and a post-restore
  broadcast, the ``hvd.load_model`` round-trip of
  ``test/test_keras.py:62-246``.

Callbacks are framework-neutral in this build (``horovod_tpu.callbacks``)
and re-exported here, playing the role of ``keras/callbacks.py``.
"""

from __future__ import annotations

from typing import Any, Optional

import optax
from flax.training import train_state as _train_state

from .. import checkpoint as _checkpoint
from ..callbacks import (  # noqa: F401  (re-exports, keras/callbacks.py role)
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from ..ops.compression import Compression
from ..optimizers import DistributedOptimizer
from ..state_bcast import broadcast_parameters

__all__ = [
    "create_distributed_optimizer",
    "DistributedTrainState",
    "broadcast_train_state",
    "save_model",
    "load_model",
    "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback",
    "LearningRateScheduleCallback",
    "LearningRateWarmupCallback",
    "Callback",
    "CallbackList",
]


def create_distributed_optimizer(
        optimizer: optax.GradientTransformation,
        *,
        axis_name=None,
        compression=Compression.none,
        average: bool = True,
        backward_passes_per_step: int = 1,
        hierarchical: Optional[bool] = None,
) -> optax.GradientTransformation:
    """Keras-parity name for :func:`horovod_tpu.DistributedOptimizer`.

    Reference ``horovod/_keras/__init__.py:20-70`` builds a dynamic subclass
    overriding ``get_gradients``; in optax the seam is the gradient
    transformation itself, so the wrap is a transformation that averages
    before delegating to the inner optimizer.
    """
    return DistributedOptimizer(
        optimizer, axis_name=axis_name, compression=compression,
        average=average, backward_passes_per_step=backward_passes_per_step,
        hierarchical=hierarchical)


class DistributedTrainState(_train_state.TrainState):
    """A ``TrainState`` whose optimizer averages gradients across the world.

    ``create`` wraps ``tx`` with :func:`create_distributed_optimizer` before
    initializing, so every subsequent ``apply_gradients`` call — eager or
    inside a pjit/shard_map step (pass ``axis_name``) — runs the reference's
    DistributedOptimizer semantics without the training loop knowing.
    """

    @classmethod
    def create(cls, *, apply_fn, params, tx,
               axis_name=None,
               compression=Compression.none,
               average: bool = True,
               backward_passes_per_step: int = 1,
               hierarchical: Optional[bool] = None,
               **kwargs):
        tx = create_distributed_optimizer(
            tx, axis_name=axis_name, compression=compression,
            average=average,
            backward_passes_per_step=backward_passes_per_step,
            hierarchical=hierarchical)
        return super().create(apply_fn=apply_fn, params=params, tx=tx,
                              **kwargs)


def broadcast_train_state(state: Any, root_rank: int = 0,
                          name_prefix: str = "train_state") -> Any:
    """Push rank ``root_rank``'s full training state (params, optimizer
    state, step counter) to every rank — ``BroadcastGlobalVariablesCallback``
    (``_keras/callbacks.py:20-30``) applied to a TrainState. ``apply_fn`` and
    ``tx`` are static pytree fields and pass through untouched."""
    return broadcast_parameters(state, root_rank=root_rank,
                                name_prefix=name_prefix)


def save_model(path: str, state: Any) -> None:
    """Checkpoint the TrainState's array leaves from rank 0 only (the
    reference's rank-0 checkpoint convention, SURVEY §5.4)."""
    _checkpoint.save(path, state)


def load_model(path: str, template: Any, root_rank: int = 0) -> Any:
    """Restore a TrainState saved by :func:`save_model`.

    ``template`` supplies the static structure — ``apply_fn`` and the
    (already-wrapped) ``tx`` — which is how the Keras ``load_model``
    guarantee "the deserialized optimizer is still distributed"
    (``_keras/__init__.py:93-109``) carries over: the optimizer wrap never
    left the template. The restored state is broadcast from ``root_rank`` so
    all ranks resume identical (``keras/__init__.py:115-148`` +
    post-load broadcast convention)."""
    return _checkpoint.restore(path, template=template, root_rank=root_rank)
