"""Flax front-end — the Keras front-end of the TPU rebuild.

The reference ships a shared Keras implementation (`horovod/_keras/
__init__.py`: ``create_distributed_optimizer`` :20-70 dynamically subclasses
the user's optimizer so ``get_gradients`` allreduces, ``load_model`` :93-109
deserializes a model with every known optimizer class wrapped) plus thin
``horovod.keras`` / ``horovod.tensorflow.keras`` shims that bind it to a
backend and re-export the callbacks (``keras/__init__.py:115-148``).

Here the shared implementation is ``horovod_tpu._frontend`` and this shim
binds it to flax, whose unit of "model + optimizer + progress" is
:class:`flax.training.train_state.TrainState`:

* :class:`DistributedTrainState` — ``TrainState.create`` with the optimizer
  wrap applied, so ``state.apply_gradients(grads=...)`` injects averaging
  transparently, exactly how a wrapped Keras optimizer hides it inside
  ``model.fit``.
* :func:`broadcast_train_state` — rank-0 consistency push for the whole
  state (params, opt state, step), the ``BroadcastGlobalVariablesCallback``
  contract applied to a TrainState.
* :func:`create_distributed_optimizer` / :func:`save_model` /
  :func:`load_model` / the callbacks — re-exported from the shared impl.
"""

from __future__ import annotations

from typing import Any, Optional

from flax.training import train_state as _train_state

from .._frontend import (  # noqa: F401  (shared impl, horovod/_keras role)
    CALLBACK_EXPORTS,
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    Compression,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    create_distributed_optimizer,
    load_model,
    save_model,
    wrap_unless_distributed,
)
from ..basics import (  # noqa: F401  (re-exported like horovod.keras's
    # init/rank/... surface, keras/__init__.py there)
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from ..state_bcast import broadcast_parameters

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "is_initialized", "mpi_threads_supported",
    "create_distributed_optimizer",
    "DistributedTrainState",
    "broadcast_train_state",
    "save_model",
    "load_model",
] + CALLBACK_EXPORTS


class DistributedTrainState(_train_state.TrainState):
    """A ``TrainState`` whose optimizer averages gradients across the world.

    ``create`` wraps ``tx`` with the shared ``create_distributed_optimizer``
    before initializing (skipped if ``tx`` is already wrapped — pre-wrapped
    optimizers keep their own knobs), so every subsequent
    ``apply_gradients`` call — eager or inside a pjit/shard_map step (pass
    ``axis_name``) — runs the reference's DistributedOptimizer semantics
    without the training loop knowing.
    """

    @classmethod
    def create(cls, *, apply_fn, params, tx,
               axis_name=None,
               compression=None,  # None: follow HOROVOD_COMPRESSION
               average: bool = True,
               backward_passes_per_step: int = 1,
               hierarchical: Optional[bool] = None,
               **kwargs):
        tx = wrap_unless_distributed(
            tx, axis_name=axis_name, compression=compression,
            average=average,
            backward_passes_per_step=backward_passes_per_step,
            hierarchical=hierarchical)
        return super().create(apply_fn=apply_fn, params=params, tx=tx,
                              **kwargs)


def broadcast_train_state(state: Any, root_rank: int = 0,
                          name_prefix: str = "train_state") -> Any:
    """Push rank ``root_rank``'s full training state (params, optimizer
    state, step counter) to every rank — ``BroadcastGlobalVariablesCallback``
    (``_keras/callbacks.py:20-30``) applied to a TrainState. ``apply_fn`` and
    ``tx`` are static pytree fields and pass through untouched."""
    return broadcast_parameters(state, root_rank=root_rank,
                                name_prefix=name_prefix)
