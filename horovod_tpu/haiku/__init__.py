"""Haiku front-end — the second backend shim over the shared impl.

The reference binds one shared Keras implementation to two backends via
thin shims (``horovod/keras/__init__.py`` for standalone Keras,
``horovod/tensorflow/keras/__init__.py`` for tf.keras, both delegating to
``horovod/_keras``). This module plays the same role for dm-haiku over
``horovod_tpu._frontend``: haiku has no TrainState, so training state is
the explicit ``(params, net_state, opt_state)`` triple produced by
``hk.transform[_with_state]`` + optax — this shim wraps that triple with
the shared machinery (optimizer wrap, rank-0 broadcast, checkpoint
round-trip, callbacks).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import optax

from .._frontend import (  # noqa: F401  (shared impl, horovod/_keras role)
    CALLBACK_EXPORTS,
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    Compression,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    create_distributed_optimizer,
    load_model,
    save_model,
)
from ..state_bcast import broadcast_parameters

from ..basics import (  # noqa: F401  (re-exported like horovod.keras's
    # init/rank/... surface, keras/__init__.py there)
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "cross_rank", "cross_size", "is_initialized", "mpi_threads_supported",
    "create_distributed_optimizer",
    "TrainingState",
    "broadcast_training_state",
    "save_model",
    "load_model",
] + CALLBACK_EXPORTS


class TrainingState(NamedTuple):
    """The (params, net_state, opt_state) triple of idiomatic haiku training
    — ``net_state`` is the ``hk.transform_with_state`` mutable state (e.g.
    BatchNorm statistics), ``None`` for stateless ``hk.transform``."""

    params: Any
    net_state: Any
    opt_state: Any

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation,
               net_state: Any = None) -> "TrainingState":
        return cls(params, net_state, tx.init(params))


def broadcast_training_state(state: TrainingState,
                             root_rank: int = 0) -> TrainingState:
    """Rank-0 consistency push for the whole triple
    (``BroadcastGlobalVariablesCallback`` contract)."""
    return broadcast_parameters(state, root_rank=root_rank,
                                name_prefix="hk_training_state")
