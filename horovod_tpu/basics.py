"""Process-level basics: init / shutdown / rank / size / local ranks.

Rebuild of ``HorovodBasics`` (``horovod/common/__init__.py:51-154``) and the
``extern "C"`` entry points it wraps (``horovod/common/operations.cc:2413-2468``).
Differences, by design (SURVEY §2.10):

* No MPI. The world comes from the launcher env or the JAX runtime
  (see ``core.topology``). ``init()`` therefore does not spawn a
  communication thread for the synchronous API — SPMD jit programs need no
  negotiation. The background controller for the *eager/async* named-tensor
  API is started lazily on first use (``ops.engine``).
* ``init(ranks=[...])`` (or ``comm=`` given as a rank list) forms a subset
  communicator over the launcher world in list order, matching the
  reference's ``MPI_Group_incl`` semantics; an mpi4py communicator object
  is rejected — there is no MPI in this build.
* ``mpi_threads_supported()`` exists for API parity and always returns False
  (there is no MPI to share with user code).
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional

from .core import Config, LOG, NotInitializedError, Topology, discover


class _GlobalState:
    """Python analog of ``HorovodGlobalState`` (``operations.cc:115-249``).

    Holds everything that must be torn down on ``shutdown()``. Unlike the
    reference there is no background MPI thread to join for the sync path;
    the async engine registers its own shutdown hook here.
    """

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.initialized = False
        self.topology: Optional[Topology] = None
        self.config: Optional[Config] = None
        # Set by ops.engine when the eager controller starts; called on
        # shutdown (analog of joining BackgroundThreadLoop,
        # operations.cc:2425-2431).
        self.engine_shutdown_hooks = []


_global = _GlobalState()


def _state() -> _GlobalState:
    return _global


def init(ranks=None, comm=None) -> None:
    """Initialize the world. Idempotent, like ``InitializeHorovodOnce``
    (``operations.cc:2384-2399``): a second call while initialized is a
    no-op; after ``shutdown()`` re-initialization is allowed.

    ``ranks`` (or ``comm`` given as a rank list — the reference accepts
    both spellings, ``common/__init__.py:58-84``) forms a subset world:
    the listed launcher ranks become the active communicator in list
    order; every launcher process must call init with the same list.
    Processes outside the list get a self-world of size 1. An mpi4py
    communicator object is rejected — there is no MPI in this build."""
    with _global.lock:
        if _global.initialized:
            return
        if comm is not None and isinstance(comm, (list, tuple)):
            if ranks:
                raise ValueError("pass ranks either as ranks= or comm=, "
                                 "not both")
            ranks = list(comm)
            comm = None
        if ranks is not None and len(list(ranks)) == 0:
            raise ValueError(
                "init(ranks=[]) is an empty communicator; pass None (or "
                "omit) for the full world.")
        if comm is not None:
            raise ValueError(
                "horovod_tpu.init(comm=<mpi communicator>) requires MPI, "
                "which this build intentionally does not use; pass "
                "ranks=[...] for a subset world.")
        _global.config = Config.from_env()
        _global.topology = discover(subset=list(ranks) if ranks else None)
        _global.initialized = True
        if _global.config.timeline_all_ranks and \
                not _global.config.timeline_path:
            # the all-ranks knob only suffixes the base path; without one
            # there is nothing to record and the operator should hear
            # that rather than find an empty trace dir later
            LOG.warning(
                "HOROVOD_TIMELINE_ALL_RANKS=1 has no effect without "
                "HOROVOD_TIMELINE=<path>; set the base path to record "
                "per-rank traces (docs/tracing.md)")
        # Steps traced before init resolved the hierarchical knob from the
        # env and keep that routing baked in; warn if the pinned config now
        # disagrees (optimizers.check_build_time_resolutions).
        from . import optimizers as _optimizers

        _optimizers.check_build_time_resolutions(_global.config)
        topo = _global.topology
        if _global.config.jax_profile_dir and topo.rank == 0 \
                and topo.is_member:
            # is_member: subset-world NON-members also carry rank 0 (their
            # self-world), and several of them tracing into one directory
            # would collide on the hostname-keyed artifact
            # On-device twin of HOROVOD_TIMELINE (SURVEY §5.1): the host
            # timeline shows enqueue/negotiate/execute; XLA kernel time
            # lives in the profiler trace. Rank 0 only, like the timeline.
            try:
                import jax

                jax.profiler.start_trace(_global.config.jax_profile_dir)

                def _stop_trace() -> None:
                    jax.profiler.stop_trace()

                _global.engine_shutdown_hooks.append(_stop_trace)
            except Exception as exc:  # noqa: BLE001 - tracing is optional
                LOG.warning("HOROVOD_JAX_PROFILE: could not start the JAX "
                            "profiler trace: %s", exc)
        if topo.size > 1:
            # Multi-process worlds start the background engine eagerly, as
            # the reference spawns BackgroundThreadLoop inside init
            # (operations.cc:2394): every rank must participate in control
            # cycles from t0 or the coordinator cannot run negotiation,
            # stall detection, or shutdown for the ranks that did arrive.
            from .ops.engine import get_engine

            get_engine()
        elif ranks and len(ranks) > 1 and not topo.is_member \
                and topo.world_rank == 0:
            # Launcher world-rank 0 hosts the controller service even when
            # outside the subset: the launcher advertised ITS address to
            # every process, so the subset's cycles must rendezvous here.
            # (A single-member subset negotiates locally — no service, and
            # no shutdown cycle to wait for.)
            from .ops.engine import start_subset_service

            start_subset_service(list(ranks))
        epoch = world_epoch()
        # Observability plane (docs/metrics.md): world-identity gauges,
        # plus the opt-in HTTP exposition server on rank 0. Gauges are set
        # on every rank; the server only where the aggregated view lives.
        from .obs.registry import registry as _metrics_registry

        reg = _metrics_registry()
        reg.gauge("horovod_world_size",
                  "World size in processes").set(topo.size)
        reg.gauge("horovod_world_rank",
                  "This process's world rank").set(topo.rank)
        reg.gauge("horovod_elastic_world_epoch",
                  "Elastic world epoch (0 = first launch)").set(epoch)
        if _global.config.metrics_port and topo.rank == 0 \
                and topo.is_member:
            from .obs import exposition as _expo, world_snapshot_provider

            try:
                server = _expo.serve(_global.config.metrics_port,
                                     world_snapshot_provider)
                _global.engine_shutdown_hooks.append(server.close)
                LOG.info("metrics exposition serving on "
                         "http://127.0.0.1:%d/metrics (and /metrics.json)",
                         server.port)
            except OSError as exc:
                # Observability must never take the job down: a taken
                # port degrades to no exposition, loudly.
                LOG.warning("HOROVOD_METRICS_PORT=%d: exposition server "
                            "failed to start (%s); metrics HTTP disabled "
                            "for this run", _global.config.metrics_port,
                            exc)
        if epoch > 0:
            # An elastic relaunch: say so at default verbosity — operators
            # reading a worker log must be able to tell attempt N from a
            # fresh start (the rank numbering may have changed).
            LOG.warning(
                "horovod_tpu initialized on elastic world epoch %d "
                "(relaunched world; ranks renumbered over surviving "
                "slots)", epoch)
        LOG.debug(
            "horovod_tpu initialized: rank=%d size=%d local_rank=%d "
            "local_size=%d devices=%d/%d",
            _global.topology.rank, _global.topology.size,
            _global.topology.local_rank, _global.topology.local_size,
            _global.topology.local_device_count,
            _global.topology.global_device_count)


def shutdown() -> None:
    """Tear down; mirrors ``horovod_shutdown`` (``operations.cc:2424-2431``)
    including the "re-init allowed afterwards" semantics."""
    with _global.lock:
        if not _global.initialized:
            return
        hooks, _global.engine_shutdown_hooks = _global.engine_shutdown_hooks, []
        # LIFO, like atexit: later-registered hooks depend on earlier state
        # (the engine registers after init's profiler hook; the engine must
        # drain and negotiate shutdown while the profiler is still tracing)
        hooks.reverse()
        for hook in hooks:
            try:
                hook()
            except Exception as exc:  # noqa: BLE001 - teardown must not raise
                LOG.warning("engine shutdown hook failed: %s", exc)
        _global.initialized = False
        _global.topology = None
        _global.config = None


atexit.register(shutdown)


def is_initialized() -> bool:
    return _global.initialized


def _topology() -> Topology:
    topo = _global.topology
    if topo is None:
        raise NotInitializedError()
    return topo


def config() -> Config:
    cfg = _global.config
    if cfg is None:
        raise NotInitializedError()
    return cfg


def rank() -> int:
    """World rank of this process (``horovod_rank``, ``operations.cc:2437``)."""
    return _topology().rank


def size() -> int:
    """World size in processes (``horovod_size``, ``operations.cc:2453``)."""
    return _topology().size


def local_rank() -> int:
    """Rank within this host (``horovod_local_rank``, ``operations.cc:2445``)."""
    return _topology().local_rank


def local_size() -> int:
    """Processes on this host (``horovod_local_size``, ``operations.cc:2461``)."""
    return _topology().local_size


def cross_rank() -> int:
    """Host index (split by local_rank in the reference,
    ``operations.cc:1781-1797``)."""
    return _topology().cross_rank


def cross_size() -> int:
    return _topology().cross_size


def local_device_count() -> int:
    """TPU chips owned by this process. No reference analog (there, one
    process drives exactly one GPU); on TPU a process drives a host's worth
    of chips and the SPMD data plane spans them."""
    return _topology().local_device_count


def num_devices() -> int:
    """Total data-parallel devices in the world = size() x chips/process.

    This is the factor examples use for linear LR scaling (the reference
    scales by ``hvd.size()`` because size == accelerator count there)."""
    return _topology().global_device_count


def mpi_threads_supported() -> bool:
    """API parity with ``horovod_mpi_threads_supported``
    (``operations.cc:2466``); always False — no MPI in this build."""
    if not _global.initialized:
        raise NotInitializedError()
    return False


def world_epoch() -> int:
    """Elastic world epoch: 0 for a first launch, bumped by
    ``runner.run_elastic`` on every relaunch (``HOROVOD_ELASTIC_EPOCH``).
    Readable before ``init()`` — the launcher env defines it, not the
    topology."""
    import os

    from .core import config as _config

    return int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))
