"""Elastic driver: detect → abort → relaunch → restore.

``run_elastic(fn, np=N, min_np=M)`` wraps the single-attempt
``runner.run`` core in the retry loop the 0.16 reference never had (its
answer to a dead worker was an infinite hang; upstream Horovod's next
subsystem era was exactly this driver). Per attempt:

* spawn the world through ``runner._execute_world`` with the elastic env
  block (world epoch, health/state service address) merged into every
  rank's environment;
* watch three failure signals concurrently — process exit (the launcher's
  ``LaunchError``, now carrying exit code + stderr tail), stopped
  heartbeats (``health.ElasticService``), and worker-side exceptions
  (``WorkerFailedError``, e.g. the coordinator's stall escalation raising
  ``RanksAbortedError`` on every healthy rank);
* on failure: tear the world down, attribute the failure to slots,
  blacklist slots that keep failing, back off exponentially, and relaunch
  the survivors (as long as ≥ ``min_np`` remain) with a bumped
  ``HOROVOD_ELASTIC_EPOCH``;
* the relaunched world's ``elastic.State.sync()`` restores the last
  commit from this driver's state store, so training resumes instead of
  restarting.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core import config as _config
from ..core.logging import LOG
from ..core.status import parse_aborted_ranks
from ..obs.registry import registry as _metrics
from ..runner.launcher import LaunchError
from ..runner.network import make_secret
from ..runner.run_api import (
    WorkerFailedError,
    WorkerLostError,
    _execute_world,
)
from .health import ElasticService
from .recovery import recovery_window_s, warm_enabled_env


# Observability plane (docs/metrics.md): driver-process families (the
# launcher's registry, not the workers' — each process snapshots its own).
_ELASTIC_FAILURES = _metrics().counter(
    "horovod_elastic_attempt_failures_total",
    "Elastic attempts that ended in a recoverable world fault")
_ELASTIC_RELAUNCHES = _metrics().counter(
    "horovod_elastic_relaunches_total",
    "Worlds relaunched by run_elastic after a failed attempt")
# Surgical recovery plane (docs/recovery.md).
_RECOVERY_WARM = _metrics().counter(
    "horovod_recovery_warm_relaunches_total",
    "Relaunches that reused parked survivor processes (warm path)")
_RECOVERY_COLD = _metrics().counter(
    "horovod_recovery_cold_relaunches_total",
    "Relaunches that cold-forked the whole world (no survivors reused)")
_RECOVERY_SURVIVORS = _metrics().counter(
    "horovod_recovery_survivors_reused_total",
    "Survivor processes re-entered warm across all relaunches")
_RECOVERY_MTTR = _metrics().histogram(
    "horovod_recovery_mttr_seconds",
    "Fault to world-fully-beating-again latency per relaunch, by recovery "
    "mode", labels=("mode",),
    buckets=(0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 40.0, 80.0, 160.0))
_STRAGGLER_EVICTIONS = _metrics().counter(
    "horovod_straggler_evictions_total",
    "Straggler eviction advisories the elastic driver received, by mode "
    "(advisory = recorded; enforce = slot blacklisted and world "
    "relaunched) and evicted world rank", labels=("mode", "rank"))


class WorkerDeadError(RuntimeError):
    """The health plane declared ranks dead (heartbeats stopped)."""

    def __init__(self, ranks: List[int], interval_s: float,
                 miss_limit: int) -> None:
        super().__init__(
            f"ranks {sorted(ranks)} stopped heartbeating for > "
            f"{miss_limit} x {interval_s:.1f}s; declaring them dead and "
            f"tearing the world down for relaunch.")
        self.ranks = sorted(ranks)


class StragglerEvictError(RuntimeError):
    """The coordinator's persistent-straggler detector named ranks and
    ``HOROVOD_STRAGGLER_EVICT=enforce`` told the driver to act: the world
    is torn down, the named slots are blacklisted outright, and the
    survivors relaunch through the normal elastic path
    (docs/autotune.md)."""

    def __init__(self, ranks: List[int], info: Optional[dict] = None) -> None:
        super().__init__(
            f"persistent straggler(s) at world rank(s) {sorted(ranks)}; "
            f"evicting the slot(s) and relaunching the survivors "
            f"(HOROVOD_STRAGGLER_EVICT=enforce).")
        self.ranks = sorted(ranks)
        # per evicted world rank: the detector's verdict evidence
        # (blame_share / mean_spread_s / cycles) — keyed so a multi-rank
        # eviction never attributes one rank's numbers to another
        self.info = {int(r): dict(i) for r, i in (info or {}).items()}


class ElasticExhaustedError(RuntimeError):
    """run_elastic gave up: restart budget spent or too few healthy slots."""


def _is_world_fault(exc: WorkerFailedError) -> bool:
    """True when the worker exceptions describe the WORLD failing
    (aborted/shut-down collectives) rather than the user's code: only
    those are worth a relaunch — a deterministic application bug would
    just burn the restart budget and blacklist healthy slots.

    The structured failure record (``core.status.failure_record``) is
    authoritative when present; the text heuristics remain only for
    old-format peers that shipped a bare traceback string."""
    records = getattr(exc, "records", {})
    for rank, detail in exc.failures:
        record = records.get(rank)
        if record is not None:
            if record.get("world_fault"):
                return True
            continue  # structured and explicitly NOT a world fault
        if parse_aborted_ranks(detail) is not None or \
                "shut down" in detail:
            return True
    return False


def _failed_ranks(exc: BaseException) -> List[int]:
    """Attribute a failed attempt to world ranks, best effort."""
    if isinstance(exc, LaunchError):
        # The first-exiting rank may be a healthy VICTIM of someone
        # else's failure (a stall escalation makes every healthy rank
        # exit 1 while the wedged rank lingers): its stderr traceback
        # carries the structured abort tag naming the real culprit —
        # prefer that over blaming the messenger. strict=True: a stderr
        # tail is LOG text, and the coordinator routinely logs stall
        # warnings whose "missing ranks" are transient, not failures.
        named = parse_aborted_ranks(exc.stderr_tail or "", strict=True)
        return named if named else [exc.rank]
    if isinstance(exc, (WorkerDeadError, WorkerLostError,
                        StragglerEvictError)):
        return list(exc.ranks)
    if isinstance(exc, WorkerFailedError):
        # Same: a worker whose fn raised RanksAbortedError is a victim;
        # prefer the ranks its abort names — as structured wire data
        # when the worker shipped a failure record, by text parse only
        # for old-format peers.
        records = getattr(exc, "records", {})
        for rank, detail in exc.failures:
            record = records.get(rank)
            if record is not None:
                named = record.get("aborted_ranks")
                if named:
                    return [int(r) for r in named]
                continue
            named = parse_aborted_ranks(detail)
            if named:
                return named
        return list(exc.ranks)
    return []


class _SlotLedger:
    """Timestamped slot strikes with optional forgiveness decay.

    ``HOROVOD_BLACKLIST_FORGIVE_S`` (docs/recovery.md): with forgiveness 0
    (the default) a slot that collects ``limit`` strikes is banned for the
    job — the original PR 2 semantics. A positive forgiveness ages strikes
    out after that many seconds, so a long job survives transient slot
    flakiness without permanently shrinking below ``min_np``. An enforced
    :class:`StragglerEvictError` verdict is an ``evict``, not a strike —
    it is NEVER forgiven (the detector already proved persistence)."""

    def __init__(self, np: int, limit: int, forgive_s: float = 0.0) -> None:
        self._np = int(np)
        self._limit = int(limit)
        self._forgive_s = max(0.0, float(forgive_s))
        self._strikes: Dict[int, List[float]] = {s: [] for s in range(np)}
        self._evicted: set = set()

    def strike(self, slot: int, now: Optional[float] = None) -> None:
        self._strikes[slot].append(
            time.monotonic() if now is None else now)

    def evict(self, slot: int) -> None:
        self._evicted.add(slot)

    def _live_strikes(self, slot: int, now: float) -> int:
        strikes = self._strikes[slot]
        if self._forgive_s > 0.0:
            strikes[:] = [t for t in strikes if now - t < self._forgive_s]
        return len(strikes)

    def active(self, now: Optional[float] = None) -> List[int]:
        now = time.monotonic() if now is None else now
        return [s for s in range(self._np)
                if s not in self._evicted
                and self._live_strikes(s, now) < self._limit]

    def blacklisted(self, now: Optional[float] = None) -> List[int]:
        alive = set(self.active(now))
        return sorted(s for s in range(self._np) if s not in alive)


def _blacklist_forgive_s() -> float:
    raw = os.environ.get(_config.HOROVOD_BLACKLIST_FORGIVE_S, "")
    try:
        return float(raw) if raw else 0.0
    except ValueError:
        return 0.0


def _plan_successions(overrides: Dict[int, int], failed: set, world: int,
                      env: Dict[str, str]) -> Dict[int, int]:
    """Standby island-head succession (docs/recovery.md): when a failed
    rank was serving as an island head, plan the island's deterministic
    successor as its head for the relaunch — the surviving members rejoin
    under a head that never died, and the respawned rank comes back as a
    plain member."""
    mode = ((env.get(_config.HOROVOD_HIERARCHY) or
             os.environ.get(_config.HOROVOD_HIERARCHY, "flat")) or
            "flat").strip().lower()
    if mode in ("", "flat"):
        return overrides
    try:
        from ..ops.hierarchy import plan_topology

        topo = plan_topology(world, mode, cross_size=1,
                             head_overrides=overrides)
    except Exception:  # noqa: BLE001 - planning must not mask the fault
        return overrides
    if topo.flat:
        return overrides
    out = dict(overrides)
    for island, members in sorted(topo.islands.items()):
        head = topo.head_of(island)
        if head not in failed or len(members) < 2:
            continue
        successor = next(
            (m for m in sorted(members) if m not in failed), None)
        if successor is None or successor == head:
            continue
        out[island] = successor
        LOG.warning(
            "island %d head (rank %d) died; planning succession to rank "
            "%d for the relaunch", island, head, successor)
    return out


def run_elastic(fn, args: Tuple = (), kwargs: Optional[dict] = None,
                np: int = 1, min_np: int = 1,
                max_restarts: int = 3, backoff_s: float = 1.0,
                timeout_s: float = 300.0, start_timeout_s: float = 60.0,
                use_host_data_plane: bool = True,
                env_extra: Optional[Dict[str, str]] = None,
                heartbeat_interval_s: float = 1.0,
                heartbeat_miss_limit: int = 5,
                slot_fail_limit: int = 2,
                stall_shutdown_s: float = 30.0,
                straggler_evict: Optional[str] = None,
                serving_plane=None,
                on_seal=None) -> List[Any]:
    """Fault-tolerant ``runner.run``: relaunch on worker death.

    ``np`` slots are launched initially; a slot that fails
    ``slot_fail_limit`` attempts is blacklisted (a bad host keeps
    killing its worker — stop scheduling onto it), and relaunches
    continue with the surviving slots while at least ``min_np`` remain.
    ``max_restarts`` bounds total relaunches; backoff doubles per
    attempt. ``stall_shutdown_s`` is exported to the workers as
    ``HOROVOD_STALL_SHUTDOWN_TIME_S`` (unless the caller set their own)
    so an in-world stall aborts into a relaunch instead of eating the
    whole ``timeout_s``. Returns the successful attempt's per-rank
    results. State continuity across relaunches is ``elastic.State``'s
    job (its commits live in this driver's store).

    ``straggler_evict`` closes the loop on the coordinator's
    persistent-straggler detector (docs/autotune.md; default: the
    ``HOROVOD_STRAGGLER_EVICT`` env, off): under ``advisory`` the driver
    records and counts advisories the coordinator pushes; under
    ``enforce`` an advisory additionally tears the world down, blacklists
    the named slot outright, and relaunches the survivors — the same
    PR-2 path a dead rank takes.

    ``serving_plane`` wires a driver-resident
    :class:`~horovod_tpu.serving.plane.ServingPlane` through the elastic
    lifecycle (docs/serving.md failover matrix): every attempt's
    environment carries ``plane.env()`` (service address + secret, so
    the worker ranks' serving loops find the coordinator),
    ``plane.begin_epoch`` targets each attempt before launch, and a
    failed attempt's ``plane.world_down`` drains or structurally errors
    every in-flight ticket — requests issued DURING a relaunch either
    complete after the plane re-arms or fail with a structured 503
    carrying the relaunch epoch, never a hang.

    ``on_seal`` is the checkpoint plane's train-to-serve hook
    (docs/checkpoint.md): ``on_seal(ckpt_no, meta, payload)`` fires in
    the driver each time the seal ledger seals a commit — every rank's
    shard digest arrived and agreed — which is the natural place to
    ``serving_plane.publish_weights(...)`` the freshly verified state."""
    from ..tune.detector import MODES

    if not 1 <= min_np <= np:
        raise ValueError(f"need 1 <= min_np <= np, got min_np={min_np} "
                         f"np={np}")
    evict_mode = (straggler_evict if straggler_evict is not None else
                  os.environ.get(_config.HOROVOD_STRAGGLER_EVICT,
                                 "off")).strip().lower() or "off"
    if evict_mode not in MODES:
        raise ValueError(
            f"bad straggler_evict mode {evict_mode!r}; expected one of "
            f"{'/'.join(MODES)}")
    secret = make_secret()
    service = ElasticService(bytes.fromhex(secret),
                             heartbeat_interval_s=heartbeat_interval_s,
                             miss_limit=heartbeat_miss_limit)
    if on_seal is not None:
        service.ckpt.on_seal = on_seal
    ledger = _SlotLedger(np, slot_fail_limit,
                         forgive_s=_blacklist_forgive_s())
    epoch = 0
    ladder = 0  # backoff exponent; resets on checkpoint progress
    last_err: Optional[BaseException] = None
    # Surgical recovery plane (docs/recovery.md): warm relaunch reuses the
    # parked survivor processes of the failed epoch whenever the slot list
    # did not shift (rank-preserving reuse only — a shifted mapping would
    # hand a survivor a different rank than its warm caches were built
    # for). Eligibility is resolved against the env the workers will see.
    probe_env = dict(os.environ)
    probe_env.update(env_extra or {})
    warm_ok = warm_enabled_env(probe_env)
    window = recovery_window_s(probe_env)
    # (failed_epoch, active list, world, fault_t, failed world ranks) of
    # the attempt that just died — consumed by the next iteration.
    last_fault: Optional[tuple] = None
    head_overrides: Dict[int, int] = {}
    overrides_for: Optional[List[int]] = None  # active list they fit
    mttr_pending: Optional[Tuple[str, float]] = None
    try:
        while True:
            active = ledger.active()
            if len(active) < min_np:
                raise ElasticExhaustedError(
                    f"only {len(active)} healthy slot(s) left of {np} "
                    f"(min_np={min_np}); blacklisted: "
                    f"{ledger.blacklisted()}. "
                    f"Last failure: {last_err}") from last_err
            world = len(active)
            service.begin_epoch(epoch)
            if overrides_for is not None and active != overrides_for:
                # the slot list shifted: planned successions no longer
                # name the right world ranks — fall back to a full
                # re-plan (cold semantics for the hierarchy)
                head_overrides = {}
                overrides_for = None
            warm_ranks: Dict[int, int] = {}
            spawn_ranks: Optional[List[int]] = None
            warm_env_cb = None
            if last_fault is not None:
                f_epoch, f_active, f_world, _fault_t, f_failed = last_fault
                if warm_ok and active == f_active:
                    expected = set(range(f_world)) - f_failed
                    got = service.wait_parked(f_epoch, expected, window)
                    if got:
                        # Attributed-but-alive ranks (a partitioned
                        # island's members, say) park moments after the
                        # blamed abort lands on them; a short settle
                        # scoops them into the warm set instead of
                        # cold-forking twins beside live processes.
                        time.sleep(0.3)
                        got = service.parked(f_epoch)
                    warm_ranks = {r: pid for r, pid in got.items()
                                  if 0 <= r < world}
                if warm_ranks:
                    spawn_ranks = [r for r in range(world)
                                   if r not in warm_ranks]
                    need = set(warm_ranks)
                    collected: Dict[int, dict] = {}

                    def warm_env_cb(rank: int, env: dict,
                                    _epoch=f_epoch, _need=need,
                                    _got=collected) -> None:
                        # the launcher hands every non-spawned rank's env
                        # block here; once the set is complete, publish
                        # the failed epoch's recovery verdicts in one shot
                        _got[int(rank)] = env
                        if _need.issubset(_got):
                            service.publish_recovery(_epoch, dict(_got))

                    _RECOVERY_WARM.inc()
                    _RECOVERY_SURVIVORS.inc(len(warm_ranks))
                    mttr_pending = ("warm", _fault_t)
                    LOG.warning(
                        "warm relaunch for epoch %d: reusing %d parked "
                        "survivor(s) %s; cold-forking rank(s) %s",
                        epoch, len(warm_ranks), sorted(warm_ranks),
                        spawn_ranks)
                else:
                    # cold: tell every parked survivor of the failed
                    # epoch to exit (slot list shifted, warm disabled,
                    # or nobody managed to park in the window)
                    service.publish_recovery(f_epoch, {})
                    _RECOVERY_COLD.inc()
                    mttr_pending = ("cold", _fault_t)
                last_fault = None
            merged_env = {
                _config.HOROVOD_ELASTIC_EPOCH: str(epoch),
                _config.HOROVOD_ELASTIC_ADDR: "127.0.0.1",
                _config.HOROVOD_ELASTIC_PORT: str(service.port),
                _config.HOROVOD_HEARTBEAT_INTERVAL:
                    str(heartbeat_interval_s),
            }
            if stall_shutdown_s > 0:
                merged_env.setdefault(_config.HOROVOD_STALL_SHUTDOWN_TIME,
                                      str(stall_shutdown_s))
            if evict_mode != "off":
                # the worker-side detector activates off the same knob,
                # and its advisories come back over this driver's service
                merged_env.setdefault(_config.HOROVOD_STRAGGLER_EVICT,
                                      evict_mode)
            if serving_plane is not None:
                # serving coordinator endpoint + secret, and the epoch
                # target the plane arms against (stale-epoch zombies are
                # refused at shello)
                merged_env.update(serving_plane.env())
                serving_plane.begin_epoch(epoch, world)
            if env_extra:
                merged_env.update(env_extra)
            if head_overrides:
                from ..ops.hierarchy import format_head_overrides

                merged_env[_config.HOROVOD_ISLAND_HEADS] = \
                    format_head_overrides(head_overrides)
                overrides_for = list(active)
            seen_advisories: Dict[int, Any] = {}  # rank -> last seq seen

            def _health_check() -> None:
                nonlocal mttr_pending
                if mttr_pending is not None and \
                        service.beating_count() >= world:
                    mode, fault_t = mttr_pending
                    _RECOVERY_MTTR.labels(mode=mode).observe(
                        time.monotonic() - fault_t)
                    mttr_pending = None
                dead = service.dead_ranks()
                if dead:
                    raise WorkerDeadError(dead, heartbeat_interval_s,
                                          heartbeat_miss_limit)
                if evict_mode == "off":
                    return
                advisories = service.evict_advisories()
                # fresh = new rank OR a refire (higher seq): a straggler
                # that persists for hours re-advises every window, and
                # each refire must count — a flatlined counter would read
                # as "the condition cleared after the first window"
                fresh = {r: i for r, i in advisories.items()
                         if seen_advisories.get(r) != i.get("seq")}
                if not fresh:
                    return
                seen_advisories.update(
                    (r, i.get("seq")) for r, i in fresh.items())
                for evict_rank, info in sorted(fresh.items()):
                    _STRAGGLER_EVICTIONS.labels(
                        mode=evict_mode, rank=evict_rank).inc()
                    LOG.warning(
                        "straggler eviction advisory: world rank %d "
                        "(blame share %.0f%%, mean spread %.1fms over %s "
                        "cycles) [mode=%s]", evict_rank,
                        100 * info.get("blame_share", 0.0),
                        1e3 * info.get("mean_spread_s", 0.0),
                        info.get("cycles", "?"), evict_mode)
                if evict_mode == "enforce":
                    raise StragglerEvictError(sorted(fresh), fresh)

            sealed_at_start = service.ckpt.sealed_no
            this_epoch = epoch
            try:
                if epoch > 0:
                    LOG.warning(
                        "elastic relaunch %d/%d: world of %d slot(s) %s",
                        epoch, max_restarts, world, active)
                return _execute_world(
                    fn, args, kwargs or {}, world, timeout_s,
                    start_timeout_s, use_host_data_plane,
                    env_extra=merged_env, extra_abort_check=_health_check,
                    secret=secret, spawn_ranks=spawn_ranks,
                    warm_env_cb=warm_env_cb,
                    spare_pids_fn=(
                        (lambda: service.parked_pids(this_epoch))
                        if warm_ok else None),
                    spare_grace_s=(window if warm_ok else 0.0))
            except (LaunchError, StragglerEvictError, WorkerDeadError,
                    WorkerFailedError, WorkerLostError,
                    TimeoutError) as exc:
                # Deliberately NOT a bare RuntimeError: an arbitrary
                # internal error is a deterministic bug that must fail
                # fast, not burn max_restarts x timeout_s retrying.
                if serving_plane is not None:
                    # drain first, classify second: in-flight tickets must
                    # resolve (requeue or structured 503) no matter how
                    # the attempt's failure is ultimately classified
                    serving_plane.world_down(
                        f"elastic attempt {epoch} failed "
                        f"({type(exc).__name__}: {exc})")
                if isinstance(exc, WorkerFailedError) and \
                        not _is_world_fault(exc):
                    # user-code exception, not a world fault: fail fast
                    # (upstream elastic likewise only recovers from
                    # HorovodInternalError-class failures)
                    raise
                _ELASTIC_FAILURES.inc()
                # flight recorder (docs/blackbox.md): the driver's own
                # black box records every failed attempt — the dying
                # world's coordinator wrote the cross-rank incident file
                # (HOROVOD_FLIGHTREC_DIR / beside the timeline); this
                # stream is how a postmortem orders attempts vs relaunches
                from ..obs import flightrec as _flightrec

                _flightrec.record(_flightrec.EV_ELASTIC_FAIL, epoch,
                                  detail=type(exc).__name__)
                last_err = exc
                failed = _failed_ranks(exc)
                if isinstance(exc, StragglerEvictError):
                    # An enforced eviction is a VERDICT, not a strike:
                    # the slot is blacklisted outright — re-scheduling
                    # onto a persistently slow host until it "fails
                    # enough" would tax every relaunch on the way there
                    # (and the forgiveness decay NEVER applies: the
                    # detector already proved persistence).
                    for rank in failed:
                        if 0 <= rank < world:
                            ledger.evict(active[rank])
                else:
                    for rank in failed:
                        if 0 <= rank < world:
                            ledger.strike(active[rank])
                failed_world = {r for r in failed if 0 <= r < world}
                head_overrides = _plan_successions(
                    head_overrides, failed_world, world, merged_env)
                last_fault = (this_epoch, list(active), world,
                              time.monotonic(), failed_world)
                LOG.warning(
                    "elastic attempt %d failed (%s: %s); failed world "
                    "rank(s) %s -> slot(s) %s",
                    epoch, type(exc).__name__, exc, sorted(failed),
                    sorted(active[r] for r in failed
                           if 0 <= r < world))
                epoch += 1
                if epoch > max_restarts:
                    raise ElasticExhaustedError(
                        f"gave up after {max_restarts} restart(s); last "
                        f"failure: {exc}") from exc
                _ELASTIC_RELAUNCHES.inc()
                _flightrec.record(_flightrec.EV_ELASTIC_RELAUNCH, epoch)
                # Backoff ladder (docs/recovery.md): an attempt that made
                # checkpoint progress — the seal watermark advanced, i.e.
                # it survived past HOROVOD_CKPT_INTERVAL_STEPS worth of
                # steps — resets the exponent: progress means the world is
                # basically healthy and the next fault deserves a fast
                # relaunch, not a doubled one.
                progressed = service.ckpt.sealed_no > sealed_at_start
                ladder = 0 if progressed else ladder + 1
                delay = backoff_s * (2.0 ** max(0, ladder - 1))
                LOG.warning("elastic backoff: %.1fs before relaunch%s",
                            delay,
                            " (ladder reset: epoch sealed a commit)"
                            if progressed else "")
                time.sleep(delay)
    finally:
        # Orphan sweep: any survivor still parked gets the explicit
        # 'everyone out' verdict before the service dies, so it exits now
        # instead of waiting out its poll deadline.
        for stale_epoch in service.parked_epochs():
            service.publish_recovery(stale_epoch, {})
        service.shutdown()
