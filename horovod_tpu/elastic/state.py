"""Elastic training state: commit / restore / sync over arbitrary pytrees.

The restore half of the elastic loop. Upstream Horovod grew
``hvd.elastic.State`` one subsystem era after the 0.16 reference; this is
that shape rebuilt on this repo's own primitives: per-leaf broadcast rides
``state_bcast.broadcast_parameters`` (fused, device-plane aware) and
non-array leaves ride ``state_bcast.broadcast_object`` (pickle wire), so a
relaunched world resumes bit-exact from the last committed step.

A relaunch replaces every worker PROCESS, so in-memory copies alone cannot
survive it: ``commit()`` also pushes rank 0's committed tree to the elastic
driver's state store (``health.ElasticService`` — the driver process
outlives every world attempt), and the first ``sync()`` of a relaunched
world fetches it back before broadcasting. Worlds launched outside
``run_elastic`` (no store in the env) degrade gracefully to in-process
commit/restore — the upstream semantics for in-place recovery.

Fault injection (``HOROVOD_ELASTIC_FAULT=rank:commit[:epoch]``): the named
rank dies with ``os._exit`` right BEFORE persisting its Nth commit of that
epoch — the hook the recovery tests (and chaos drills) use to kill a worker
mid-training deterministically.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import basics, state_bcast
from ..basics import world_epoch
from ..core import config as _config
from ..core.config import _env_bool, _env_float, _env_int
from ..core.logging import LOG
from ..runner.network import BasicClient, default_secret


def parse_fault_spec(spec: str) -> Optional[Tuple[int, int, int]]:
    """``rank:commit[:epoch]`` -> (rank, commit_no, epoch); None if unset
    or malformed (a malformed spec must not take down production jobs)."""
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) not in (2, 3):
        return None
    try:
        rank, commit_no = int(parts[0]), int(parts[1])
        epoch = int(parts[2]) if len(parts) == 3 else 0
    except ValueError:
        return None
    return rank, commit_no, epoch


def _maybe_inject_fault(commit_no: int) -> None:
    fault = parse_fault_spec(
        os.environ.get(_config.HOROVOD_ELASTIC_FAULT, ""))
    if fault is None:
        return
    rank, at_commit, at_epoch = fault
    if (basics.rank() == rank and commit_no == at_commit
            and world_epoch() == at_epoch):
        LOG.warning("HOROVOD_ELASTIC_FAULT firing: rank %d dying before "
                    "commit %d (epoch %d)", rank, at_commit, at_epoch)
        os._exit(13)


def _is_array(leaf: Any) -> bool:
    return hasattr(leaf, "shape") and hasattr(leaf, "dtype") \
        and not np.isscalar(leaf)


def _host_copy(leaf: Any) -> Any:
    """Private host-side snapshot of a leaf (D2H for jax arrays — the
    committed copy must survive donation/deletion of the live buffers).
    ZeRO-1 shard leaves copy their resident shard (docs/sharding.md):
    the snapshot stays 1/N-sized and communication-free; the canonical
    expansion happens only on the commit persist path."""
    from ..sharding.zero1 import ShardLeaf, is_shard

    if is_shard(leaf):
        return ShardLeaf(np.array(leaf.data, copy=True), leaf.spec)
    if _is_array(leaf):
        return np.array(np.asarray(leaf), copy=True)
    return leaf


class State:
    """Commit/restore wrapper over named pytrees (params, optimizer state,
    step counters, ...).

    ::

        state = elastic.State(params=params, opt_state=opt_state, step=0)

        def train(state):
            while state.step < total_steps:
                ... one step using state.params / state.opt_state ...
                state.step += 1
                state.commit()

        state.run(train)

    ``run`` syncs first — after a relaunch that pulls the last committed
    state from the elastic driver and broadcasts rank 0's copy to every
    rank — then calls the function. ``commit`` snapshots the current
    values (and persists them to the driver from rank 0); ``restore``
    rewinds to the last snapshot without any communication.
    """

    def __init__(self, **values: Any) -> None:
        if not values:
            raise ValueError("State needs at least one named value, e.g. "
                             "State(params=..., step=0)")
        for key in values:
            if key.startswith("_") or hasattr(type(self), key):
                raise ValueError(f"invalid state name {key!r}")
        self._keys = sorted(values)
        for key, value in values.items():
            setattr(self, key, value)
        self._commit_no = 0
        self._sync_no = 0
        self._maybe_no = 0
        self._synced = False
        self._store: Optional[BasicClient] = None
        self._committer = None  # lazy ckpt.AsyncCommitter (async path)
        self._manifest_warned = False  # warn once on old-driver degrade
        # restore provenance, set by _fetch_commit for tests/postmortems:
        # "sealed" (checkpoint-plane ledger) or "legacy" (synchronous
        # whole-tree store), plus the adopted commit number
        self.restore_source: Optional[str] = None
        self.restore_commit_no: Optional[int] = None
        self._committed = self._snapshot()

    # -- snapshots ------------------------------------------------------------

    def _tree(self) -> Dict[str, Any]:
        return {key: getattr(self, key) for key in self._keys}

    def _snapshot(self) -> Dict[str, Any]:
        import jax

        return jax.tree_util.tree_map(_host_copy, self._tree())

    def commit(self) -> None:
        """Snapshot the current values as the recovery point; rank 0 also
        persists the snapshot to the elastic driver's store (when this
        world was launched by ``run_elastic``). With
        ``HOROVOD_CKPT_ASYNC=1`` the persist rides the checkpoint plane
        instead (docs/checkpoint.md): EVERY rank hands the snapshot to a
        background :class:`~horovod_tpu.ckpt.committer.AsyncCommitter`
        (rank 0 streams the chunked payload, the others ship the digest
        votes that let the driver seal = verify the commit) and this
        call returns in O(snapshot), independent of state size. The
        fault-injection hook fires before anything is saved, so an
        injected death always rolls back to the PREVIOUS commit — a
        real mid-step crash."""
        t0 = time.monotonic()
        self._commit_no += 1
        _maybe_inject_fault(self._commit_no)
        self._committed = self._snapshot()
        # ZeRO-1 (docs/sharding.md): the LOCAL snapshot keeps shard
        # leaves (communication-free restore at 1/N memory); everything
        # that leaves this process — the consensus digest, the driver
        # push, the async stream — uses the CANONICAL expanded tree,
        # which is byte-identical on every rank and byte-identical to
        # what a replicated world would commit, so digest votes agree,
        # observe_commit semantics are unchanged, and a relaunch at a
        # DIFFERENT world size restores it by simply re-cutting. The
        # expansion is collective (one negotiated allgather per shard
        # leaf): every rank reaches this line each commit.
        canonical = self._canonical_commit()
        # Consensus verification of the recovery point itself
        # (docs/integrity.md): every rank folds the committed tree's
        # digest into its live consensus window, so relaunch-and-restore
        # can never resume from state the ranks did not actually agree
        # on. No-op when HOROVOD_CONSENSUS_INTERVAL_STEPS is unset or no
        # engine is running.
        from ..integrity.consensus import observe_commit

        observe_commit(canonical, self._commit_no)
        # flight recorder (docs/blackbox.md): the commit ordinal is the
        # restore point a postmortem reader reasons back from
        from ..obs import flightrec as _flightrec

        _flightrec.record(_flightrec.EV_COMMIT, self._commit_no,
                          aux=basics.world_epoch())
        if self._async_enabled():
            self._submit_async(canonical)
        elif basics.rank() == 0:
            self._push_commit(canonical)
        # both paths report the stall the TRAINING LOOP paid — the bench
        # headline (docs/checkpoint.md): ~flat vs state size when async,
        # linear when synchronous
        from ..ckpt.committer import observe_commit_stall

        observe_commit_stall(time.monotonic() - t0)

    def maybe_commit(self) -> bool:
        """Commit every ``HOROVOD_CKPT_INTERVAL_STEPS``-th call (default
        1 = every call) — the cadence knob the autotune ladder owns
        (``tune.policy.ckpt_interval_knob``). Returns True when a commit
        actually ran."""
        self._maybe_no += 1
        interval = max(_env_int(_config.HOROVOD_CKPT_INTERVAL_STEPS, 1), 1)
        if self._maybe_no % interval != 0:
            return False
        self.commit()
        return True

    def flush_commits(self, timeout_s: float = 30.0) -> bool:
        """Drain the async commit stream (no-op on the synchronous
        path). Call before a clean exit so the last commit has reached
        the driver's ledger; the chaos drills also use it to serialize
        streams against the kill-between-chunks fault."""
        if self._committer is None:
            return True
        return self._committer.wait_idle(timeout_s=timeout_s)

    def restore(self) -> None:
        """Rewind the live attributes to the last committed snapshot."""
        import jax

        for key in self._keys:
            setattr(self, key, jax.tree_util.tree_map(
                _host_copy, self._committed[key]))

    # -- driver store ---------------------------------------------------------

    def _store_client(self) -> Optional[BasicClient]:
        port = os.environ.get(_config.HOROVOD_ELASTIC_PORT)
        if not port:
            return None
        if self._store is None:
            addr = os.environ.get(_config.HOROVOD_ELASTIC_ADDR, "127.0.0.1")
            # HOROVOD_CKPT_PUSH_TIMEOUT_S (docs/checkpoint.md): the
            # 60 s default assumes one synchronous commit frame can
            # carry the whole model; the chunked async pipeline never
            # needs that and jobs on it should tighten the bound
            self._store = BasicClient(
                (addr, int(port)), secret=default_secret(), attempts=3,
                timeout_s=_env_float(_config.HOROVOD_CKPT_PUSH_TIMEOUT_S,
                                     60.0))
        return self._store

    def _drop_store_client(self) -> None:
        """A failed request may leave a partial frame on the connection;
        reconnect next time rather than poisoning every later commit."""
        if self._store is not None:
            try:
                self._store.close()
            except Exception:  # noqa: BLE001
                pass
            self._store = None

    def _async_enabled(self) -> bool:
        return _env_bool(_config.HOROVOD_CKPT_ASYNC) and \
            bool(os.environ.get(_config.HOROVOD_ELASTIC_PORT))

    def _canonical_commit(self) -> Dict[str, Any]:
        """The commit tree every byte-level consumer sees: identical to
        ``self._committed`` for replicated state; for ZeRO-1 sharded
        state, the expanded canonical tree (COLLECTIVE — one negotiated
        allgather per shard leaf), plus this rank's partition-manifest
        vote to the driver's seal ledger (best-effort: an old driver
        errors the tag, warned once, and the commit proceeds with the
        whole-tree digest only)."""
        from ..sharding import zero1 as _z1

        if not _z1.has_shards(self._committed):
            return self._committed
        from .. import ops as _ops

        tag = f"zero1.commit.{world_epoch()}.{self._commit_no}"
        canonical = {
            key: _z1.expand_tree(val, _ops.allgather, tag=f"{tag}.{key}")
            for key, val in self._committed.items()}
        self._push_shard_manifest()
        return canonical

    def _push_shard_manifest(self) -> None:
        client = self._store_client()
        if client is None:
            return
        from ..sharding import zero1 as _z1

        digest = _z1.shard_digest(self._committed).hex()
        try:
            client.request(("shard_manifest", world_epoch(),
                            self._commit_no, basics.rank(),
                            basics.size(), digest))
        except Exception as exc:  # noqa: BLE001 - provenance, not safety
            self._drop_store_client()
            if not self._manifest_warned:
                self._manifest_warned = True
                LOG.warning(
                    "shard manifest push failed: %s (driver predates the "
                    "sharding plane? commits proceed with the whole-tree "
                    "digest only)", exc)

    def _submit_async(self, tree: Optional[Dict[str, Any]] = None) -> None:
        """Hand the committed snapshot to the background stream (every
        rank — the ledger needs the full world's digest votes to seal)."""
        from ..ckpt.committer import AsyncCommitter
        from ..obs import flightrec as _flightrec

        if self._committer is None:
            addr = os.environ.get(_config.HOROVOD_ELASTIC_ADDR, "127.0.0.1")
            port = int(os.environ.get(_config.HOROVOD_ELASTIC_PORT))
            self._committer = AsyncCommitter(
                (addr, port), rank=basics.rank(), world=basics.size(),
                secret=default_secret())
        self._committer.submit(
            self._commit_no,
            self._committed if tree is None else tree, world_epoch())
        _flightrec.record(_flightrec.EV_CKPT_SUBMIT, self._commit_no,
                          aux=world_epoch())

    def _push_commit(self, tree: Optional[Dict[str, Any]] = None) -> None:
        client = self._store_client()
        if client is None:
            return
        meta = {"commit_no": self._commit_no}
        from ..sharding import zero1 as _z1

        if _z1.has_shards(self._committed):
            # Provenance only — the pushed tree is already canonical
            # (expanded), so restore needs no world-size translation.
            meta["zero1"] = {"world": basics.size()}
        try:
            client.request(("commit", world_epoch(), meta,
                            pickle.dumps(
                                self._committed if tree is None else tree,
                                protocol=pickle.HIGHEST_PROTOCOL)))
        except Exception as exc:  # noqa: BLE001 - commits are best-effort
            self._drop_store_client()
            LOG.warning("elastic commit push failed: %s (recovery will "
                        "fall back to an older commit)", exc)

    def _fetch_commit(self) -> Optional[Dict[str, Any]]:
        client = self._store_client()
        if client is None:
            return None
        sealed = self._fetch_sealed(client)
        if sealed is not None:
            return sealed
        try:
            resp = client.request(("fetch",))
        except Exception as exc:  # noqa: BLE001
            self._drop_store_client()
            LOG.warning("elastic commit fetch failed: %s (starting from "
                        "the constructor state)", exc)
            return None
        _, meta, payload = resp
        if payload is None:
            return None
        committed = pickle.loads(payload)
        if not self._keys_match(committed):
            return None
        self.restore_source = "legacy"
        self.restore_commit_no = (meta or {}).get("commit_no")
        LOG.info("elastic restore: adopting driver commit %s",
                 (meta or {}).get("commit_no"))
        return committed

    def _fetch_sealed(self, client: BasicClient) -> Optional[Dict[str, Any]]:
        """Checkpoint-plane restore: adopt the driver ledger's last
        SEALED commit (docs/checkpoint.md). Verified on the way in —
        the restored tree must reproduce the digest the world's ranks
        agreed on at seal time, or the adoption is refused and restore
        falls back to the legacy synchronous store."""
        try:
            resp = client.request(("ckpt_fetch",))
        except Exception as exc:  # noqa: BLE001 - older driver or wire hiccup
            self._drop_store_client()
            LOG.warning("ckpt fetch failed: %s (falling back to the "
                        "legacy commit store)", exc)
            return None
        _, sealed_no, meta, payload = resp
        if payload is None:
            return None
        committed = pickle.loads(payload)
        if not self._keys_match(committed):
            return None
        from ..integrity.consensus import tree_digest

        want = (meta or {}).get("digest")
        got = tree_digest(committed)
        if want and got != want:
            LOG.warning(
                "sealed commit %s fails its digest (%s != %s) — refusing "
                "it, falling back to the legacy commit store",
                sealed_no, got, want)
            return None
        self.restore_source = "sealed"
        self.restore_commit_no = (meta or {}).get("commit_no", sealed_no)
        from ..obs import flightrec as _flightrec

        _flightrec.record(_flightrec.EV_CKPT_RESTORE,
                          int(self.restore_commit_no or -1),
                          detail="sealed")
        LOG.info("elastic restore: adopting SEALED commit %s (digest ok)",
                 sealed_no)
        return committed

    def _keys_match(self, committed: Dict[str, Any]) -> bool:
        if sorted(committed) == self._keys:
            return True
        LOG.warning("stored elastic commit has keys %s but this State "
                    "has %s; ignoring the stored commit",
                    sorted(committed), self._keys)
        return False

    # -- sync -----------------------------------------------------------------

    def sync(self, root_rank: int = 0) -> None:
        """Make every rank's state identical to root's.

        On the FIRST sync of a process, rank ``root_rank`` first adopts
        the elastic driver's stored commit (present only after a
        relaunch), so the broadcast seeds the new world from the last
        recovery point. Array leaves broadcast fused via
        ``broadcast_parameters``; everything else rides one
        ``broadcast_object``."""
        import jax

        from ..sharding import zero1 as _z1

        # ZeRO-1: the pickle/broadcast wire below moves plain arrays, and
        # every rank must flatten the SAME leaf flavors (arr_mask is
        # computed locally). Expand sharded keys to canonical full trees
        # first — collective, so it runs before the root-only fetch can
        # make leaf flavors diverge — and re-localize after the merge.
        # Each rank shards uniformly (same apply_step path), so
        # has_shards() agrees across the world.
        shard_templates: Dict[str, Any] = {}
        live = self._tree()
        if _z1.has_shards(live):
            from .. import ops as _ops

            tag = f"zero1.sync.{world_epoch()}.{self._sync_no + 1}"
            for key, val in live.items():
                if not _z1.has_shards(val):
                    continue
                shard_templates[key] = val
                setattr(self, key, _z1.expand_tree(
                    val, _ops.allgather, tag=f"{tag}.{key}"))

        if not self._synced and basics.rank() == root_rank:
            stored = self._fetch_commit()
            if stored is not None:
                self._committed = stored
                self.restore()
        self._synced = True
        self._sync_no += 1
        leaves, treedef = jax.tree_util.tree_flatten(self._tree())
        arr_mask = [_is_array(leaf) for leaf in leaves]
        # Array leaves: placeholder-None the rest so the engine can fuse
        # the real tensors (None leaves vanish from the flatten and
        # reappear on unflatten).
        arrays = [leaf if m else None for leaf, m in zip(leaves, arr_mask)]
        arrays = state_bcast.broadcast_parameters(
            arrays, root_rank,
            name_prefix=f"elastic.sync.{world_epoch()}.{self._sync_no}")
        others = [None if m else leaf for leaf, m in zip(leaves, arr_mask)]
        others = state_bcast.broadcast_object(
            others, root_rank,
            name=f"elastic.sync.obj.{world_epoch()}.{self._sync_no}")
        merged = [a if m else o
                  for a, o, m in zip(arrays, others, arr_mask)]
        # Preserve each rank's local leaf flavor: root may have adopted
        # numpy snapshots from the store while this rank built jax arrays.
        merged = [_match_flavor(new, old)
                  for new, old in zip(merged, leaves)]
        tree = jax.tree_util.tree_unflatten(treedef, merged)
        for key in self._keys:
            setattr(self, key, tree[key])
        # Re-localize keys that were sharded going in: adopt_tree cuts
        # this rank's shard of the (now world-identical) full tree —
        # repartitioning for the CURRENT world size, which is how an
        # N -> N-1 relaunch reshards the last sealed commit.
        for key, template in shard_templates.items():
            setattr(self, key, _z1.adopt_tree(
                template, getattr(self, key), basics.size(), basics.rank()))
        # The synced state is the recovery point (local snapshot only: a
        # push here would overwrite the driver's commit with itself).
        self._committed = self._snapshot()

    def run(self, fn, *args: Any, **kwargs: Any) -> Any:
        """``sync()`` then ``fn(self, *args, **kwargs)`` — user training
        loops written this way resume from the last commit after an
        elastic relaunch with no extra code."""
        self.sync()
        return fn(self, *args, **kwargs)


def _match_flavor(new: Any, old: Any) -> Any:
    """Return ``new`` converted to ``old``'s array flavor (jax vs numpy)
    so a sync never silently changes the types user code steps with."""
    if not _is_array(old) or not _is_array(new):
        return new
    if isinstance(old, np.ndarray):
        return np.asarray(new)
    try:
        import jax
        import jax.numpy as jnp

        if isinstance(old, jax.Array) and not isinstance(new, jax.Array):
            return jnp.asarray(new)
    except Exception:  # noqa: BLE001 - no jax: numpy passthrough
        pass
    return new
