"""Elastic fault tolerance: heartbeats, abort-instead-of-hang, relaunch.

The subsystem the 0.16 reference lacked (its answer to a dead worker was
an infinite hang behind a stall warning; upstream Horovod's next era was
elastic mode). Four pieces, built entirely on this repo's existing
primitives (docs/elastic.md):

* **health plane** (:mod:`.health`): every rank heartbeats the elastic
  driver over the HMAC-framed TCP wire; the driver declares ranks dead
  when beats stop.
* **abort-instead-of-hang** (``HOROVOD_STALL_SHUTDOWN_TIME_S``): the
  coordinator escalates an expired stall deadline into a structured
  shutdown, so healthy ranks raise :class:`RanksAbortedError` (naming the
  missing ranks) out of ``allreduce``/``synchronize`` instead of blocking
  forever (``ops/controller.py`` + the native wrapper).
* **elastic driver** (:func:`run_elastic`): detect → abort → relaunch →
  restore, with slot blacklisting, ``min_np``, restart budget, and
  exponential backoff.
* **state** (:class:`State`): commit/restore/sync over arbitrary pytrees
  (params + optimizer state + step), persisted in the driver's store so a
  relaunched world resumes from the last commit.

``State`` imports lazily: the worker entry hooks the health plane without
paying the jax import.
"""

from __future__ import annotations

from ..core.status import RanksAbortedError
from .driver import (
    ElasticExhaustedError,
    StragglerEvictError,
    WorkerDeadError,
    run_elastic,
)
from .health import ElasticService, HeartbeatReporter

__all__ = [
    "ElasticExhaustedError",
    "ElasticService",
    "HeartbeatReporter",
    "RanksAbortedError",
    "State",
    "StragglerEvictError",
    "WorkerDeadError",
    "run_elastic",
    "world_epoch",
]


def __getattr__(name):
    # State (and world_epoch) live with the jax-facing code; loading them
    # lazily keeps `elastic.health` importable from the worker entry
    # before the platform pin.
    if name in ("State", "world_epoch"):
        from . import state as _state

        return getattr(_state, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
