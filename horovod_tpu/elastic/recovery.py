"""Worker-side half of the surgical recovery plane (docs/recovery.md).

When a world fault aborts an epoch, the cold path exits every surviving
process and pays jax import + XLA compiles + warmup again per slot. This
module is the warm alternative: a survivor parks in the elastic driver's
recovery barrier (``("recover", epoch, rank, pid)`` — the PR 2/PR 7
epoch-fencing convention), tears down ONLY the control plane (the engine
singleton; its connections and caches epoch-invalidate anyway), keeps the
process with its devices and compiled-program caches, and polls for the
driver's verdict:

* ``("assign", env)`` — warm re-entry: apply the successor epoch's
  ``HOROVOD_*`` env block in-process and re-run the training fn. The fn
  object itself is REUSED (never re-fetched): jit caches key on function
  identity, and preserving it is the whole point of staying warm.
* ``("exit", reason)`` — the slot was not reused (rank mapping shifted,
  warm disabled for the round, job over): exit like the cold path.

The worker decides eligibility locally from env — warm must be opt-out-able
per process and must never engage for non-elastic jobs, user-code faults
(``world_fault`` False), or the native controller (whose binary wire has no
re-hello path; docs/recovery.md degrade matrix).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..core import config as _config
from ..core.logging import LOG
from ..obs import flightrec as _flightrec
from ..runner.network import BasicClient, default_secret


def warm_enabled_env(env=os.environ) -> bool:
    """HOROVOD_RECOVERY_WARM gate (default ON), minus the documented
    degrades: native controller worlds go cold."""
    raw = env.get(_config.HOROVOD_RECOVERY_WARM, "1").strip().lower()
    if raw in ("", "0", "false"):
        return False
    if env.get(_config.HOROVOD_NATIVE_CONTROLLER, "").strip() not in ("", "0"):
        return False
    return True


def recovery_window_s(env=os.environ) -> float:
    raw = env.get(_config.HOROVOD_RECOVERY_WINDOW_S, "")
    try:
        return float(raw) if raw else 15.0
    except ValueError:
        return 15.0


def maybe_recover(rank: int, record: dict) -> Optional[dict]:
    """Park this survivor in the recovery barrier and wait for a verdict.

    Returns the warm re-entry env block, or None when this process should
    exit (ineligible, told to exit, or the driver went silent past the
    poll deadline — the hang-proofing bound; a dead driver also ends us
    via the parent-death watchdog)."""
    if not warm_enabled_env():
        return None
    port = os.environ.get(_config.HOROVOD_ELASTIC_PORT)
    if not port:
        return None  # not an elastic job: nobody to park with
    if not record.get("world_fault"):
        return None  # user-code failure: fail fast, never relaunch
    epoch = int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))
    addr = os.environ.get(_config.HOROVOD_ELASTIC_ADDR, "127.0.0.1")
    # Tear down the control plane NOW, before parking: the successor epoch
    # must never find a half-alive engine, and survivors unwinding their
    # services promptly is what lets peers' reconnect windows resolve.
    from .. import basics

    try:
        basics.shutdown()
    except Exception:  # noqa: BLE001 - already down on most crash paths
        pass
    try:
        client = BasicClient((addr, int(port)), secret=default_secret(),
                             attempts=3, timeout_s=10.0)
    except Exception:  # noqa: BLE001 - driver gone: cold exit
        return None
    try:
        client.request(("recover", epoch, rank, os.getpid()))
        _flightrec.record(_flightrec.EV_RECOVER_PARK, epoch)
        LOG.warning("rank %d parked in the recovery barrier for epoch %d "
                    "(pid %d kept warm)", rank, epoch, os.getpid())
        # The verdict can trail the fault by the driver's survivor-wait
        # window PLUS its relaunch backoff ladder; the deadline is a
        # hang-proofing bound well past both.
        deadline = time.monotonic() + 4 * recovery_window_s() + 120.0
        while time.monotonic() < deadline:
            try:
                resp = client.request(("recover_poll", epoch, rank))
            except Exception:  # noqa: BLE001 - service shut down: job over
                return None
            if resp[0] == "assign":
                return dict(resp[1])
            if resp[0] == "exit":
                LOG.warning("rank %d leaving the recovery barrier: %s",
                            rank, resp[1])
                return None
            time.sleep(0.25)
        LOG.warning("rank %d recovery poll deadline expired; exiting cold",
                    rank)
        return None
    finally:
        client.close()


def apply_assignment(env: dict) -> int:
    """Apply a warm re-entry env block in-process and return the new rank.

    Only ``HOROVOD_*`` / ``TPU_*`` keys are touched; keys of those
    prefixes present in the process env but ABSENT from the block are
    removed — critically the launcher-inherited listener fds
    (``HOROVOD_CONTROLLER_FD`` and friends), which point at sockets the
    dead epoch already closed and must not be adopted again."""
    managed = ("HOROVOD_", "TPU_")
    for key in [k for k in os.environ
                if k.startswith(managed) and k not in env]:
        del os.environ[key]
    for key, val in env.items():
        if key.startswith(managed):
            os.environ[key] = str(val)
    new_epoch = int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))
    _flightrec.record(_flightrec.EV_RECOVER_WARM, new_epoch)
    return int(os.environ[_config.HOROVOD_RANK])
