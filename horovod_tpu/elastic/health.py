"""Health plane: worker heartbeats + the driver's health-and-state service.

The reference has no liveness story beyond the coordinator's stall warning
(``CheckForStalledTensors``): a wedged or dead rank hangs the world until an
operator intervenes. This module is the driver-side half of the elastic
subsystem's detect step: every rank heartbeats the elastic driver over the
same HMAC-framed TCP wire the launcher and controller already use
(``runner.network``), and the driver declares a rank dead when its beats
stop — catching the one failure mode neither process-exit watching (the
launcher's ``_wait_all``) nor the coordinator's stall escalation can see: a
process that is alive but wedged before it ever reaches a collective.

The same service doubles as the committed-state store for
``elastic.State``: rank 0 pushes its last commit here (the driver process
outlives every worker world), and the first sync of a relaunched world
fetches it back. One port, one secret, one service.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..ckpt.store import SealLedger
from ..core import config as _config
from ..core.logging import LOG
from ..runner.network import BasicClient, BasicService, default_secret


class ElasticService:
    """Driver-side heartbeat monitor + committed-state store.

    Requests on the wire:
      ("beat", epoch, rank)              -> ("ok",)
      ("goodbye", epoch, rank)           -> ("ok",)  # clean exit: stop watching
      ("commit", epoch, meta, payload)   -> ("ok",)  # rank 0's state push
      ("fetch",)                         -> ("commit", meta, payload | None)
      ("advise_evict", epoch, rank, info)-> ("ok",)  # straggler advisory
                                                     # (docs/autotune.md)
      ("recover", epoch, rank, pid)      -> ("ok",)  # survivor parks in the
                                                     # recovery barrier
                                                     # (docs/recovery.md)
      ("recover_poll", epoch, rank)      -> ("wait",)
                                          | ("assign", env)   # warm re-entry
                                          | ("exit", reason)  # cold: exit now

    plus the checkpoint plane's chunked commit streams and the gateway
    ticket journal (docs/checkpoint.md), ingested into the
    :class:`~horovod_tpu.ckpt.store.SealLedger` at ``self.ckpt``:
      ("ckpt_begin", epoch, no, rank, meta)        -> ("ok",)
      ("ckpt_chunk", epoch, no, rank, seq, bytes)  -> ("ok",)   # rank 0 only
      ("ckpt_end", epoch, no, rank, n_chunks, dig) -> ("ok", sealed_no)
      ("ckpt_fetch",)                     -> ("ckpt", sealed_no, meta,
                                              payload | None)
      ("ckpt_journal_put", key, entry)    -> ("ok",)
      ("ckpt_journal_get", key)           -> ("entry", entry | None)
      ("ckpt_journal_del", key)           -> ("ok",)

    plus the sharding plane's partition manifest (docs/sharding.md):
      ("shard_manifest", epoch, no, rank, world, dig) -> ("ok",)

    Beats are tagged with the world epoch so a straggler from a torn-down
    attempt cannot resurrect itself into the successor world's liveness
    table. A rank is dead when its beats STOPPED: ranks that never beat at
    all are the registration timeout's problem (they may still be
    importing jax), not this monitor's.
    """

    def __init__(self, secret: bytes,
                 heartbeat_interval_s: float = 1.0,
                 miss_limit: int = 5) -> None:
        self._interval_s = heartbeat_interval_s
        self._miss_limit = miss_limit
        self._lock = threading.Lock()
        self._epoch = 0
        self._last_beat: Dict[int, float] = {}
        self._departed: set = set()
        self._evict_advisories: Dict[int, dict] = {}
        self._commit: Optional[bytes] = None
        self._commit_meta: Optional[dict] = None
        # Surgical recovery barrier (docs/recovery.md): survivors of a
        # world fault park here instead of exiting, keyed by the epoch
        # that FAILED — the PR 2/PR 7 fencing convention keeps a torn-down
        # attempt's late park from joining the wrong recovery round.
        # {failed epoch -> {rank -> pid}}; plans mirror the keying with
        # the driver's verdict per rank (an env block = warm re-entry,
        # absence after the plan publishes = exit).
        self._parked: Dict[int, Dict[int, int]] = {}
        self._recovery_plans: Dict[int, Dict[int, dict]] = {}
        # checkpoint plane (docs/checkpoint.md): the seal ledger lives
        # with the service — the driver process outlives every world
        # attempt, and with HOROVOD_CKPT_DIR set it outlives the driver
        self.ckpt = SealLedger(
            dir=os.environ.get(_config.HOROVOD_CKPT_DIR) or None)
        self._service = BasicService("horovod-elastic", self._handle,
                                     secret=secret)
        self.port = self._service.port

    def _handle(self, req: Any, _sock) -> Any:
        kind = req[0]
        if kind == "beat":
            _, epoch, rank = req
            with self._lock:
                if epoch == self._epoch:
                    self._last_beat[rank] = time.monotonic()
            return ("ok",)
        if kind == "goodbye":
            _, epoch, rank = req
            with self._lock:
                if epoch == self._epoch:
                    self._departed.add(rank)
                    self._last_beat.pop(rank, None)
            return ("ok",)
        if kind == "commit":
            _, epoch, meta, payload = req
            with self._lock:
                # Epoch fence, like beats: a torn-down world's straggling
                # commit must not overwrite the successor's newer state
                # (the next relaunch would silently replay steps).
                if epoch == self._epoch:
                    self._commit = payload
                    self._commit_meta = dict(meta, epoch=epoch)
            return ("ok",)
        if kind == "fetch":
            with self._lock:
                return ("commit", self._commit_meta, self._commit)
        if kind == "ckpt_begin":
            # Checkpoint-plane stream frames (docs/checkpoint.md). The
            # ledger applies its own epoch fence — a torn-down world's
            # straggling stream is acknowledged and ignored, like beats.
            _, epoch, ckpt_no, rank, meta = req
            self.ckpt.ingest_begin(epoch, ckpt_no, rank, meta)
            return ("ok",)
        if kind == "ckpt_chunk":
            _, epoch, ckpt_no, rank, seq, payload = req
            self.ckpt.ingest_chunk(epoch, ckpt_no, rank, seq, payload)
            return ("ok",)
        if kind == "ckpt_end":
            # the response doubles as the seal ack: the committer learns
            # whether its commit (or a later one) actually sealed
            _, epoch, ckpt_no, rank, n_chunks, digest = req
            sealed_no = self.ckpt.ingest_end(epoch, ckpt_no, rank,
                                             n_chunks, digest)
            return ("ok", sealed_no)
        if kind == "ckpt_fetch":
            sealed_no, meta, payload = self.ckpt.fetch_sealed()
            return ("ckpt", sealed_no, meta, payload)
        if kind == "shard_manifest":
            # ZeRO-1 partition manifest (docs/sharding.md): per-rank
            # shard-digest vote for a pending commit, folded into the
            # seal meta. Epoch-fenced by the ledger like ckpt frames.
            _, epoch, ckpt_no, rank, world, digest = req
            self.ckpt.ingest_shard_manifest(epoch, ckpt_no, rank, world,
                                            digest)
            return ("ok",)
        if kind == "ckpt_journal_put":
            _, key, entry = req
            self.ckpt.journal.put(key, entry)
            return ("ok",)
        if kind == "ckpt_journal_get":
            _, key = req
            return ("entry", self.ckpt.journal.get(key))
        if kind == "ckpt_journal_del":
            _, key = req
            self.ckpt.journal.delete(key)
            return ("ok",)
        if kind == "recover":
            # No epoch gate against self._epoch: survivors of epoch E park
            # while the driver may already be preparing epoch E+1 — the
            # barrier is keyed by the epoch they FELL OUT OF, and stale
            # epochs age out in begin_epoch.
            _, epoch, rank, pid = req
            with self._lock:
                self._parked.setdefault(int(epoch), {})[int(rank)] = int(pid)
            return ("ok",)
        if kind == "recover_poll":
            _, epoch, rank = req
            with self._lock:
                plan = self._recovery_plans.get(int(epoch))
                if plan is None:
                    return ("wait",)
                env = plan.get(int(rank))
            if env is None:
                return ("exit", "slot not reused in the successor world")
            return ("assign", env)
        if kind == "advise_evict":
            # Persistent-straggler advisory from the coordinator's
            # detector (horovod_tpu.tune.detector; docs/autotune.md).
            # Epoch-fenced like beats: a torn-down attempt's late
            # advisory must not evict a slot from the successor world.
            _, epoch, rank, info = req
            with self._lock:
                if epoch == self._epoch:
                    self._evict_advisories[int(rank)] = dict(info)
            return ("ok",)
        raise ValueError(f"unknown elastic request {kind!r}")

    def begin_epoch(self, epoch: int) -> None:
        """Reset the liveness table for a (re)launched world attempt."""
        with self._lock:
            self._epoch = epoch
            self._last_beat = {}
            self._departed = set()
            self._evict_advisories = {}
            # age out recovery rounds two epochs back: epoch E's survivors
            # park while begin_epoch(E+1) runs, so E must survive this
            # call — anything older is a finished (or abandoned) round
            for store in (self._parked, self._recovery_plans):
                for old in [e for e in store if e < epoch - 1]:
                    del store[old]
        # drop partial ckpt streams (a kill mid-commit leaves its commit
        # unsealed forever); sealed state and the journal survive
        self.ckpt.begin_epoch(epoch)

    def evict_advisories(self) -> Dict[int, dict]:
        """This epoch's straggler eviction advisories (world rank → the
        detector's verdict info), as pushed by the coordinator."""
        with self._lock:
            return {r: dict(i) for r, i in self._evict_advisories.items()}

    def dead_ranks(self) -> List[int]:
        """Ranks whose heartbeats stopped for > miss_limit intervals."""
        deadline = self._interval_s * self._miss_limit
        now = time.monotonic()
        with self._lock:
            return sorted(r for r, t in self._last_beat.items()
                          if now - t > deadline and r not in self._departed)

    # -- recovery barrier (docs/recovery.md) ----------------------------------

    def parked(self, epoch: int) -> Dict[int, int]:
        """Survivors parked in epoch ``epoch``'s recovery barrier
        (rank → pid)."""
        with self._lock:
            return dict(self._parked.get(epoch, {}))

    def wait_parked(self, epoch: int, expected: set,
                    deadline_s: float) -> Dict[int, int]:
        """Wait (bounded) for ``expected`` ranks to park in epoch
        ``epoch``'s barrier; returns whatever parked by the deadline. The
        driver calls this AFTER the world teardown, by which point
        survivors have usually parked already — the wait only pays out
        when a survivor is slow through its own crash path."""
        deadline = time.monotonic() + max(deadline_s, 0.0)
        while True:
            got = self.parked(epoch)
            if expected.issubset(got) or time.monotonic() >= deadline:
                return got
            time.sleep(0.05)

    def parked_pids(self, epoch: int) -> set:
        """PIDs parked for ``epoch`` — the launcher's spare set during
        teardown (a parked survivor must outlive _terminate_all)."""
        with self._lock:
            return set(self._parked.get(epoch, {}).values())

    def publish_recovery(self, epoch: int,
                         assignments: Dict[int, dict]) -> None:
        """Publish epoch ``epoch``'s recovery verdicts: ranks in
        ``assignments`` get their warm re-entry env block, every other
        parked rank is told to exit. Publishing an empty dict is the
        explicit 'everyone out' verdict (cold relaunch / job over)."""
        with self._lock:
            self._recovery_plans[int(epoch)] = {
                int(r): dict(env) for r, env in assignments.items()}

    def beating_count(self) -> int:
        """Ranks currently beating in the live epoch — the MTTR probe's
        'world is back' signal."""
        with self._lock:
            return len(self._last_beat)

    def parked_epochs(self) -> List[int]:
        """Epochs with survivors still parked — the driver's shutdown path
        publishes the 'everyone out' verdict for each so no orphan waits
        out its poll deadline."""
        with self._lock:
            return sorted(e for e, ranks in self._parked.items() if ranks)

    @property
    def last_commit_meta(self) -> Optional[dict]:
        with self._lock:
            return dict(self._commit_meta) if self._commit_meta else None

    def shutdown(self) -> None:
        self._service.shutdown()


class HeartbeatReporter:
    """Worker-side daemon: one beat per interval to the elastic driver.

    Transport losses are retried quietly — a missing driver is not a
    worker failure (the parent-death watchdog owns that direction); after
    repeated reconnect failures the reporter just stops (the driver being
    gone means the whole job is ending anyway)."""

    def __init__(self, addr: Tuple[str, int], rank: int, epoch: int,
                 secret: Optional[bytes] = None,
                 interval_s: float = 1.0) -> None:
        self._addr = addr
        self._rank = rank
        self._epoch = epoch
        self._secret = secret
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="horovod-heartbeat", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        client = None
        failures = 0
        while not self._stop.wait(self._interval_s):
            try:
                if client is None:
                    client = BasicClient(self._addr, secret=self._secret,
                                         attempts=3, timeout_s=5.0)
                client.request(("beat", self._epoch, self._rank))
                failures = 0
            except Exception:  # noqa: BLE001 - reconnect next tick
                if client is not None:
                    try:
                        client.close()
                    except Exception:  # noqa: BLE001
                        pass
                    client = None
                failures += 1
                if failures == 5:
                    # NEVER give up while the process lives: a reporter
                    # that stops beating reads as a DEATH to the driver,
                    # and a transiently-busy driver (GIL-bound unpickling
                    # a large commit) must not get a healthy world torn
                    # down. If the driver is really gone, the parent
                    # watchdog ends this process anyway.
                    LOG.warning("elastic heartbeat channel flapping "
                                "(%d consecutive failures); retrying "
                                "until the driver answers", failures)
        # Clean exit: tell the driver this rank LEFT, so the in-flight
        # teardown is not misread as a death by the liveness monitor.
        try:
            if client is None:
                client = BasicClient(self._addr, secret=self._secret,
                                     attempts=1, timeout_s=2.0)
            client.request(("goodbye", self._epoch, self._rank))
        except Exception:  # noqa: BLE001 - driver may already be gone
            pass
        finally:
            if client is not None:
                try:
                    client.close()
                except Exception:  # noqa: BLE001
                    pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def reporter_from_env() -> Optional[HeartbeatReporter]:
    """Start a heartbeat reporter from the elastic driver's env block
    (``HOROVOD_ELASTIC_ADDR``/``PORT``/``EPOCH``); None for non-elastic
    jobs. Called by the worker entry (``runner._exec_fn``)."""
    port = os.environ.get(_config.HOROVOD_ELASTIC_PORT)
    if not port:
        return None
    addr = os.environ.get(_config.HOROVOD_ELASTIC_ADDR, "127.0.0.1")
    rank = int(os.environ.get(_config.HOROVOD_RANK, "0"))
    epoch = int(os.environ.get(_config.HOROVOD_ELASTIC_EPOCH, "0"))
    interval = float(
        os.environ.get(_config.HOROVOD_HEARTBEAT_INTERVAL, "") or 1.0)
    return HeartbeatReporter((addr, int(port)), rank, epoch,
                             secret=default_secret(), interval_s=interval)
