"""Deterministic fault-injection plane for the TCP control plane.

The coordinator protocol keeps every rank in lockstep (1802.05799 §3), which
makes the control-plane wire a single point of fragility: any transport
fault used to be terminal for the job. ``HOROVOD_CHAOS`` injects those
faults ON PURPOSE, deterministically, so the self-healing machinery
(``runner.network.BasicClient`` reconnect + request dedup, the controller's
reconnect window, the stall escalation) can be proven to convert every one
of them into recovery or a structured abort — never a hang
(docs/chaos.md).

Spec grammar (comma-separated clauses)::

    HOROVOD_CHAOS="drop@rank1:msg12,delay@rank0:50ms:every7,seed:7"

    clause   := kind "@" scope { ":" arg }    |  "seed" ":" INT
    kind     := drop | delay | corrupt | close | refuse    (control wire)
              | nan | flipbits                             (data plane)
              | partition                                  (island domain)
    scope    := "rank" INT   (that rank's controller client only)
              | "all"        (every rank)
              | "relaunch"   (refuse's ONLY scope: reconnect attempts,
                              any rank — refuse@rankN/all are rejected,
                              a spec must inject exactly what it says)
              | "island" INT (partition's ONLY scope: that island's
                              head<->root hop, docs/recovery.md; trigger
                              is "cycle" INT on the head's upstream-cycle
                              ordinals — its own replay domain — and the
                              second arg is the blackhole duration durS)
    trigger  := "msg" INT    (the INT-th request round trip, once)
              | "every" INT  (every INT-th request round trip)
              | "p" FLOAT    (per-request probability, seeded RNG)
    delay    := FLOAT "ms" | FLOAT "s"       (delay kind, first arg)
    refuse   := INT                          (refusals per reconnect episode)

Fault semantics, all at the frame boundary of the rank's controller client:

* ``drop``    — the response frame is consumed and discarded
                (``ConnectionClosedError``: a transport loss).
* ``delay``   — the response frame is delayed; a delay at or past the
                socket timeout raises ``socket.timeout`` WITHOUT consuming
                the frame, leaving the stale bytes buffered — the exact
                post-timeout desync hazard the client's broken-latch
                exists for.
* ``corrupt`` — one bit of the response body is flipped before HMAC
                verification (``CorruptFrameError``).
* ``close``   — the connection is closed instead of sending the request.
* ``refuse``  — the first N reconnect attempts of each reconnect episode
                fail at connect time (exercises the exponential backoff;
                N larger than the retry budget forces escalation).

Data-plane faults (docs/integrity.md), at the host-side fused-buffer
boundary of the engine's allreduce execution — the ground truth the
integrity plane (grad sentry + consensus verification) is certified
against:

* ``nan``      — the rank's LOCAL input fused buffer is poisoned with a
                 NaN before the reduce (float batches only): a genuinely
                 non-finite gradient entering the collective, which the
                 sum propagates to every rank — the sentry's quarry.
* ``flipbits`` — one low mantissa bit of the rank's RECEIVED reduced
                 buffer is flipped after the reduce: a silent, finite,
                 single-rank divergence (the host-memory SDC class) that
                 only cross-rank consensus digests can see.

Determinism: control-wire faults are keyed by (rank, request ordinal) —
LOGICAL requests on the rank's controller client; retries of a faulted
request do not advance it, so a replay under the same spec and the same
request stream injects bit-identical faults. Data-plane faults are keyed
by (rank, allreduce-batch ordinal) — batches execute in negotiated order,
identical on every rank, so the two ordinal domains are independently
replay-stable. Probabilistic triggers draw from a seeded per-domain RNG
exactly once per ordinal, so they replay too.
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.config import HOROVOD_CHAOS, HOROVOD_RANK
from ..obs import flightrec as _flightrec
from ..obs.registry import registry as _metrics

# Observability plane (docs/metrics.md): every fired fault counts here
# beside the per-injector ``events`` audit trail (events stay the replay
# proof; the counter is the live operational signal).
_CHAOS_INJECTIONS = _metrics().counter(
    "horovod_chaos_injections_total",
    "Faults injected by the HOROVOD_CHAOS plane", labels=("kind",))


class ChaosSpecError(ValueError):
    """A malformed HOROVOD_CHAOS spec must fail LOUDLY at client
    construction: a typo'd fault plan silently injecting nothing would
    certify nothing."""


# Fault kinds by injection domain: wire kinds fire on the controller
# client's request ordinals, data kinds on the engine's allreduce-batch
# ordinals (docs/integrity.md). A rule's kind decides which hooks can
# ever fire it — the two domains never cross-consume armings. Island
# kinds (docs/recovery.md) fire on an island HEAD's upstream-cycle
# ordinals — a third independent domain, consumed by
# ``ops.hierarchy.SubCoordinatorService``, never by ``ChaosInjector``.
WIRE_KINDS = ("drop", "delay", "corrupt", "close", "refuse")
DATA_KINDS = ("nan", "flipbits")
ISLAND_KINDS = ("partition",)


@dataclass
class FaultRule:
    kind: str                      # drop | delay | corrupt | close | refuse
    rank: Optional[int]            # None = any rank
    ordinal: Optional[int] = None  # msgN trigger (fires once)
    every: Optional[int] = None    # everyK trigger
    prob: Optional[float] = None   # pF trigger
    delay_s: float = 0.0           # delay kind only
    refusals: int = 0              # refuse kind: budget per episode

    def describe(self) -> str:
        if self.kind == "refuse":  # relaunch is refuse's only scope
            return f"refuse@relaunch:{self.refusals}"
        if self.kind == "partition":  # island scope, cycle trigger
            return (f"partition@island{self.rank}:cycle{self.ordinal}"
                    f":dur{self.delay_s:g}s")
        scope = "all" if self.rank is None else f"rank{self.rank}"
        trig = (f"msg{self.ordinal}" if self.ordinal is not None
                else f"every{self.every}" if self.every is not None
                else f"p{self.prob}")
        return f"{self.kind}@{scope}:{trig}"


@dataclass
class ChaosPlan:
    rules: List[FaultRule] = field(default_factory=list)
    seed: int = 0
    spec: str = ""


def _parse_trigger(rule: FaultRule, tok: str, clause: str) -> None:
    if tok.startswith("msg"):
        rule.ordinal = int(tok[3:])
        if rule.ordinal < 1:
            raise ChaosSpecError(f"msg ordinal must be >= 1 in {clause!r}")
    elif tok.startswith("every"):
        rule.every = int(tok[5:])
        if rule.every < 1:
            raise ChaosSpecError(f"every period must be >= 1 in {clause!r}")
    elif tok.startswith("p"):
        rule.prob = float(tok[1:])
        if not 0.0 <= rule.prob <= 1.0:
            raise ChaosSpecError(f"probability out of [0,1] in {clause!r}")
    else:
        raise ChaosSpecError(
            f"unknown trigger {tok!r} in {clause!r} (msgN/everyK/pF)")


def parse_chaos_spec(spec: str) -> ChaosPlan:
    """Parse a ``HOROVOD_CHAOS`` spec string; raises ``ChaosSpecError``
    on any malformed clause."""
    plan = ChaosPlan(spec=spec)
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed:"):
            try:
                plan.seed = int(clause[5:])
            except ValueError as exc:
                raise ChaosSpecError(f"bad seed in {clause!r}") from exc
            continue
        if "@" not in clause:
            raise ChaosSpecError(
                f"chaos clause {clause!r} is not kind@scope[:args]")
        kind, rest = clause.split("@", 1)
        toks = rest.split(":")
        scope, args = toks[0], toks[1:]
        if kind not in WIRE_KINDS + DATA_KINDS + ISLAND_KINDS:
            raise ChaosSpecError(f"unknown fault kind {kind!r} in {clause!r}")
        rule = FaultRule(kind=kind, rank=None)
        if kind == "partition":
            # partition@islandN:cycleK:durS (docs/recovery.md): island is
            # partition's ONLY scope — it blackholes one island<->root
            # hop, so rank/all scopes would promise something the fault
            # cannot deliver. The rule's ``rank`` field carries the
            # ISLAND id and its ordinal the head's upstream-cycle count.
            if not scope.startswith("island"):
                raise ChaosSpecError(
                    f"partition scope must be 'islandN' in {clause!r}")
            try:
                rule.rank = int(scope[len("island"):])
            except ValueError as exc:
                raise ChaosSpecError(f"bad island in {clause!r}") from exc
            if len(args) != 2:
                raise ChaosSpecError(
                    f"partition takes cycleK:durS in {clause!r}")
            trig, dur = args
            if not trig.startswith("cycle"):
                raise ChaosSpecError(
                    f"partition trigger must be 'cycleK' in {clause!r}")
            try:
                rule.ordinal = int(trig[len("cycle"):])
            except ValueError as exc:
                raise ChaosSpecError(f"bad cycle in {clause!r}") from exc
            if rule.ordinal < 0:
                raise ChaosSpecError(
                    f"partition cycle must be >= 0 in {clause!r}")
            if not dur.startswith("dur"):
                raise ChaosSpecError(
                    f"partition duration must be 'durS' in {clause!r}")
            dur = dur[len("dur"):]
            try:
                if dur.endswith("ms"):
                    rule.delay_s = float(dur[:-2]) / 1000.0
                elif dur.endswith("s"):
                    rule.delay_s = float(dur[:-1])
                else:
                    raise ChaosSpecError(
                        f"partition duration needs ms/s suffix in "
                        f"{clause!r}")
            except ValueError as exc:
                raise ChaosSpecError(
                    f"bad duration in {clause!r}") from exc
            if rule.delay_s <= 0:
                raise ChaosSpecError(
                    f"partition duration must be > 0 in {clause!r}")
            plan.rules.append(rule)
            continue
        if kind == "refuse":
            # relaunch is refuse's ONLY scope: a rank/all-scoped refuse
            # would parse as if it meant something narrower than it does
            # (refusals hit whichever rank reconnects), and a spec must
            # inject exactly what it says
            if scope != "relaunch":
                raise ChaosSpecError(
                    f"refuse scope must be 'relaunch' in {clause!r}")
        elif scope.startswith("rank"):
            try:
                rule.rank = int(scope[4:])
            except ValueError as exc:
                raise ChaosSpecError(f"bad rank in {clause!r}") from exc
        elif scope == "all":
            pass
        else:
            raise ChaosSpecError(
                f"unknown scope {scope!r} in {clause!r} "
                f"(rankN / all / relaunch-for-refuse)")
        try:
            if kind == "refuse":
                if len(args) != 1:
                    raise ChaosSpecError(
                        f"refuse takes exactly one count arg in {clause!r}")
                rule.refusals = int(args[0])
                if rule.refusals < 1:
                    raise ChaosSpecError(
                        f"refuse count must be >= 1 in {clause!r}")
            elif kind == "delay":
                if not args:
                    raise ChaosSpecError(
                        f"delay needs a duration in {clause!r}")
                dur = args[0]
                if dur.endswith("ms"):
                    rule.delay_s = float(dur[:-2]) / 1000.0
                elif dur.endswith("s"):
                    rule.delay_s = float(dur[:-1])
                else:
                    raise ChaosSpecError(
                        f"delay duration needs ms/s suffix in {clause!r}")
                if len(args) > 2:
                    raise ChaosSpecError(f"too many args in {clause!r}")
                _parse_trigger(rule, args[1] if len(args) > 1 else "every1",
                               clause)
            else:  # drop | corrupt | close | nan | flipbits
                if len(args) != 1:
                    raise ChaosSpecError(
                        f"{kind} takes exactly one trigger arg in {clause!r}")
                _parse_trigger(rule, args[0], clause)
        except ChaosSpecError:
            raise
        except ValueError as exc:
            raise ChaosSpecError(f"bad numeric arg in {clause!r}") from exc
        plan.rules.append(rule)
    return plan


class ChaosInjector:
    """Per-client fault injector; installed on a ``BasicClient``'s wire.

    Hook protocol (all called by ``runner.network`` with the client lock
    held, so no cross-thread state races for a given client):

    * ``begin_request()``   — once per LOGICAL request; advances the
      ordinal and arms this ordinal's faults (retries re-use the arming).
    * ``on_connect(reconnecting)`` / ``on_connected()`` — refuse faults.
    * ``on_send(sock)``     — close faults, before the request frame.
    * ``on_recv_begin(sock)``       — delay faults, before the header read.
    * ``on_recv_frame(body) -> body`` — drop / corrupt faults, after the
      body read and before HMAC verification.

    Data-plane hooks (called by ``ops.engine`` at the host-side
    fused-buffer boundary, single engine-loop thread — ordinals count
    ALLREDUCE batches in negotiated execution order):

    * ``begin_batch()`` — once per allreduce batch; advances the data
      ordinal and arms this ordinal's data faults.
    * ``on_reduce_input(buf) -> buf``  — nan faults, the local input
      buffer before the reduce (returns a poisoned COPY; the caller's
      array is never mutated).
    * ``on_reduce_output(buf) -> buf`` — flipbits faults, the received
      reduced buffer after the reduce.

    ``events`` records every fired fault as ``(kind, ordinal)`` — the
    proof, in tests and the dryrun certification, that the plan actually
    executed (wire kinds carry the request ordinal, data kinds the batch
    ordinal; the kind disambiguates)."""

    def __init__(self, plan: ChaosPlan, rank: int) -> None:
        self.plan = plan
        self.rank = rank
        self.ordinal = 0
        self.data_ordinal = 0
        self.events: List[Tuple[str, int]] = []
        # partition rules live in the island domain: their ``rank`` field
        # is an ISLAND id, so the per-rank filter must never adopt them
        self._rules = [r for r in plan.rules
                       if r.kind not in ISLAND_KINDS
                       and (r.rank is None or r.rank == rank)]
        self._rng = random.Random(plan.seed ^ (rank + 1) * 0x9E3779B1)
        # independent draw stream per domain: adding a data clause must
        # not shift the wire clauses' probabilistic replay (and vice
        # versa)
        self._data_rng = random.Random(plan.seed ^ (rank + 1) * 0x85EBCA6B)
        self._armed: dict = {}
        self._armed_data: dict = {}
        self._fired_once: set = set()
        self._episode_refusals: dict = {}

    def has_data_rules(self) -> bool:
        """Whether any clause targets the data plane at this rank — the
        engine only threads the batch hooks through when one does."""
        return any(r.kind in DATA_KINDS for r in self._rules)

    def _fire(self, kind: str) -> Optional[FaultRule]:
        """Consume this ordinal's armed fault of ``kind``, if any."""
        armed = self._armed_data if kind in DATA_KINDS else self._armed
        ordinal = self.data_ordinal if kind in DATA_KINDS else self.ordinal
        rule = armed.pop(kind, None)
        if rule is not None:
            self.events.append((kind, ordinal))
            _CHAOS_INJECTIONS.labels(kind=kind).inc()
            # flight recorder (docs/blackbox.md): the injected rank is
            # the one whose stream RECORDS the injection — the incident
            # classifier's attribution source for data-plane faults
            _flightrec.record(_flightrec.EV_CHAOS, ordinal, detail=kind)
        return rule

    @staticmethod
    def _arm(rules, armed: dict, ordinal: int, rng, fired_once: set,
             kinds: tuple) -> None:
        for rule in rules:
            if rule.kind == "refuse" or rule.kind not in kinds:
                continue  # refuse is connection-scoped, not ordinal-scoped
            if rule.ordinal is not None:
                hit = (rule.ordinal == ordinal
                       and id(rule) not in fired_once)
                if hit:
                    fired_once.add(id(rule))
            elif rule.every is not None:
                hit = ordinal % rule.every == 0
            else:
                # exactly one draw per (rule, ordinal): replay-stable
                hit = rng.random() < (rule.prob or 0.0)
            if hit:
                # one fault per kind per ordinal; first clause wins
                armed.setdefault(rule.kind, rule)

    # -- lifecycle hooks ------------------------------------------------------

    def begin_request(self) -> None:
        self.ordinal += 1
        self._armed = {}
        self._arm(self._rules, self._armed, self.ordinal, self._rng,
                  self._fired_once, WIRE_KINDS)

    def on_connect(self, reconnecting: bool) -> None:
        if not reconnecting:
            return  # the initial connect has its own retry machinery
        for rule in self._rules:
            if rule.kind != "refuse":
                continue
            used = self._episode_refusals.get(id(rule), 0)
            if used < rule.refusals:
                self._episode_refusals[id(rule)] = used + 1
                self.events.append(("refuse", self.ordinal))
                _CHAOS_INJECTIONS.labels(kind="refuse").inc()
                _flightrec.record(_flightrec.EV_CHAOS, self.ordinal,
                                  detail="refuse")
                raise ConnectionRefusedError(
                    f"chaos: reconnect refused ({rule.describe()}, "
                    f"refusal {used + 1}/{rule.refusals})")

    def on_connected(self) -> None:
        self._episode_refusals.clear()  # next episode gets a fresh budget

    def on_send(self, sock: socket.socket) -> None:
        rule = self._fire("close")
        if rule is None:
            return
        try:
            sock.close()  # the peer sees a real EOF, not just our error
        except OSError:
            pass
        raise OSError(f"chaos: connection closed before send "
                      f"({rule.describe()} at msg {self.ordinal})")

    def on_recv_begin(self, sock: socket.socket) -> None:
        rule = self._fire("delay")
        if rule is None:
            return
        timeout = sock.gettimeout()
        if timeout is not None and rule.delay_s >= timeout:
            # the frame stays BUFFERED: exactly the stale-response hazard
            # the client's broken-latch must defuse
            raise socket.timeout(
                f"chaos: frame delayed {rule.delay_s:.3f}s past the "
                f"{timeout:.3f}s socket timeout ({rule.describe()})")
        time.sleep(rule.delay_s)

    def on_recv_frame(self, body: bytes) -> bytes:
        # drop preempts corrupt on a shared ordinal: a dropped frame never
        # reaches HMAC verification, so firing corrupt first would record
        # an event (and consume a msgN rule) for a fault that never ran —
        # events must stay the proof the plan actually executed
        rule = self._fire("drop")
        if rule is not None:
            from ..runner.network import ConnectionClosedError

            raise ConnectionClosedError(
                f"chaos: dropped response frame ({rule.describe()} at "
                f"msg {self.ordinal})")
        rule = self._fire("corrupt")
        if rule is not None:
            body = (bytes([body[0] ^ 0x01]) + body[1:]) if body else b"\x00"
        return body

    # -- data-plane hooks (docs/integrity.md) ---------------------------------

    def begin_batch(self) -> None:
        """Once per allreduce batch on the engine loop; arms this batch
        ordinal's data faults."""
        self.data_ordinal += 1
        self._armed_data = {}
        self._arm(self._rules, self._armed_data, self.data_ordinal,
                  self._data_rng, self._fired_once, DATA_KINDS)

    def on_reduce_input(self, buf):
        """nan fault: poison element 0 of the LOCAL input buffer before
        the reduce. Float batches only — a NaN cannot enter an integer
        wire, and firing an event for an injection that could not happen
        would break the events-are-proof contract (the armed rule simply
        lapses at the next batch)."""
        import numpy as np

        if "nan" not in self._armed_data or \
                not np.issubdtype(buf.dtype, np.floating):
            return buf
        self._fire("nan")
        poisoned = np.array(buf, copy=True)
        poisoned.reshape(-1)[0] = np.nan
        return poisoned

    def on_sparse_indices(self, idx):
        """flipbits fault, sparse wire variant: flip the lowest bit of
        the first RECEIVED gathered index. The scatter-decode clips the
        corrupt index into range, so the dropped/duplicated mass lands
        in the wrong row on the armed rank only — exactly the silent
        decode divergence consensus (which digests the decoded DENSE
        result) must catch and attribute. Same arming kind as the dense
        cell: one grammar, two wire shapes."""
        rule = self._fire("flipbits")
        if rule is None:
            return idx
        import numpy as np

        out = np.array(idx, copy=True)
        if out.size:
            out.reshape(-1)[0] ^= 1
        return out

    def on_reduce_output(self, buf):
        """flipbits fault: flip the lowest bit of the first byte of the
        RECEIVED reduced buffer — for little-endian floats a low mantissa
        bit, so the value stays finite and the divergence is exactly the
        silent kind only consensus digests can see."""
        rule = self._fire("flipbits")
        if rule is None:
            return buf
        import numpy as np

        raw = bytearray(buf.tobytes())
        if raw:
            raw[0] ^= 0x01
        # copy: frombuffer views are read-only, and the engine's callers
        # get writable results by contract (see _run_allreduce)
        return np.frombuffer(bytes(raw),
                             dtype=buf.dtype).reshape(buf.shape).copy()


def partition_for_island(island: int,
                         env: str = HOROVOD_CHAOS
                         ) -> Optional[Tuple[int, float]]:
    """The (cycle, duration_s) of the first partition clause targeting
    ``island`` in the process's chaos spec, or None. Consumed by the
    island head's sub-coordinator (docs/recovery.md) — the island
    domain's faults never route through ``ChaosInjector``."""
    import os

    spec = os.environ.get(env, "")
    if not spec:
        return None
    for rule in parse_chaos_spec(spec).rules:
        if rule.kind == "partition" and rule.rank == int(island):
            return (int(rule.ordinal or 0), float(rule.delay_s))
    return None


def note_injection(kind: str, detail: str = "", ordinal: int = 0) -> None:
    """Record a fault fired OUTSIDE a ``ChaosInjector`` (the island
    domain) on the same counter + flight-recorder trail, so the replay
    proof and the operational signal stay unified across domains."""
    _CHAOS_INJECTIONS.labels(kind=kind).inc()
    _flightrec.record(_flightrec.EV_CHAOS, ordinal, detail=detail or kind)


def injector_from_env(rank: Optional[int] = None,
                      env: str = HOROVOD_CHAOS) -> Optional[ChaosInjector]:
    """Build the injector for this process's ``HOROVOD_CHAOS`` spec, or
    None when unset. ``rank`` defaults to ``HOROVOD_RANK``; rank-scoped
    clauses not matching it are filtered out (the injector still exists,
    carrying 'all'/'relaunch' clauses).

    ``env`` names the spec variable: the serving plane's wire reads its
    faults from ``HOROVOD_SERVING_CHAOS`` (docs/serving.md) so each wire
    owns an independent ordinal domain — injecting serving faults must
    never perturb the cycle channel's replay determinism, and vice
    versa."""
    import os

    spec = os.environ.get(env, "")
    if not spec:
        return None
    if rank is None:
        rank = int(os.environ.get(HOROVOD_RANK, "-1"))
    return ChaosInjector(parse_chaos_spec(spec), rank)
