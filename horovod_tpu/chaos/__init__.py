"""Deterministic fault-injection plane for the TCP control plane.

The coordinator protocol keeps every rank in lockstep (1802.05799 §3), which
makes the control-plane wire a single point of fragility: any transport
fault used to be terminal for the job. ``HOROVOD_CHAOS`` injects those
faults ON PURPOSE, deterministically, so the self-healing machinery
(``runner.network.BasicClient`` reconnect + request dedup, the controller's
reconnect window, the stall escalation) can be proven to convert every one
of them into recovery or a structured abort — never a hang
(docs/chaos.md).

Spec grammar (comma-separated clauses)::

    HOROVOD_CHAOS="drop@rank1:msg12,delay@rank0:50ms:every7,seed:7"

    clause   := kind "@" scope { ":" arg }    |  "seed" ":" INT
    kind     := drop | delay | corrupt | close | refuse
    scope    := "rank" INT   (that rank's controller client only)
              | "all"        (every rank)
              | "relaunch"   (refuse's ONLY scope: reconnect attempts,
                              any rank — refuse@rankN/all are rejected,
                              a spec must inject exactly what it says)
    trigger  := "msg" INT    (the INT-th request round trip, once)
              | "every" INT  (every INT-th request round trip)
              | "p" FLOAT    (per-request probability, seeded RNG)
    delay    := FLOAT "ms" | FLOAT "s"       (delay kind, first arg)
    refuse   := INT                          (refusals per reconnect episode)

Fault semantics, all at the frame boundary of the rank's controller client:

* ``drop``    — the response frame is consumed and discarded
                (``ConnectionClosedError``: a transport loss).
* ``delay``   — the response frame is delayed; a delay at or past the
                socket timeout raises ``socket.timeout`` WITHOUT consuming
                the frame, leaving the stale bytes buffered — the exact
                post-timeout desync hazard the client's broken-latch
                exists for.
* ``corrupt`` — one bit of the response body is flipped before HMAC
                verification (``CorruptFrameError``).
* ``close``   — the connection is closed instead of sending the request.
* ``refuse``  — the first N reconnect attempts of each reconnect episode
                fail at connect time (exercises the exponential backoff;
                N larger than the retry budget forces escalation).

Determinism: faults are keyed by (rank, request ordinal). The ordinal
counts LOGICAL requests on the rank's controller client — retries of a
faulted request do not advance it, so a replay under the same spec and the
same request stream injects bit-identical faults. Probabilistic triggers
draw from ``random.Random(seed ^ rank)`` exactly once per ordinal, so they
replay too.
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.config import HOROVOD_CHAOS
from ..obs.registry import registry as _metrics

# Observability plane (docs/metrics.md): every fired fault counts here
# beside the per-injector ``events`` audit trail (events stay the replay
# proof; the counter is the live operational signal).
_CHAOS_INJECTIONS = _metrics().counter(
    "horovod_chaos_injections_total",
    "Faults injected by the HOROVOD_CHAOS plane", labels=("kind",))


class ChaosSpecError(ValueError):
    """A malformed HOROVOD_CHAOS spec must fail LOUDLY at client
    construction: a typo'd fault plan silently injecting nothing would
    certify nothing."""


@dataclass
class FaultRule:
    kind: str                      # drop | delay | corrupt | close | refuse
    rank: Optional[int]            # None = any rank
    ordinal: Optional[int] = None  # msgN trigger (fires once)
    every: Optional[int] = None    # everyK trigger
    prob: Optional[float] = None   # pF trigger
    delay_s: float = 0.0           # delay kind only
    refusals: int = 0              # refuse kind: budget per episode

    def describe(self) -> str:
        if self.kind == "refuse":  # relaunch is refuse's only scope
            return f"refuse@relaunch:{self.refusals}"
        scope = "all" if self.rank is None else f"rank{self.rank}"
        trig = (f"msg{self.ordinal}" if self.ordinal is not None
                else f"every{self.every}" if self.every is not None
                else f"p{self.prob}")
        return f"{self.kind}@{scope}:{trig}"


@dataclass
class ChaosPlan:
    rules: List[FaultRule] = field(default_factory=list)
    seed: int = 0
    spec: str = ""


def _parse_trigger(rule: FaultRule, tok: str, clause: str) -> None:
    if tok.startswith("msg"):
        rule.ordinal = int(tok[3:])
        if rule.ordinal < 1:
            raise ChaosSpecError(f"msg ordinal must be >= 1 in {clause!r}")
    elif tok.startswith("every"):
        rule.every = int(tok[5:])
        if rule.every < 1:
            raise ChaosSpecError(f"every period must be >= 1 in {clause!r}")
    elif tok.startswith("p"):
        rule.prob = float(tok[1:])
        if not 0.0 <= rule.prob <= 1.0:
            raise ChaosSpecError(f"probability out of [0,1] in {clause!r}")
    else:
        raise ChaosSpecError(
            f"unknown trigger {tok!r} in {clause!r} (msgN/everyK/pF)")


def parse_chaos_spec(spec: str) -> ChaosPlan:
    """Parse a ``HOROVOD_CHAOS`` spec string; raises ``ChaosSpecError``
    on any malformed clause."""
    plan = ChaosPlan(spec=spec)
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed:"):
            try:
                plan.seed = int(clause[5:])
            except ValueError as exc:
                raise ChaosSpecError(f"bad seed in {clause!r}") from exc
            continue
        if "@" not in clause:
            raise ChaosSpecError(
                f"chaos clause {clause!r} is not kind@scope[:args]")
        kind, rest = clause.split("@", 1)
        toks = rest.split(":")
        scope, args = toks[0], toks[1:]
        if kind not in ("drop", "delay", "corrupt", "close", "refuse"):
            raise ChaosSpecError(f"unknown fault kind {kind!r} in {clause!r}")
        rule = FaultRule(kind=kind, rank=None)
        if kind == "refuse":
            # relaunch is refuse's ONLY scope: a rank/all-scoped refuse
            # would parse as if it meant something narrower than it does
            # (refusals hit whichever rank reconnects), and a spec must
            # inject exactly what it says
            if scope != "relaunch":
                raise ChaosSpecError(
                    f"refuse scope must be 'relaunch' in {clause!r}")
        elif scope.startswith("rank"):
            try:
                rule.rank = int(scope[4:])
            except ValueError as exc:
                raise ChaosSpecError(f"bad rank in {clause!r}") from exc
        elif scope == "all":
            pass
        else:
            raise ChaosSpecError(
                f"unknown scope {scope!r} in {clause!r} "
                f"(rankN / all / relaunch-for-refuse)")
        try:
            if kind == "refuse":
                if len(args) != 1:
                    raise ChaosSpecError(
                        f"refuse takes exactly one count arg in {clause!r}")
                rule.refusals = int(args[0])
                if rule.refusals < 1:
                    raise ChaosSpecError(
                        f"refuse count must be >= 1 in {clause!r}")
            elif kind == "delay":
                if not args:
                    raise ChaosSpecError(
                        f"delay needs a duration in {clause!r}")
                dur = args[0]
                if dur.endswith("ms"):
                    rule.delay_s = float(dur[:-2]) / 1000.0
                elif dur.endswith("s"):
                    rule.delay_s = float(dur[:-1])
                else:
                    raise ChaosSpecError(
                        f"delay duration needs ms/s suffix in {clause!r}")
                if len(args) > 2:
                    raise ChaosSpecError(f"too many args in {clause!r}")
                _parse_trigger(rule, args[1] if len(args) > 1 else "every1",
                               clause)
            else:  # drop | corrupt | close
                if len(args) != 1:
                    raise ChaosSpecError(
                        f"{kind} takes exactly one trigger arg in {clause!r}")
                _parse_trigger(rule, args[0], clause)
        except ChaosSpecError:
            raise
        except ValueError as exc:
            raise ChaosSpecError(f"bad numeric arg in {clause!r}") from exc
        plan.rules.append(rule)
    return plan


class ChaosInjector:
    """Per-client fault injector; installed on a ``BasicClient``'s wire.

    Hook protocol (all called by ``runner.network`` with the client lock
    held, so no cross-thread state races for a given client):

    * ``begin_request()``   — once per LOGICAL request; advances the
      ordinal and arms this ordinal's faults (retries re-use the arming).
    * ``on_connect(reconnecting)`` / ``on_connected()`` — refuse faults.
    * ``on_send(sock)``     — close faults, before the request frame.
    * ``on_recv_begin(sock)``       — delay faults, before the header read.
    * ``on_recv_frame(body) -> body`` — drop / corrupt faults, after the
      body read and before HMAC verification.

    ``events`` records every fired fault as ``(kind, ordinal)`` — the
    proof, in tests and the dryrun certification, that the plan actually
    executed."""

    def __init__(self, plan: ChaosPlan, rank: int) -> None:
        self.plan = plan
        self.rank = rank
        self.ordinal = 0
        self.events: List[Tuple[str, int]] = []
        self._rules = [r for r in plan.rules
                       if r.rank is None or r.rank == rank]
        self._rng = random.Random(plan.seed ^ (rank + 1) * 0x9E3779B1)
        self._armed: dict = {}
        self._fired_once: set = set()
        self._episode_refusals: dict = {}

    def _fire(self, kind: str) -> Optional[FaultRule]:
        """Consume this ordinal's armed fault of ``kind``, if any."""
        rule = self._armed.pop(kind, None)
        if rule is not None:
            self.events.append((kind, self.ordinal))
            _CHAOS_INJECTIONS.labels(kind=kind).inc()
        return rule

    # -- lifecycle hooks ------------------------------------------------------

    def begin_request(self) -> None:
        self.ordinal += 1
        self._armed = {}
        for rule in self._rules:
            if rule.kind == "refuse":
                continue  # connection-scoped, not ordinal-scoped
            if rule.ordinal is not None:
                hit = (rule.ordinal == self.ordinal
                       and id(rule) not in self._fired_once)
                if hit:
                    self._fired_once.add(id(rule))
            elif rule.every is not None:
                hit = self.ordinal % rule.every == 0
            else:
                # exactly one draw per (rule, ordinal): replay-stable
                hit = self._rng.random() < (rule.prob or 0.0)
            if hit:
                # one fault per kind per ordinal; first clause wins
                self._armed.setdefault(rule.kind, rule)

    def on_connect(self, reconnecting: bool) -> None:
        if not reconnecting:
            return  # the initial connect has its own retry machinery
        for rule in self._rules:
            if rule.kind != "refuse":
                continue
            used = self._episode_refusals.get(id(rule), 0)
            if used < rule.refusals:
                self._episode_refusals[id(rule)] = used + 1
                self.events.append(("refuse", self.ordinal))
                _CHAOS_INJECTIONS.labels(kind="refuse").inc()
                raise ConnectionRefusedError(
                    f"chaos: reconnect refused ({rule.describe()}, "
                    f"refusal {used + 1}/{rule.refusals})")

    def on_connected(self) -> None:
        self._episode_refusals.clear()  # next episode gets a fresh budget

    def on_send(self, sock: socket.socket) -> None:
        rule = self._fire("close")
        if rule is None:
            return
        try:
            sock.close()  # the peer sees a real EOF, not just our error
        except OSError:
            pass
        raise OSError(f"chaos: connection closed before send "
                      f"({rule.describe()} at msg {self.ordinal})")

    def on_recv_begin(self, sock: socket.socket) -> None:
        rule = self._fire("delay")
        if rule is None:
            return
        timeout = sock.gettimeout()
        if timeout is not None and rule.delay_s >= timeout:
            # the frame stays BUFFERED: exactly the stale-response hazard
            # the client's broken-latch must defuse
            raise socket.timeout(
                f"chaos: frame delayed {rule.delay_s:.3f}s past the "
                f"{timeout:.3f}s socket timeout ({rule.describe()})")
        time.sleep(rule.delay_s)

    def on_recv_frame(self, body: bytes) -> bytes:
        # drop preempts corrupt on a shared ordinal: a dropped frame never
        # reaches HMAC verification, so firing corrupt first would record
        # an event (and consume a msgN rule) for a fault that never ran —
        # events must stay the proof the plan actually executed
        rule = self._fire("drop")
        if rule is not None:
            from ..runner.network import ConnectionClosedError

            raise ConnectionClosedError(
                f"chaos: dropped response frame ({rule.describe()} at "
                f"msg {self.ordinal})")
        rule = self._fire("corrupt")
        if rule is not None:
            body = (bytes([body[0] ^ 0x01]) + body[1:]) if body else b"\x00"
        return body


def injector_from_env(rank: Optional[int] = None) -> Optional[ChaosInjector]:
    """Build the injector for this process's ``HOROVOD_CHAOS`` spec, or
    None when unset. ``rank`` defaults to ``HOROVOD_RANK``; rank-scoped
    clauses not matching it are filtered out (the injector still exists,
    carrying 'all'/'relaunch' clauses)."""
    import os

    spec = os.environ.get(HOROVOD_CHAOS, "")
    if not spec:
        return None
    if rank is None:
        rank = int(os.environ.get("HOROVOD_RANK", "-1"))
    return ChaosInjector(parse_chaos_spec(spec), rank)
