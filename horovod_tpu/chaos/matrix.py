"""Chaos matrix cells: drive a small world under one fault spec and
classify the outcome.

Every cell's acceptance contract (ISSUE 4 / docs/chaos.md): a 2-process
run under single-fault injection either completes with bit-exact results
(``healed``) or surfaces a structured abort within the stall-shutdown
deadline (``escalated``) — never a hang. ``tools/chaos_matrix.sh`` sweeps
the fault grid under both controller implementations
(``HOROVOD_NATIVE_CONTROLLER=0/1``) and both negotiation cores
(``HOROVOD_NATIVE_CORE=0/1``); ``tests/test_chaos.py`` drives the same
cells in-process.

Run directly::

    python -m horovod_tpu.chaos.matrix            # default single-fault grid
    python -m horovod_tpu.chaos.matrix --spec "drop@rank1:every3"
    python -m horovod_tpu.chaos.matrix --data-plane   # integrity grid
                                                      # (docs/integrity.md)
    python -m horovod_tpu.chaos.matrix --recovery     # recovery plane
                                                      # (docs/recovery.md)
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional, Tuple

# One fault of each kind, aimed at rank 1's controller client. The msgN
# ordinals land during warmup (negotiation cycles), the everyK clauses
# keep firing through the warm steady state (cache-bit cycles on the
# Python controller) — both boundaries of the acceptance matrix.
DEFAULT_SPECS = [
    "drop@rank1:msg6,drop@rank1:every9",
    "delay@rank1:40ms:every5",
    "corrupt@rank1:msg7,corrupt@rank1:every11",
    "close@rank1:msg8,refuse@relaunch:1",
]

# A fault budget no reconnect can satisfy: the rank must escalate into a
# structured abort, and its healthy peer must see RanksAbortedError.
ESCALATION_SPEC = "close@rank1:msg6,refuse@relaunch:999"

# Data-plane integrity grid (docs/integrity.md): fault kind x policy.
# Every cell must resolve as healed (skip/zero neutralized the poisoned
# batch with bit-exact results elsewhere; warn surfaced it and kept
# going) or escalated (a structured NonFiniteGradError/ConsensusError
# INSIDE the deadline) — never a hang. The poisoned batch ordinal is
# pinned (msg3) so healed cells can assert exact final values.
DATA_POISON_ORDINAL = 3
DATA_GRID = [
    # (chaos spec, sentry policy, consensus interval, expected outcome,
    #  wire codec)
    (f"nan@rank1:msg{DATA_POISON_ORDINAL}", "skip", 0, "healed", "none"),
    (f"nan@rank1:msg{DATA_POISON_ORDINAL}", "zero", 0, "healed", "none"),
    (f"nan@rank1:msg{DATA_POISON_ORDINAL}", "warn", 0, "healed", "none"),
    (f"nan@rank1:msg{DATA_POISON_ORDINAL}", "abort", 0, "escalated",
     "none"),
    (f"flipbits@rank1:msg{DATA_POISON_ORDINAL}", "off", 1, "escalated",
     "none"),
    # Sparse wire cell (docs/compression.md): the same flipbits arming
    # lands on the gathered INDEX stream of the top-k codec — the
    # armed rank's scatter-decode puts mass in the wrong row, and
    # consensus (which digests the decoded DENSE result) must catch the
    # divergence and name the injected rank.
    (f"flipbits@rank1:msg{DATA_POISON_ORDINAL}", "off", 1, "escalated",
     "topk"),
]


# Serving-plane grid (docs/serving.md): faults aimed at the serving RPC
# wire (HOROVOD_SERVING_CHAOS — its own ordinal domain, so the cycle
# channel's replay stays untouched) plus the kill-mid-batch cell
# (HOROVOD_SERVING_FAULT through the elastic driver). Heal cells must
# resolve every request 200-bit-exact with ZERO relaunches (the dedup
# wire heals drops/delays/closes); the kill cell must relaunch and leave
# every request either 200-bit-exact or a structured 503 carrying the
# relaunch epoch — never a hang.
SERVING_GRID = [
    ("drop@rank1:msg3,drop@rank1:every7", "", "healed"),
    ("delay@rank1:40ms:every3", "", "healed"),
    ("close@rank1:msg4", "", "healed"),
    ("", "kill@rank1:batch2@epoch0", "recovered"),
]


# Checkpoint-plane grid (docs/checkpoint.md): the async commit pipeline
# under the two kill shapes that matter to sealing. Cells are
# (HOROVOD_ELASTIC_FAULT, HOROVOD_CKPT_FAULT, expected outcome), all
# with HOROVOD_CKPT_ASYNC=1 and a chunk size small enough that every
# commit streams multiple chunks. The contract: a kill ANYWHERE in the
# commit path (before the snapshot, or between two chunks of the
# stream) relaunches and restores the last SEALED commit bit-exactly —
# never a torn/partial one — and a clean run never relaunches at all.
CHECKPOINT_GRID = [
    ("", "", "clean"),
    # rank 1 dies right before commit 2: commit 1 is sealed, restore
    # adopts it
    ("1:2", "", "recovered"),
    # rank 0's streaming thread dies between chunk 0 and chunk 1 of
    # commit 2: the partial stream must never seal; restore adopts
    # sealed commit 1
    ("", "0:2:1", "recovered"),
]


# Hierarchical negotiation tree grid (docs/hierarchy.md): the same
# acceptance contract as the flat grid, but the faults land on TREE
# links. Cells are (chaos spec, np, HOROVOD_HIERARCHY, kill_rank,
# expected outcome). The heal cells aim drop/delay/close at rank 1's
# controller client — which in a tree world is the MEMBER-to-
# SUB-COORDINATOR link — and must heal bit-exactly through the PR 4
# reconnect/dedup envelopes, with the tree demonstrably live (the cell
# asserts the hier gauge, so a silent flat degrade cannot certify).
# The kill cell hard-kills rank 2 — island 1's sub-coordinator in a
# 4-rank islands:2 world — and must escalate in-deadline as a
# structured abort naming the island's member ranks.
HIERARCHY_GRID = [
    ("drop@rank1:msg6,drop@rank1:every9", 2, "islands:2", None, "healed"),
    ("delay@rank1:40ms:every5", 2, "islands:2", None, "healed"),
    ("close@rank1:msg8,refuse@relaunch:1", 2, "islands:2", None,
     "healed"),
    ("", 4, "islands:2", 2, "escalated"),
]


# Recovery-plane grid (docs/recovery.md): every cell is a 4-rank elastic
# world on the async checkpoint pipeline, and every cell must land in
# exactly ONE bucket — ``healed`` (bit-exact, zero relaunches),
# ``recovered`` (warm relaunch from the last SEALED epoch with survivor
# PIDs unchanged, classified verdict like ``recovered@epoch1
# survivors=3/4``), or a structured failure label — never a hang.
#   kill-rank-warm      rank 1 dies before commit 2; the other three park
#                       in the recovery barrier and re-enter warm
#   partition-heal      island 1's uplink blackholed for LESS than the
#                       root's reconnect window: dedup heals bit-exact,
#                       zero relaunches
#   partition-escalate  the same blackhole held PAST the window: the root
#                       aborts the island in-deadline, the world
#                       warm-recovers (nobody died, so island 0's
#                       processes — at least — keep their PIDs)
#   head-kill           island 1's HEAD dies; warm recovery with the
#                       island rejoining under the driver's planned
#                       successor (HOROVOD_ISLAND_HEADS) and one merged
#                       blackbox verdict for the epoch-0 abort
#   succession-live     headstop drill on island 1's primary: members
#                       fail over to the standby MID-JOB — bit-exact,
#                       zero relaunches, the successions counter proves
#                       the standby served
RECOVERY_GRID = [
    ("kill-rank-warm", "recovered"),
    ("partition-heal", "healed"),
    ("partition-escalate", "recovered"),
    ("head-kill", "recovered"),
    ("succession-live", "healed"),
]


def _matrix_fn(steps: int, expect_escalation: bool):
    """Per-rank body (shipped by value through runner.run's driver)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    try:
        for step in range(steps):
            for i in range(2):
                out = hvd.allreduce(
                    np.full((16,), float(rank + i + 1), np.float32),
                    average=False, name=f"chaos.m.{i}")
                # bit-exact-or-escalate: small integers sum exactly in
                # float32, so equality IS the fault-free result
                np.testing.assert_array_equal(
                    np.asarray(out),
                    float(sum(r + i + 1 for r in range(size))))
    except hvd.RanksAbortedError as exc:
        # timeliness is judged by the DRIVER (run_cell's deadline_s →
        # late-escalation), not here — a worker-side assert would turn a
        # slow escalation into an AssertionError and hide the real label
        assert expect_escalation, f"unexpected escalation: {exc}"
        return {"rank": rank, "outcome": "escalated",
                "aborted_ranks": exc.ranks}
    except hvd.HorovodInternalError as exc:
        # The faulted rank itself fails with the transport cause; only
        # under an escalation run is that acceptable.
        assert expect_escalation, f"unexpected world failure: {exc}"
        return {"rank": rank, "outcome": "escalated", "aborted_ranks": []}
    engine = get_engine()
    client = getattr(engine, "_client", None)
    chaos = getattr(client, "_chaos", None)
    events = list(chaos.events) if chaos is not None else []
    stats = engine.cache_stats()
    reconnects = getattr(getattr(client, "_client", None), "reconnects", 0)
    hvd.shutdown()
    return {"rank": rank, "outcome": "healed", "events": events,
            "reconnects": reconnects, "hit_cycles": stats["hit_cycles"]}


def _data_matrix_fn(steps: int, policy: str, poison_ordinal: int,
                    expect_escalation: bool, codec: str = "none"):
    """Per-rank body for one data-plane integrity cell (shipped by value
    through runner.run's driver): one allreduce per step with
    step-dependent values, so the driver can pin what a healed world's
    final accumulator must be bit-exactly. ``codec`` routes the batch
    through a lossy wire instead ("topk": the sparse cell) — lossy
    results carry no exactness contract, the cell's whole point is that
    consensus still digests the decoded dense result bit-identically."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops.engine import get_engine

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    comp = hvd.Compression.lookup(codec)
    w = 0.0
    try:
        for step in range(steps):
            out = hvd.allreduce(
                np.full((16,), float(rank + step + 1), np.float32),
                average=False, name="chaos.data", compression=comp)
            w += float(np.asarray(out)[0])
            if codec != "none":
                continue  # lossy wire: no bit-exactness to pin
            clean = float(sum(r + step + 1 for r in range(size)))
            if step + 1 == poison_ordinal:
                # the poisoned batch: skip/zero hand back zeros, warn
                # hands the NaN through — anything else here means the
                # fault did not fire where the spec said it would
                continue
            # bit-exact-or-escalate everywhere else: small integers sum
            # exactly in float32
            np.testing.assert_array_equal(np.asarray(out), clean)
    except hvd.NonFiniteGradError as exc:
        assert expect_escalation, f"unexpected sentry abort: {exc}"
        return {"rank": rank, "outcome": "escalated",
                "error_type": "NonFiniteGradError", "step": exc.step}
    except hvd.ConsensusError as exc:
        assert expect_escalation, f"unexpected consensus abort: {exc}"
        return {"rank": rank, "outcome": "escalated",
                "error_type": "ConsensusError",
                "consensus_ranks": exc.ranks}
    except hvd.HorovodInternalError as exc:
        assert expect_escalation, f"unexpected world failure: {exc}"
        return {"rank": rank, "outcome": "escalated",
                "error_type": type(exc).__name__, "error": str(exc)[:300]}
    stats = get_engine().integrity_stats()
    hvd.shutdown()
    return {"rank": rank, "outcome": "healed", "w": w,
            "sentry": stats["sentry"],
            "chaos_events": stats["data_chaos_events"]}


def run_data_cell(spec: str, policy: str, consensus_interval: int,
                  expect: str,
                  native_core: Optional[int] = None,
                  np_: int = 2, steps: int = 6,
                  timeout_s: float = 120.0,
                  deadline_s: float = 60.0,
                  codec: str = "none") -> Dict:
    """Run one data-plane integrity cell; classification mirrors
    ``run_cell``: healed / escalated / late-escalation / hang — plus the
    healed cells' EXACTNESS contract: under skip/zero the final
    accumulator must equal the clean world's minus the poisoned batch
    (the step it fed was a collective no-op), and the sentry's verdict
    ordinal must be identical on every rank."""
    from horovod_tpu.runner import run
    from horovod_tpu.runner.launcher import LaunchError
    from horovod_tpu.runner.run_api import WorkerFailedError, WorkerLostError

    env = {
        "HOROVOD_CHAOS": spec,
        "HOROVOD_GRAD_SENTRY": policy,
        "HOROVOD_CONSENSUS_INTERVAL_STEPS": str(consensus_interval),
        "HOROVOD_NATIVE_CONTROLLER": "0",  # verdict RPC + digest wire
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        "HOROVOD_STALL_WARNING_TIME": "2",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "4",
    }
    if native_core is not None:
        env["HOROVOD_NATIVE_CORE"] = str(native_core)
    expect_escalation = expect == "escalated"
    t0 = time.monotonic()
    import os

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run(_data_matrix_fn,
                      args=(steps, policy, DATA_POISON_ORDINAL,
                            expect_escalation, codec),
                      np=np_, timeout_s=timeout_s, start_timeout_s=120.0)
        if any(r.get("outcome") == "escalated" for r in results):
            cell = {"outcome": "escalated", "results": results}
        else:
            cell = {"outcome": "healed", "results": results}
            size = np_
            clean = sum(sum(r + s + 1 for r in range(size))
                        for s in range(steps))
            poisoned_contrib = sum(
                r + DATA_POISON_ORDINAL for r in range(size))
            if codec != "none":
                # lossy wire: there is no bit-exactness contract to
                # audit; a healed classification stands on its own
                pass
            elif "nan@" not in spec:
                # no sentry-visible poison: full-exactness contract. A
                # flipbits cell WITHOUT consensus lands here too and
                # honestly classifies wrong-results — that silent
                # corruption is exactly what consensus exists to catch.
                for r in results:
                    if r["w"] != clean:
                        cell["outcome"] = "wrong-results"
                        cell["error"] = (
                            f"rank {r['rank']} w={r['w']} != {clean}")
            elif policy in ("skip", "zero"):
                want = clean - poisoned_contrib
                for r in results:
                    if r["w"] != want:
                        cell["outcome"] = "wrong-results"
                        cell["error"] = (
                            f"rank {r['rank']} w={r['w']} != {want}")
                # the verdicts must be collective: identical action on
                # the identical batch ordinal on EVERY rank
                trips = {tuple(map(tuple, r["sentry"]["trips"]))
                         for r in results}
                if len(trips) != 1 or not trips or \
                        next(iter(trips)) != (
                            (DATA_POISON_ORDINAL, policy, "nan"),):
                    cell["outcome"] = "desynced-verdict"
                    cell["error"] = f"trips diverged: {trips}"
    except WorkerFailedError as exc:
        cell = {"outcome": _classify_worker_failure(exc),
                "error": str(exc)[:500]}
    except (WorkerLostError, LaunchError) as exc:
        cell = {"outcome": "escalated", "error": str(exc)[:500]}
    except TimeoutError as exc:
        cell = {"outcome": "hang", "error": str(exc)[:500]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    cell["spec"] = spec
    cell["policy"] = policy
    cell["consensus_interval"] = consensus_interval
    cell["codec"] = codec
    cell["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell["outcome"] == "escalated" and cell["elapsed_s"] > deadline_s:
        cell["outcome"] = "late-escalation"
    cell["native_core"] = native_core
    return cell


def _classify_worker_failure(exc) -> str:
    """``escalated`` only when every failed rank's structured record says
    the WORLD failed under it; any rank whose record pins the failure on
    its own code (``world_fault`` false — e.g. the bit-exact assertion)
    is a ``worker-failure``, an outcome no cell ever accepts."""
    records = getattr(exc, "records", None) or {}
    if any(not rec.get("world_fault") for rec in records.values()):
        return "worker-failure"
    return "escalated"


def _serving_world_fn():
    """Per-rank body for one serving cell (shipped by value through the
    elastic driver): a real hvd world (so the negotiation-core sweep
    means something and the serving RPC demonstrably rides its own
    connection, never the cycle channel) running the serving loop on a
    small integer-valued matmul — integer products and sums are exact in
    float32, so bit-exact is the fault-free contract."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.serving.worker import serve_worker

    hvd.init()
    warm = hvd.allreduce(np.ones(8, np.float32), average=False,
                         name="serving.warm")
    weights = (np.arange(64, dtype=np.float32).reshape(8, 8) % 5) - 2
    try:
        stats = serve_worker({"demo": lambda x: x @ weights + 1.0},
                             jit=False)
    finally:
        try:
            hvd.shutdown()
        except Exception:  # noqa: BLE001 - a killed peer's world cannot
            pass  # negotiate shutdown; the abort already attributed it
    stats["warm"] = float(np.asarray(warm)[0])
    return stats


def serving_expected(x):
    """Driver-side twin of the cell model (what a 200 must equal)."""
    import numpy as np

    weights = (np.arange(64, dtype=np.float32).reshape(8, 8) % 5) - 2
    return x @ weights + 1.0


def run_serving_cell(spec: str, fault: str, expect: str,
                     native_core: Optional[int] = None,
                     np_: int = 2, requests: int = 10,
                     timeout_s: float = 240.0,
                     deadline_s: float = 120.0) -> Dict:
    """Run one serving cell: a 2-proc elastic serving world under one
    fault, with a closed-loop client stream against the gateway.
    Outcomes: ``healed`` (every request 200 bit-exact, zero relaunches),
    ``recovered`` (the kill relaunched, every request 200-exact or a
    structured 503 carrying an epoch), ``escalated`` (a heal cell
    relaunched), ``wrong-results``, ``hang``."""
    import json
    import os
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    from horovod_tpu.elastic import run_elastic
    from horovod_tpu.serving import ServingPlane

    env = {
        "HOROVOD_SERVING_CHAOS": spec,
        "HOROVOD_SERVING_FAULT": fault,
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        "HOROVOD_RECONNECT_ATTEMPTS": "4",
        "HOROVOD_RECONNECT_BACKOFF_S": "0.05",
        "HOROVOD_RECONNECT_WINDOW_S": "2",
        "HOROVOD_STALL_WARNING_TIME": "2",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "4",
    }
    if native_core is not None:
        env["HOROVOD_NATIVE_CORE"] = str(native_core)
    t0 = time.monotonic()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    plane = ServingPlane(gateway_port=0, batch_max=4, slo_ms=5000.0,
                         deadline_ms=30000.0, reconnect_window_s=2.0)
    box: Dict[str, object] = {}

    def _driver() -> None:
        try:
            box["results"] = run_elastic(
                _serving_world_fn, np=np_, min_np=np_, max_restarts=2,
                backoff_s=0.2, timeout_s=timeout_s,
                start_timeout_s=120.0, serving_plane=plane,
                env_extra=dict(env))
        except BaseException as exc:  # noqa: BLE001 - classified below
            box["error"] = f"{type(exc).__name__}: {exc}"

    driver = threading.Thread(target=_driver, daemon=True)
    driver.start()
    outcomes: List[Tuple] = []
    try:
        arm_deadline = time.monotonic() + 90.0
        while not plane.stats()["armed"]:
            if time.monotonic() > arm_deadline or "error" in box:
                cell = {"outcome": "hang",
                        "error": str(box.get(
                            "error", "serving world never armed"))}
                return _finish_serving_cell(cell, spec, fault,
                                            native_core, t0, deadline_s)
            time.sleep(0.1)
        url = f"http://127.0.0.1:{plane.gateway_port}/v1/infer"
        lock = threading.Lock()

        def _client(i: int) -> None:
            x = np.full(8, float(i % 7), np.float32)
            req = urllib.request.Request(
                url,
                data=json.dumps({"name": "demo",
                                 "inputs": x.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                resp = urllib.request.urlopen(req, timeout=45)
                out = np.asarray(json.loads(resp.read())["outputs"],
                                 np.float32)
                exact = bool(np.array_equal(out, serving_expected(x)))
                record = (i, 200, exact)
            except urllib.error.HTTPError as exc:
                body = json.loads(exc.read() or b"{}")
                record = (i, exc.code, body.get("epoch"))
            except Exception as exc:  # noqa: BLE001 - a hang marker
                record = (i, "exc", f"{type(exc).__name__}: {exc}")
            with lock:
                outcomes.append(record)

        clients = [threading.Thread(target=_client, args=(i,))
                   for i in range(requests)]
        for thread in clients:
            thread.start()
            time.sleep(0.15)
        for thread in clients:
            thread.join(timeout=60.0)
        if any(thread.is_alive() for thread in clients):
            cell = {"outcome": "hang",
                    "error": "client requests never resolved",
                    "responses": sorted(outcomes)}
            return _finish_serving_cell(cell, spec, fault, native_core,
                                        t0, deadline_s)
        epoch = plane.stats()["epoch"]
    finally:
        plane.stop()
        driver.join(timeout=60.0)
        plane.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if driver.is_alive():
        cell = {"outcome": "hang", "error": "elastic driver never returned"}
    elif any(r[1] == "exc" for r in outcomes):
        cell = {"outcome": "hang",
                "error": f"unresolved requests: "
                         f"{[r for r in outcomes if r[1] == 'exc']}"}
    elif any(r[1] == 200 and r[2] is not True for r in outcomes):
        cell = {"outcome": "wrong-results",
                "error": f"inexact 200s: "
                         f"{[r for r in outcomes if r[1] == 200 and r[2] is not True]}"}
    elif fault:
        structured = all(r[1] == 200 or (r[1] == 503 and r[2] is not None)
                         for r in outcomes)
        cell = {"outcome": "recovered" if epoch >= 1 and structured
                else "escalated",
                "responses": sorted(outcomes)}
    else:
        all_served = all(r[1] == 200 for r in outcomes)
        cell = {"outcome": "healed" if all_served and epoch == 0
                else "escalated",
                "responses": sorted(outcomes)}
    if "error" in box and cell["outcome"] in ("healed", "recovered"):
        cell = {"outcome": "escalated", "error": str(box["error"])}
    return _finish_serving_cell(cell, spec, fault, native_core, t0,
                                deadline_s)


def _finish_serving_cell(cell: Dict, spec: str, fault: str,
                         native_core: Optional[int], t0: float,
                         deadline_s: float) -> Dict:
    cell["spec"] = spec
    cell["fault"] = fault
    cell["native_core"] = native_core
    cell["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell["outcome"] == "recovered" and cell["elapsed_s"] > deadline_s:
        # recovery that only lands because some teardown timer fired is a
        # wedge, not a recovery (the run_cell late-escalation contract)
        cell["outcome"] = "late-recovery"
    return cell


def _ckpt_world_fn(total_steps):
    """Per-rank body for one checkpoint cell (shipped by value through
    the elastic driver): integer-valued accumulation so bit-exact
    restore IS the fault-free result, with ≥4 KiB of state so the cell's
    1 KiB chunk knob forces a real multi-chunk stream. Each step commits
    then drains the async stream — the drain is what makes the
    kill-between-chunks fault deterministic (commit N's stream is fully
    sealed before commit N+1 starts)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.elastic import State

    hvd.init()
    state = State(w=np.zeros(1024, np.float32), step=0)

    def train(state):
        while state.step < total_steps:
            grad = hvd.allreduce(
                np.full(1024, float(state.step + 1), np.float32),
                average=False, name=f"chaos.ck.{state.step}")
            state.w = state.w + np.asarray(grad)
            state.step += 1
            state.commit()
            state.flush_commits()
        return {"step": state.step, "w0": float(state.w[0]),
                "epoch": world_epoch(),
                "restore": state.restore_source,
                "restore_no": state.restore_commit_no}

    out = state.run(train)
    hvd.shutdown()
    return out


def run_checkpoint_cell(elastic_fault: str, ckpt_fault: str, expect: str,
                        native_core: Optional[int] = None,
                        np_: int = 2, steps: int = 3,
                        timeout_s: float = 240.0,
                        deadline_s: float = 120.0) -> Dict:
    """Run one checkpoint cell: a 2-proc elastic world on the async
    commit pipeline under one kill. Outcomes: ``clean`` (no fault, no
    relaunch, exact result), ``recovered`` (relaunched AND restored from
    a SEALED commit bit-exactly), ``wrong-restore`` (finished with the
    wrong numbers, or restored from something other than the sealed
    ledger), ``hang``, ``escalated``."""
    import os

    from horovod_tpu.runner import run_elastic

    env = {
        "HOROVOD_ELASTIC_FAULT": elastic_fault,
        "HOROVOD_CKPT_FAULT": ckpt_fault,
        "HOROVOD_CKPT_ASYNC": "1",
        "HOROVOD_CKPT_CHUNK_BYTES": "1024",
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
    }
    if native_core is not None:
        env["HOROVOD_NATIVE_CORE"] = str(native_core)
    t0 = time.monotonic()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run_elastic(
            _ckpt_world_fn, args=(steps,), np=np_, min_np=np_,
            max_restarts=2, backoff_s=0.2, timeout_s=timeout_s,
            start_timeout_s=120.0, heartbeat_interval_s=0.5,
            heartbeat_miss_limit=6, env_extra=dict(env))
        cell = _classify_checkpoint_results(results, elastic_fault,
                                            ckpt_fault, np_, steps)
    except TimeoutError as exc:
        cell = {"outcome": "hang", "error": str(exc)[:500]}
    except Exception as exc:  # noqa: BLE001 - classified as escalation
        cell = {"outcome": "escalated",
                "error": f"{type(exc).__name__}: {exc}"[:500]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    cell["elastic_fault"] = elastic_fault
    cell["ckpt_fault"] = ckpt_fault
    cell["native_core"] = native_core
    cell["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell["outcome"] == "recovered" and cell["elapsed_s"] > deadline_s:
        cell["outcome"] = "late-recovery"
    return cell


def _classify_checkpoint_results(results, elastic_fault: str,
                                 ckpt_fault: str, np_: int,
                                 steps: int) -> Dict:
    """Bit-exact-or-name-the-failure: the unfailed run's numbers are
    computable in closed form (integer sums in float32), so equality IS
    the restored-correctly contract."""
    expected_w0 = float(np_ * sum(range(1, steps + 1)))
    faulted = bool(elastic_fault or ckpt_fault)
    if len(results) != np_:
        return {"outcome": "escalated",
                "error": f"expected {np_} results, got {results!r}"[:500]}
    for r in results:
        if r.get("step") != steps or r.get("w0") != expected_w0:
            return {"outcome": "wrong-restore",
                    "error": f"expected step={steps} w0={expected_w0}, "
                             f"got {results!r}"[:500]}
    epochs = {r.get("epoch") for r in results}
    if not faulted:
        if epochs != {0}:
            return {"outcome": "escalated",
                    "error": f"clean cell relaunched: epochs {epochs}"}
        return {"outcome": "clean", "results": results}
    if epochs == {0}:
        return {"outcome": "escalated",
                "error": "fault cell never relaunched (fault did not "
                         "fire?)"}
    # only root fetches the store; the sealed provenance lives on the
    # rank that adopted the commit and broadcast it
    sources = {r.get("restore") for r in results}
    if "sealed" not in sources:
        return {"outcome": "wrong-restore",
                "error": f"relaunch restored from {sources} — not the "
                         f"sealed ledger"}
    restore_no = next(r.get("restore_no") for r in results
                      if r.get("restore") == "sealed")
    return {"outcome": "recovered", "results": results,
            "restore_no": restore_no}


def _hier_matrix_fn(steps: int, kill_rank, kill_step: int,
                    expect_escalation: bool):
    """Per-rank body for one hierarchy cell (shipped by value through
    runner.run's driver): the flat grid's bit-exact-or-escalate loop,
    plus (a) a hard mid-job exit on ``kill_rank`` — aimed at an island
    HEAD, so the death must travel head→root→world as ONE structured
    abort naming the island — and (b) proof the tree was live: a healed
    cell reports the hier gauge and the island cycle counters off the
    live registry, so a silently-flat degrade can never certify."""
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    try:
        for step in range(steps):
            if rank == kill_rank and step == kill_step:
                os._exit(1)  # the sub-coordinator process dies mid-job
            out = hvd.allreduce(
                np.full((16,), float(rank + step + 1), np.float32),
                average=False, name="chaos.hier")
            if kill_rank is None:
                np.testing.assert_array_equal(
                    np.asarray(out),
                    float(sum(r + step + 1 for r in range(size))))
    except hvd.RanksAbortedError as exc:
        assert expect_escalation, f"unexpected escalation: {exc}"
        if kill_rank is not None:
            # kill cells RE-RAISE: the abort text (the island-naming
            # sub-coordinator attribution) must reach the driver's
            # structured failure record, where run_hierarchy_cell checks
            # it — a returned dict would be discarded when the killed
            # rank's nonzero exit fails the whole run() call
            raise
        return {"rank": rank, "outcome": "escalated",
                "aborted_ranks": exc.ranks, "error": str(exc)[:500]}
    except hvd.HorovodInternalError as exc:
        assert expect_escalation, f"unexpected world failure: {exc}"
        if kill_rank is not None:
            raise
        return {"rank": rank, "outcome": "escalated", "aborted_ranks": [],
                "error": str(exc)[:500]}
    snap = hvd.metrics_snapshot()

    def _val(name):
        samples = (snap.get(name) or {}).get("samples") or []
        return sum(s.get("value", 0) for s in samples)

    hvd.shutdown()
    return {"rank": rank, "outcome": "healed",
            "hier_islands": _val("horovod_hier_islands"),
            "merged_cycles": _val("horovod_hier_merged_cycles_total"),
            "raw_cycles": _val("horovod_hier_raw_cycles_total")}


def run_hierarchy_cell(spec: str, np_: int = 2,
                       hierarchy: str = "islands:2",
                       kill_rank=None, kill_step: int = 3,
                       steps: int = 8,
                       expect_escalation: bool = False,
                       timeout_s: float = 120.0,
                       deadline_s: float = 60.0) -> Dict:
    """One hierarchy-grid cell: the ``run_cell`` env-pin pattern with the
    tree armed (Python controller — the native wire predates the island
    RPCs and would degrade the cell to a flat re-run). Healed cells
    additionally require the tree to have been LIVE (every rank saw the
    islands gauge at its planned value and the world's heads forwarded
    at least one island cycle); escalated cells record whether the abort
    text named the dead head's island (``island_named``)."""
    from horovod_tpu.runner import run
    from horovod_tpu.runner.run_api import WorkerFailedError, WorkerLostError
    from horovod_tpu.runner.launcher import LaunchError

    env = {
        "HOROVOD_CHAOS": spec,
        "HOROVOD_HIERARCHY": hierarchy,
        "HOROVOD_NATIVE_CONTROLLER": "0",
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        "HOROVOD_RECONNECT_ATTEMPTS": "4",
        "HOROVOD_RECONNECT_BACKOFF_S": "0.05",
        "HOROVOD_RECONNECT_WINDOW_S": "2",
        "HOROVOD_STALL_WARNING_TIME": "2",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "4",
    }
    t0 = time.monotonic()
    import os

    from horovod_tpu.core.config import HOROVOD_FLIGHTREC_DIR

    # Kill cells judge the island attribution from the black-box dump:
    # the surviving ranks' failure reports race the launcher's teardown
    # of the world (the kill IS a launcher-visible death), but the
    # flight recorder's evidence grace (docs/blackbox.md) deterministically
    # lands the coordinator's merged incident — whose classified verdict
    # must be the island-scoped one. Honors an outer --blackbox dir.
    bb_dir = None
    if kill_rank is not None and not os.environ.get(HOROVOD_FLIGHTREC_DIR):
        import tempfile

        bb_dir = tempfile.mkdtemp(prefix="hvd-hier-bb-")
        env[HOROVOD_FLIGHTREC_DIR] = bb_dir
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run(_hier_matrix_fn,
                      args=(steps, kill_rank, kill_step,
                            expect_escalation or kill_rank is not None),
                      np=np_, timeout_s=timeout_s, start_timeout_s=120.0)
        if any(r.get("outcome") == "escalated" for r in results):
            cell = {"outcome": "escalated", "results": results}
        else:
            n_islands = int(hierarchy.split(":", 1)[1])
            live = all(r.get("hier_islands") == n_islands
                       for r in results) and any(
                r.get("merged_cycles", 0) + r.get("raw_cycles", 0) > 0
                for r in results)
            cell = {"outcome": "healed" if live else "degraded-flat",
                    "results": results}
    except WorkerFailedError as exc:
        cell = {"outcome": _classify_worker_failure(exc),
                "error": str(exc)[:800],
                # the island-naming attribution lives at the TAIL of a
                # surviving rank's traceback (the exception message);
                # keep those tails where the 800-char head would cut it
                "record_errors": [str(r.get("traceback", ""))[-400:]
                                  for r in exc.records.values()]}
    except (WorkerLostError, LaunchError) as exc:
        cell = {"outcome": "escalated", "error": str(exc)[:800]}
    except TimeoutError as exc:
        cell = {"outcome": "hang", "error": str(exc)[:500]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    cell["spec"] = spec
    cell["hierarchy"] = hierarchy
    cell["kill_rank"] = kill_rank
    cell["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell["outcome"] == "escalated" and cell["elapsed_s"] > deadline_s:
        cell["outcome"] = "late-escalation"
    if kill_rank is not None:
        texts = ([cell.get("error", "")]
                 + list(cell.get("record_errors", []))
                 + [str(r.get("error", ""))
                    for r in cell.get("results", [])])
        cell["island_named"] = any("sub-coordinator" in t for t in texts)
        verdict_dir = bb_dir or os.environ.get(HOROVOD_FLIGHTREC_DIR)
        if not cell["island_named"] and verdict_dir:
            cell["blackbox_verdict"] = _island_verdict(verdict_dir)
            cell["island_named"] = str(
                cell["blackbox_verdict"] or "").startswith(
                    "island-dead@island")
    if bb_dir is not None:
        import shutil

        shutil.rmtree(bb_dir, ignore_errors=True)
    return cell


def _island_verdict(bb_dir: str) -> Optional[str]:
    """Classify the cell's black-box dumps; the merged verdict is the
    island-scoped one when the kill's attribution reached the recorder
    (it deterministically does — the evidence grace holds the world open
    long enough for the coordinator's incident push even when the killed
    rank's nonzero exit beats the survivors' failure reports to the
    launcher, which strips the island text from the driver's error)."""
    import glob as _glob
    import json as _json
    import os

    from horovod_tpu.obs.flightrec import classify_incident, merge_incidents

    docs = []
    for path in sorted(_glob.glob(os.path.join(bb_dir, "blackbox-*.json"))):
        try:
            with open(path, encoding="utf-8") as fh:
                docs.append(_json.load(fh))
        except (OSError, ValueError):
            continue
    if not docs:
        return None
    return classify_incident(merge_incidents(docs)).get("verdict")


def _recovery_world_fn(total_steps, kill_rank, kill_step, piddir):
    """Per-rank body for one recovery cell (shipped by value through the
    elastic driver): the checkpoint grid's integer-exact commit loop,
    plus the evidence the recovery ladder is judged on — a per-epoch PID
    file (warm survivors write the SAME pid under two epochs; a cold
    fork cannot), the island-subcoordinator duty this rank ended up
    holding, and the local successions counter. ``kill_rank`` hard-kills
    that rank at ``kill_step`` in epoch 0 only — the epoch is re-read at
    fire time, so a warm-recovered survivor never re-fires it."""
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.basics import world_epoch
    from horovod_tpu.core import config as _config
    from horovod_tpu.elastic import State

    hvd.init()
    rank = hvd.rank()
    with open(os.path.join(piddir,
                           f"epoch{world_epoch()}.rank{rank}"),
              "w", encoding="utf-8") as fh:
        fh.write(str(os.getpid()))
    state = State(w=np.zeros(64, np.float32), step=0)

    def train(state):
        while state.step < total_steps:
            if rank == kill_rank and state.step == kill_step and \
                    world_epoch() == 0:
                os._exit(1)
            grad = hvd.allreduce(
                np.full(64, float(state.step + 1), np.float32),
                average=False, name=f"chaos.rec.{state.step}")
            state.w = state.w + np.asarray(grad)
            state.step += 1
            state.commit()
            state.flush_commits()
        from horovod_tpu.ops.engine import get_engine

        engine = get_engine()
        sub = getattr(engine, "_subcoord", None)
        snap = hvd.metrics_snapshot()

        def _val(name):
            samples = (snap.get(name) or {}).get("samples") or []
            return sum(s.get("value", 0) for s in samples)

        return {"rank": rank, "pid": os.getpid(), "step": state.step,
                "w0": float(state.w[0]), "epoch": world_epoch(),
                "restore": state.restore_source,
                "restore_no": state.restore_commit_no,
                "subcoord_island": (getattr(sub, "_island", None)
                                    if sub is not None else None),
                "successions": _val(
                    "horovod_recovery_successions_total"),
                "heads_env": os.environ.get(
                    _config.HOROVOD_ISLAND_HEADS, "")}

    out = state.run(train)
    hvd.shutdown()
    return out


def _recovery_pids(piddir: str) -> Dict[Tuple[int, int], int]:
    """{(epoch, rank): pid} from the worker-written evidence files."""
    import os
    import re

    pids: Dict[Tuple[int, int], int] = {}
    for name in os.listdir(piddir):
        m = re.fullmatch(r"epoch(\d+)\.rank(\d+)", name)
        if not m:
            continue
        try:
            with open(os.path.join(piddir, name), encoding="utf-8") as fh:
                pids[(int(m.group(1)), int(m.group(2)))] = int(
                    fh.read().strip())
        except (OSError, ValueError):
            continue
    return pids


def run_recovery_cell(cell: str, native_core: Optional[int] = None,
                      steps: int = 4, timeout_s: float = 240.0,
                      deadline_s: float = 150.0) -> Dict:
    """Run one recovery-plane cell (docs/recovery.md). Outcomes:
    ``healed`` (bit-exact, zero relaunches), ``recovered`` (exactly one
    warm relaunch, restored from the sealed ledger where one existed,
    survivor PIDs unchanged — the cell's ``verdict`` reads like
    ``recovered@epoch1 survivors=3/4``), ``wrong-results`` /
    ``wrong-restore`` / ``cold-relaunch`` / ``escalated`` (a structured
    wrong bucket), ``hang``. Never an unclassified exit."""
    import os
    import shutil
    import tempfile

    from horovod_tpu.runner import run_elastic

    np_ = 4
    kill_rank = kill_step = None
    env = {
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_NATIVE_CONTROLLER": "0",
        "HOROVOD_CYCLE_TIME": "2",
        "HOROVOD_CKPT_ASYNC": "1",
        "HOROVOD_RECOVERY_WINDOW_S": "20",
        "HOROVOD_RECONNECT_ATTEMPTS": "4",
        "HOROVOD_RECONNECT_BACKOFF_S": "0.05",
        "HOROVOD_RECONNECT_WINDOW_S": "2",
        "HOROVOD_STALL_WARNING_TIME": "2",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "8",
    }
    if cell == "kill-rank-warm":
        env["HOROVOD_ELASTIC_FAULT"] = "1:2"
    elif cell == "partition-heal":
        env["HOROVOD_HIERARCHY"] = "islands:2"
        env["HOROVOD_CHAOS"] = "partition@island1:cycle3:dur0.4s"
        env["HOROVOD_RECONNECT_WINDOW_S"] = "4"
    elif cell == "partition-escalate":
        # cycle4, not earlier: commit 1 must SEAL before the blackhole
        # lands, or the warm relaunch has no sealed epoch to prove
        # bit-exact restore against
        env["HOROVOD_HIERARCHY"] = "islands:2"
        env["HOROVOD_CHAOS"] = "partition@island1:cycle4:dur30s"
    elif cell == "head-kill":
        env["HOROVOD_HIERARCHY"] = "islands:2"
        kill_rank, kill_step = 2, 2
    elif cell == "succession-live":
        env["HOROVOD_HIERARCHY"] = "islands:2"
        env["HOROVOD_RECOVERY_FAULT"] = "headstop@island1:cycle2"
    else:
        raise ValueError(f"unknown recovery cell {cell!r}")
    if native_core is not None:
        env["HOROVOD_NATIVE_CORE"] = str(native_core)
    piddir = tempfile.mkdtemp(prefix="hvd-rec-pids-")
    t0 = time.monotonic()
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run_elastic(
            _recovery_world_fn, args=(steps, kill_rank, kill_step,
                                      piddir),
            np=np_, min_np=np_, max_restarts=2, backoff_s=0.2,
            timeout_s=timeout_s, start_timeout_s=120.0,
            heartbeat_interval_s=0.5, heartbeat_miss_limit=6,
            env_extra=dict(env))
        cell_out = _classify_recovery_results(
            cell, results, _recovery_pids(piddir), np_, steps)
    except TimeoutError as exc:
        cell_out = {"outcome": "hang", "error": str(exc)[:500]}
    except Exception as exc:  # noqa: BLE001 - classified as escalation
        cell_out = {"outcome": "escalated",
                    "error": f"{type(exc).__name__}: {exc}"[:500]}
    finally:
        shutil.rmtree(piddir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    cell_out["cell"] = cell
    cell_out["native_core"] = native_core
    cell_out["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell_out["outcome"] == "recovered" and \
            cell_out["elapsed_s"] > deadline_s:
        # a recovery that only lands because some teardown timer fired
        # is a wedge, not a recovery
        cell_out["outcome"] = "late-recovery"
    return cell_out


def _classify_recovery_results(cell: str, results, pids, np_: int,
                               steps: int) -> Dict:
    """Exactly-one-bucket classification: bit-exact numbers first (the
    integer commit loop's end state is computable in closed form), then
    the relaunch count, then the recovery ladder's own evidence — PID
    preservation for warm cells, the successor's island duty for the
    head-kill cell, the successions counter for the live drill."""
    expected_w0 = float(np_ * sum(range(1, steps + 1)))
    if len(results) != np_:
        return {"outcome": "escalated",
                "error": f"expected {np_} results, got {results!r}"[:500]}
    for r in results:
        if r.get("step") != steps or r.get("w0") != expected_w0:
            return {"outcome": "wrong-results",
                    "error": f"expected step={steps} w0={expected_w0}, "
                             f"got {results!r}"[:500]}
    epochs = {r.get("epoch") for r in results}
    heal_cell = cell in ("partition-heal", "succession-live")
    if heal_cell:
        if epochs != {0}:
            return {"outcome": "escalated",
                    "error": f"heal cell relaunched: epochs {epochs}"}
        out = {"outcome": "healed", "results": results}
        if cell == "succession-live":
            successions = sum(r.get("successions") or 0 for r in results)
            if successions < 1:
                return {"outcome": "escalated",
                        "error": "headstop drill fired but no standby "
                                 "recorded a succession"}
            out["verdict"] = "island-head-succeeded@island1"
        return out
    if epochs == {0}:
        return {"outcome": "escalated",
                "error": "fault cell never relaunched (fault did not "
                         "fire?)"}
    if epochs != {1}:
        return {"outcome": "escalated",
                "error": f"expected exactly one relaunch, epochs "
                         f"{epochs}"}
    # Warm proof: a survivor wrote the SAME pid under both epochs. The
    # dead rank (if any) must have a fresh pid; ranks the driver was
    # forced to cold-fork (a parking race) show up here honestly.
    dead = {1} if cell == "kill-rank-warm" else \
        {2} if cell == "head-kill" else set()
    preserved = {r for r in range(np_)
                 if (0, r) in pids and pids.get((0, r)) == pids.get((1, r))}
    if dead & preserved:
        return {"outcome": "cold-relaunch",
                "error": f"dead rank(s) {sorted(dead)} kept their pid "
                         f"({pids}) — the kill did not fire"}
    must_survive = ({0, 2, 3} if cell == "kill-rank-warm" else
                    {0, 1, 3} if cell == "head-kill" else
                    {0, 1})  # partition-escalate: island 0 at minimum
    if not must_survive <= preserved:
        return {"outcome": "cold-relaunch",
                "error": f"survivors {sorted(must_survive - preserved)} "
                         f"were cold-forked, not parked ({pids})"}
    # Restored-from-sealed proof: some rank must carry the sealed
    # provenance (commit 1 seals before any cell's fault fires).
    sources = {r.get("restore") for r in results}
    if "sealed" not in sources:
        return {"outcome": "wrong-restore",
                "error": f"relaunch restored from {sources} — not the "
                         f"sealed ledger"}
    out = {"outcome": "recovered", "results": results,
           "survivors": sorted(preserved),
           "verdict": f"recovered@epoch1 "
                      f"survivors={len(preserved)}/{np_}"}
    if cell == "head-kill":
        # the island must be SERVING under the planned successor: rank 3
        # (island 1's standby) hosts the primary sub-coordinator in
        # epoch 1, and every rank's plan carries the 1:3 override
        successor = [r for r in results
                     if r.get("subcoord_island") == 1]
        if [r.get("rank") for r in successor] != [3]:
            return {"outcome": "escalated",
                    "error": f"island 1 not under the planned successor "
                             f"after relaunch: {results!r}"[:500]}
        if any("1:3" not in (r.get("heads_env") or "") for r in results):
            return {"outcome": "escalated",
                    "error": "HOROVOD_ISLAND_HEADS succession override "
                             "missing from the relaunched world"}
        out["verdict"] = ("recovered@epoch1 "
                          f"survivors={len(preserved)}/{np_} "
                          "island-head-succeeded@island1")
    return out


def run_cell(spec: str,
             native_controller: Optional[int] = None,
             native_core: Optional[int] = None,
             np_: int = 2, steps: int = 8,
             expect_escalation: bool = False,
             timeout_s: float = 120.0,
             deadline_s: float = 60.0) -> Dict:
    """Run one matrix cell; returns a classification dict and never
    hangs past ``timeout_s`` (the runner tears the world down). An
    escalation past ``deadline_s`` is classified ``late-escalation`` —
    the contract is a structured abort INSIDE the deadline, and a
    verdict that only arrives because the runner's teardown timer fired
    is a wedge, not an escalation."""
    from horovod_tpu.runner import run
    from horovod_tpu.runner.run_api import WorkerFailedError, WorkerLostError
    from horovod_tpu.runner.launcher import LaunchError

    env = {
        "HOROVOD_CHAOS": spec,
        "HOROVOD_PLATFORM": "cpu",
        "HOROVOD_CYCLE_TIME": "2",
        # tight-but-real healing budgets so escalation cells stay quick
        "HOROVOD_RECONNECT_ATTEMPTS": "4",
        "HOROVOD_RECONNECT_BACKOFF_S": "0.05",
        "HOROVOD_RECONNECT_WINDOW_S": "2",
        "HOROVOD_STALL_WARNING_TIME": "2",
        "HOROVOD_STALL_SHUTDOWN_TIME_S": "4",
    }
    if native_controller is not None:
        env["HOROVOD_NATIVE_CONTROLLER"] = str(native_controller)
    if native_core is not None:
        env["HOROVOD_NATIVE_CORE"] = str(native_core)
    t0 = time.monotonic()
    # Workers inherit the launcher's environment: pin the cell's knobs in
    # os.environ for the duration of the run (the dryrun pattern).
    import os

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        results = run(_matrix_fn, args=(steps, expect_escalation), np=np_,
                      timeout_s=timeout_s, start_timeout_s=120.0)
        outcome = ("escalated" if any(
            r.get("outcome") == "escalated" for r in results) else "healed")
        cell = {"outcome": outcome, "results": results}
    except WorkerFailedError as exc:
        # A rank raised before reporting. Only a WORLD fault (abort /
        # shut-down collectives, per the structured failure records) is an
        # escalation; a rank that died of its own assertion — a bit-exact
        # mismatch — means the run produced WRONG RESULTS, which must
        # never certify as a passing escalation in --allow-escalation
        # cells. Old-format peers ship no records: keep the escalation
        # reading, the abort tag in the text attributed it.
        cell = {"outcome": _classify_worker_failure(exc),
                "error": str(exc)[:500]}
    except (WorkerLostError, LaunchError) as exc:
        # a rank died of the fault before reporting: escalation — the
        # structured record/abort tag attributes it; the deadline check
        # below decides whether it counts
        cell = {"outcome": "escalated", "error": str(exc)[:500]}
    except TimeoutError as exc:
        cell = {"outcome": "hang", "error": str(exc)[:500]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    cell["spec"] = spec
    cell["elapsed_s"] = round(time.monotonic() - t0, 2)
    if cell["outcome"] == "escalated" and cell["elapsed_s"] > deadline_s:
        cell["outcome"] = "late-escalation"
    cell["native_controller"] = native_controller
    cell["native_core"] = native_core
    return cell


class _BlackboxCheck:
    """``--blackbox`` assertion mode (docs/blackbox.md): every ESCALATED
    cell must also leave a classifiable ``blackbox-*.json`` incident
    file — an escalation with no dump is a failing cell (the flight
    recorder's whole contract is that no world abort goes undiagnosed).
    Each cell gets a fresh ``HOROVOD_FLIGHTREC_DIR`` so incidents never
    cross-contaminate cells."""

    def __init__(self) -> None:
        import tempfile

        from horovod_tpu.core.config import HOROVOD_FLIGHTREC_DIR

        self._key = HOROVOD_FLIGHTREC_DIR
        self._root = tempfile.mkdtemp(prefix="hvd-blackbox-")
        self._n = 0
        self.dir = ""
        self._saved = None

    def begin_cell(self) -> None:
        import os

        self._n += 1
        self.dir = os.path.join(self._root, f"cell{self._n}")
        os.makedirs(self.dir, exist_ok=True)
        self._saved = os.environ.get(self._key)
        os.environ[self._key] = self.dir

    def end_cell(self) -> None:
        import os

        if self._saved is None:
            os.environ.pop(self._key, None)
        else:
            os.environ[self._key] = self._saved

    def verdict(self) -> Optional[str]:
        """Classify this cell's incident file(s); None when none exist."""
        import glob
        import json
        import os

        from horovod_tpu.obs.flightrec import (
            classify_incident,
            merge_incidents,
        )

        files = sorted(glob.glob(os.path.join(self.dir,
                                              "blackbox-*.json")))
        if not files:
            return None
        docs = []
        for path in files:
            with open(path, "r", encoding="utf-8") as fh:
                docs.append(json.load(fh))
        return classify_incident(merge_incidents(docs))["verdict"]

    def run(self, cell_fn):
        """Run one grid cell under a fresh per-cell incident dir."""
        self.begin_cell()
        try:
            return cell_fn()
        finally:
            self.end_cell()

    def assess(self, outcome: str) -> tuple:
        """``(print_suffix, ok)`` for a finished cell: every ESCALATED
        cell must leave a classifiable incident — an escalation with no
        dump is a failing cell (the one assertion of --blackbox mode)."""
        if outcome != "escalated":
            return "", True
        verdict = self.verdict()
        if verdict is None:
            return "  blackbox=MISSING (escalation left no dump)", False
        return f"  blackbox={verdict!r}", True

    def cleanup(self) -> None:
        """Drop the per-sweep incident root (the verdicts were printed;
        repeated CI sweeps must not accumulate /tmp trees)."""
        import shutil

        shutil.rmtree(self._root, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--spec", action="append", default=None,
                        help="fault spec(s); default: the single-fault grid")
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--np", type=int, default=2, dest="np_")
    parser.add_argument("--escalation", action="store_true",
                        help="run the escalation cell instead of the grid")
    parser.add_argument("--allow-escalation", action="store_true",
                        help="accept escalated outcomes for heal cells "
                             "(the native controller's binary wire has no "
                             "request dedup, so faults escalate by design)")
    parser.add_argument("--data-plane", action="store_true",
                        help="run the data-plane integrity grid instead: "
                             "fault kind (nan/flipbits) x sentry policy / "
                             "consensus cells, each asserting "
                             "healed-by-skip / zeroed / "
                             "escalated-in-deadline (docs/integrity.md)")
    parser.add_argument("--blackbox", action="store_true",
                        help="assert black-box incident coverage "
                             "(docs/blackbox.md): every ESCALATED cell "
                             "must leave a classifiable blackbox-*.json "
                             "in a per-cell HOROVOD_FLIGHTREC_DIR; an "
                             "escalation with no dump is a failing cell")
    parser.add_argument("--serving", action="store_true",
                        help="run the serving-plane grid instead "
                             "(docs/serving.md): drop/delay/close on the "
                             "serving RPC must heal with every request "
                             "200-bit-exact, kill-rank-mid-batch must "
                             "relaunch with every request 200 or a "
                             "structured 503 — never a hang")
    parser.add_argument("--hierarchy", action="store_true",
                        help="run the negotiation-tree grid instead "
                             "(docs/hierarchy.md): drop/delay/close on a "
                             "member-to-sub-coordinator link must heal "
                             "bit-exactly with the tree LIVE; a "
                             "sub-coordinator kill must escalate "
                             "in-deadline naming the island")
    parser.add_argument("--checkpoint", action="store_true",
                        help="run the checkpoint-plane grid instead "
                             "(docs/checkpoint.md): kill-before-commit "
                             "and kill-between-chunks must relaunch and "
                             "restore the last SEALED commit bit-exactly; "
                             "a clean async run must never relaunch")
    parser.add_argument("--recovery", action="store_true",
                        help="run the recovery-plane grid instead "
                             "(docs/recovery.md): kill-one-rank and "
                             "partition-past-the-window must WARM-relaunch "
                             "(survivor PIDs unchanged, sealed restore "
                             "bit-exact), partition-inside-the-window and "
                             "the headstop succession drill must heal "
                             "with zero relaunches — never a hang")
    args = parser.parse_args(argv)
    if args.recovery:
        failed = 0
        blackbox = _BlackboxCheck() if args.blackbox else None
        try:
            for cell_name, expect in RECOVERY_GRID:
                def _cell(cell_name=cell_name):
                    return run_recovery_cell(cell_name, steps=args.steps)
                cell = blackbox.run(_cell) if blackbox is not None \
                    else _cell()
                ok = cell["outcome"] == expect
                bb = ""
                if blackbox is not None:
                    # a RECOVERED cell rode a world abort too: it owes a
                    # classifiable incident dump exactly like an
                    # escalation (the PR 14 no-undiagnosed-abort contract)
                    if cell["outcome"] in ("recovered", "escalated",
                                           "late-recovery"):
                        verdict = blackbox.verdict()
                        if verdict is None:
                            bb = ("  blackbox=MISSING (abort left no "
                                  "dump)")
                            ok = False
                        else:
                            bb = f"  blackbox={verdict!r}"
                if not ok:
                    failed += 1
                verdict_str = (f"  {cell['verdict']}"
                               if "verdict" in cell else "")
                print(f"recovery-cell {'OK ' if ok else 'BAD'} "
                      f"outcome={cell['outcome']:<15} "
                      f"{cell['elapsed_s']:6.1f}s  "
                      f"{cell_name}{verdict_str}{bb}", flush=True)
                if not ok:
                    print(f"  {cell.get('error', '')}", flush=True)
        finally:
            if blackbox is not None:
                blackbox.cleanup()
        return 1 if failed else 0
    if args.hierarchy:
        failed = 0
        blackbox = _BlackboxCheck() if args.blackbox else None
        try:
            for spec, np_, hierarchy, kill_rank, expect in HIERARCHY_GRID:
                def _cell(spec=spec, np_=np_, hierarchy=hierarchy,
                          kill_rank=kill_rank, expect=expect):
                    return run_hierarchy_cell(
                        spec, np_=np_, hierarchy=hierarchy,
                        kill_rank=kill_rank, steps=args.steps,
                        expect_escalation=(expect == "escalated"))
                cell = blackbox.run(_cell) if blackbox is not None \
                    else _cell()
                ok = cell["outcome"] == expect
                if kill_rank is not None:
                    # an escalation that lost the island attribution is
                    # a failing cell: the whole point of the head-death
                    # path is a structured abort NAMING the island
                    ok = ok and cell.get("island_named", False)
                bb = ""
                if blackbox is not None:
                    bb, bb_ok = blackbox.assess(cell["outcome"])
                    ok = ok and bb_ok
                if not ok:
                    failed += 1
                label = (f"{hierarchy} np={np_} " +
                         (f"kill-head@rank{kill_rank}" if kill_rank
                          is not None else spec))
                print(f"hier-cell {'OK ' if ok else 'BAD'} "
                      f"outcome={cell['outcome']:<15} "
                      f"{cell['elapsed_s']:6.1f}s  {label}{bb}",
                      flush=True)
                if not ok:
                    print(f"  {cell.get('error', '')}", flush=True)
        finally:
            if blackbox is not None:
                blackbox.cleanup()
        return 1 if failed else 0
    if args.checkpoint:
        failed = 0
        for elastic_fault, ckpt_fault, expect in CHECKPOINT_GRID:
            cell = run_checkpoint_cell(elastic_fault, ckpt_fault, expect,
                                       np_=args.np_)
            ok = cell["outcome"] == expect
            if not ok:
                failed += 1
            label = (f"elastic={elastic_fault}" if elastic_fault
                     else f"ckpt={ckpt_fault}" if ckpt_fault else "clean")
            sealed = (f"  sealed_no={cell['restore_no']}"
                      if "restore_no" in cell else "")
            print(f"ckpt-cell {'OK ' if ok else 'BAD'} "
                  f"outcome={cell['outcome']:<13} "
                  f"{cell['elapsed_s']:6.1f}s  {label}{sealed}",
                  flush=True)
            if not ok:
                print(f"  {cell.get('error', '')}", flush=True)
        return 1 if failed else 0
    if args.serving:
        failed = 0
        for spec, fault, expect in SERVING_GRID:
            cell = run_serving_cell(spec, fault, expect, np_=args.np_)
            ok = cell["outcome"] == expect
            if not ok:
                failed += 1
            label = spec or fault
            print(f"serving-cell {'OK ' if ok else 'BAD'} "
                  f"outcome={cell['outcome']:<13} "
                  f"{cell['elapsed_s']:6.1f}s  {label}", flush=True)
            if not ok:
                print(f"  {cell.get('error', cell.get('responses', ''))}",
                      flush=True)
        return 1 if failed else 0
    if args.data_plane:
        failed = 0
        blackbox = _BlackboxCheck() if args.blackbox else None
        try:
            for spec, policy, consensus, expect, codec in DATA_GRID:
                def _cell(spec=spec, policy=policy, consensus=consensus,
                          expect=expect, codec=codec):
                    return run_data_cell(spec, policy, consensus, expect,
                                         np_=args.np_, steps=args.steps,
                                         codec=codec)
                cell = blackbox.run(_cell) if blackbox is not None \
                    else _cell()
                ok = cell["outcome"] == expect
                bb = ""
                if blackbox is not None:
                    bb, bb_ok = blackbox.assess(cell["outcome"])
                    ok = ok and bb_ok
                if not ok:
                    failed += 1
                label = f"{spec} sentry={policy}" + (
                    f" consensus={consensus}" if consensus else "") + (
                    f" codec={codec}" if codec != "none" else "")
                print(f"data-cell {'OK ' if ok else 'BAD'} "
                      f"outcome={cell['outcome']:<15} "
                      f"{cell['elapsed_s']:6.1f}s  {label}{bb}", flush=True)
                if not ok:
                    print(f"  {cell.get('error', '')}", flush=True)
        finally:
            if blackbox is not None:
                blackbox.cleanup()
        return 1 if failed else 0
    if not args.allow_escalation:
        from horovod_tpu.core.config import Config
        from horovod_tpu.ops.native_controller import (
            native_controller_enabled,
        )

        if native_controller_enabled(Config.from_env()):
            # the effective controller for this env is the native one:
            # its dedup-less binary wire escalates single faults by
            # design, so heal-cell strictness would only certify a
            # misconfiguration
            args.allow_escalation = True
            print("native controller in effect: escalated outcomes "
                  "accepted for heal cells (--allow-escalation implied; "
                  "set HOROVOD_NATIVE_CONTROLLER=0 to certify the "
                  "dedup-heal path)", flush=True)
    specs = args.spec or (
        [ESCALATION_SPEC] if args.escalation else DEFAULT_SPECS)
    failed = 0
    blackbox = _BlackboxCheck() if args.blackbox else None
    try:
        for spec in specs:
            escalation_cell = args.escalation or spec == ESCALATION_SPEC

            def _cell(spec=spec, escalation_cell=escalation_cell):
                return run_cell(spec, np_=args.np_, steps=args.steps,
                                expect_escalation=escalation_cell
                                or args.allow_escalation)
            cell = blackbox.run(_cell) if blackbox is not None else _cell()
            # The expectation IS the certification: an escalation cell
            # must escalate, and a heal cell must HEAL — accepting
            # "escalated" there would hide a broken dedup-heal path
            # behind a green sweep (--allow-escalation relaxes heal
            # cells for the native controller's dedup-less binary wire,
            # where faults escalate by design).
            expected = (("escalated",) if escalation_cell
                        else ("healed", "escalated")
                        if args.allow_escalation else ("healed",))
            ok = cell["outcome"] in expected
            bb = ""
            if blackbox is not None:
                bb, bb_ok = blackbox.assess(cell["outcome"])
                ok = ok and bb_ok
            if not ok:
                failed += 1
            print(f"chaos-cell {'OK ' if ok else 'BAD'} "
                  f"outcome={cell['outcome']:<9} {cell['elapsed_s']:6.1f}s  "
                  f"{spec}{bb}", flush=True)
            if not ok:
                print(f"  {cell.get('error', '')}", flush=True)
    finally:
        if blackbox is not None:
            blackbox.cleanup()
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
