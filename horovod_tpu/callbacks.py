"""Training-loop callbacks: metric averaging, LR warmup/schedule, broadcast.

Rebuild of ``horovod/_keras/callbacks.py`` (shared by the keras/tf.keras
front-ends, SURVEY §2.5). JAX has no Model.fit; these callbacks target the
explicit training loops JAX users write. Two forms are provided:

* Callback objects with the reference's hook names
  (``on_train_begin`` / ``on_epoch_end`` / ``on_batch_begin``) driven by a
  user loop through ``CallbackList`` — a drop-in structural match for code
  migrating from ``hvd.callbacks.*``.
* ``warmup_schedule(...)``: the same Goyal et al. gradual-warmup math as
  ``LearningRateWarmupCallback`` (``_keras/callbacks.py:149-168``) expressed
  as an optax schedule — the idiomatic JAX form, compiled into the update.

The LR-mutating callbacks require the optimizer be built with
``optax.inject_hyperparams`` so ``learning_rate`` is a leaf in the optimizer
state (the analog of Keras's mutable ``optimizer.lr`` the reference pokes).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from . import basics, ops
from .state_bcast import broadcast_optimizer_state, broadcast_parameters


class Callback:
    """Hook surface (subset of keras.callbacks.Callback the reference uses)."""

    def on_train_begin(self, state: "TrainLoop") -> None: ...

    def on_epoch_begin(self, epoch: int, state: "TrainLoop") -> None: ...

    def on_batch_begin(self, batch: int, state: "TrainLoop") -> None: ...

    def on_epoch_end(self, epoch: int, state: "TrainLoop",
                     logs: Optional[Dict[str, float]] = None) -> None: ...


class TrainLoop:
    """Minimal mutable loop state the callbacks operate on."""

    def __init__(self, params: Any = None, opt_state: Any = None,
                 learning_rate: Optional[float] = None) -> None:
        self.params = params
        self.opt_state = opt_state
        self.learning_rate = learning_rate
        self.epoch = 0

    def set_lr(self, lr: float) -> None:
        """Update the learning rate in place. Works on a plain float field
        and, when ``opt_state`` came from ``optax.inject_hyperparams``, on
        the ``hyperparams['learning_rate']`` leaf."""
        self.learning_rate = lr
        hp = getattr(self.opt_state, "hyperparams", None)
        if hp is not None and "learning_rate" in hp:
            import jax.numpy as jnp

            hp["learning_rate"] = jnp.asarray(lr, dtype=jnp.float32)


class CallbackList:
    def __init__(self, callbacks: List[Callback]) -> None:
        self.callbacks = list(callbacks)

    def on_train_begin(self, state: TrainLoop) -> None:
        for c in self.callbacks:
            c.on_train_begin(state)

    def on_epoch_begin(self, epoch: int, state: TrainLoop) -> None:
        state.epoch = epoch
        for c in self.callbacks:
            c.on_epoch_begin(epoch, state)

    def on_batch_begin(self, batch: int, state: TrainLoop) -> None:
        for c in self.callbacks:
            c.on_batch_begin(batch, state)

    def on_epoch_end(self, epoch: int, state: TrainLoop,
                     logs: Optional[Dict[str, float]] = None) -> None:
        for c in self.callbacks:
            c.on_epoch_end(epoch, state, logs)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast rank-0 params + optimizer state at train start
    (``_keras/callbacks.py:20-30``; the consistent-start contract of
    SURVEY §5.4)."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank

    def on_train_begin(self, state: TrainLoop) -> None:
        if state.params is not None:
            state.params = broadcast_parameters(state.params, self.root_rank)
        if state.opt_state is not None:
            state.opt_state = broadcast_optimizer_state(
                state.opt_state, self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch-end metrics over ranks (``_keras/callbacks.py:33-67``).
    Mutates ``logs`` in place, like the reference mutates Keras logs."""

    def on_epoch_end(self, epoch: int, state: TrainLoop,
                     logs: Optional[Dict[str, float]] = None) -> None:
        if not logs or basics.size() == 1:
            return
        for key in sorted(logs):
            value = np.asarray(float(logs[key]), dtype=np.float64)
            avg = ops.allreduce(value, average=True,
                                name=f"metric.{key}.epoch{epoch}")
            logs[key] = float(np.asarray(avg))


class LearningRateScheduleCallback(Callback):
    """LR = initial_lr * multiplier(epoch) within [start_epoch, end_epoch)
    (``_keras/callbacks.py:70-147``; staircase vs smooth interpolation)."""

    def __init__(self, initial_lr: float,
                 multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 steps_per_epoch: Optional[int] = None) -> None:
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _adjust(self, epoch: float, state: TrainLoop) -> None:
        if self._in_range(epoch):
            state.set_lr(self.initial_lr * self.multiplier(epoch))

    def on_epoch_begin(self, epoch: int, state: TrainLoop) -> None:
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch, state)

    def on_batch_begin(self, batch: int, state: TrainLoop) -> None:
        if not self.staircase:
            if self.steps_per_epoch is None:
                raise ValueError(
                    "steps_per_epoch is required for smooth (staircase="
                    "False) schedules, as in the reference.")
            self._adjust(self.current_epoch + batch / self.steps_per_epoch,
                         state)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from initial_lr to initial_lr * num_devices over
    ``warmup_epochs`` (Goyal et al.; ``_keras/callbacks.py:149-168``)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None,
                 target_scale: Optional[float] = None) -> None:
        self.warmup_epochs = warmup_epochs
        scale_holder = [target_scale]

        def multiplier(epoch: float) -> float:
            scale = scale_holder[0]
            if scale is None:
                scale = scale_holder[0] = float(basics.num_devices())
            progress = min(epoch / warmup_epochs, 1.0)
            return 1.0 + progress * (scale - 1.0)

        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs + 1, staircase=False,
                         steps_per_epoch=steps_per_epoch)


def warmup_schedule(base_lr: float, steps_per_epoch: int,
                    warmup_epochs: int = 5,
                    target_scale: Optional[float] = None,
                    after: Optional[Callable] = None):
    """The same warmup as ``LearningRateWarmupCallback`` as an optax
    schedule (step -> lr), composable with any decay via ``after``."""

    def schedule(step):
        import jax.numpy as jnp

        scale = float(basics.num_devices()) if target_scale is None \
            else target_scale
        epoch = step / steps_per_epoch
        progress = jnp.minimum(epoch / warmup_epochs, 1.0)
        warm = base_lr * (1.0 + progress * (scale - 1.0))
        if after is None:
            return warm
        return jnp.where(epoch < warmup_epochs, warm,
                         after(step - warmup_epochs * steps_per_epoch))

    return schedule
