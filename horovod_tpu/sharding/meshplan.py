"""2-D GSPMD mesh planner (docs/sharding.md §mesh grammar).

The repo's meshes so far are 1-D data-parallel (``parallel/mesh.py``
``('data',)``) or hosts×chips (``('dcn','ici')``). SNIPPETS [2]'s
exemplar scales "from 8-chip v4 to 6000-chip v5p without changing
application code" by naming a ``(batch, model)`` mesh once and letting
GSPMD propagate shardings — this module grows the 1-D data axis into
that named 2-D mesh from ``core/topology`` + ``parallel/mesh.py``
device facts, governed by one knob:

    HOROVOD_MESH=batch            # flat default: model axis of size 1
    HOROVOD_MESH=batch,model:K    # K-way model axis, batch gets the rest

The flat default is byte-identical to today's 1-D world: a model axis
of size 1 shards nothing (every ``PartitionSpec`` over it is a no-op),
so existing programs compile to the same HLO. The planner only PLANS —
it returns the named mesh and ``NamedSharding`` specs; callers (SPMD
front-ends, the ZeRO-1 plane's future model-sharded stage) decide what
to place where. Nothing here opens a socket or owns a device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..core import config as _config

BATCH_AXIS = "batch"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshPlan:
    """One planned ``(batch, model)`` factoring of the device world."""

    batch: int
    model: int

    def __post_init__(self) -> None:
        if self.batch < 1 or self.model < 1:
            raise ValueError(
                f"mesh axes must be positive, got batch={self.batch} "
                f"model={self.model}")

    @property
    def devices(self) -> int:
        return self.batch * self.model

    @property
    def axes(self) -> Tuple[str, str]:
        return (BATCH_AXIS, MODEL_AXIS)

    @property
    def flat(self) -> bool:
        """True when the model axis is degenerate — the byte-identical
        1-D data-parallel world."""
        return self.model == 1

    def describe(self) -> str:
        return f"{BATCH_AXIS}={self.batch}x{MODEL_AXIS}={self.model}"


def parse_mesh_spec(spec: str) -> int:
    """Model-axis size from the ``HOROVOD_MESH`` grammar.

    ``"batch"`` → 1 (flat); ``"batch,model:K"`` → K. Anything else is a
    loud ValueError at plan time — a mesh typo must never silently fall
    back to an unsharded world."""
    s = (spec or BATCH_AXIS).strip()
    if s == BATCH_AXIS:
        return 1
    prefix = f"{BATCH_AXIS},{MODEL_AXIS}:"
    if s.startswith(prefix):
        try:
            k = int(s[len(prefix):])
        except ValueError:
            k = 0
        if k >= 1:
            return k
    raise ValueError(
        f"bad {_config.HOROVOD_MESH} spec {spec!r}; expected "
        f"'{BATCH_AXIS}' or '{BATCH_AXIS},{MODEL_AXIS}:K' with K >= 1")


def plan(n_devices: int, spec: Optional[str] = None) -> MeshPlan:
    """Factor ``n_devices`` per the spec (config/env when ``None``):
    the model axis takes K, the batch axis the rest — K must divide the
    device count, the same divisibility contract GSPMD itself enforces
    at compile time, surfaced here with the knob's name on it."""
    if spec is None:
        from ..core import basics

        if basics.is_initialized():
            spec = basics.config().mesh
        else:
            from ..core.config import Config

            spec = Config.from_env().mesh
    model = parse_mesh_spec(spec)
    if n_devices % model != 0:
        raise ValueError(
            f"{_config.HOROVOD_MESH}={spec!r}: model axis {model} does "
            f"not divide the {n_devices}-device world")
    return MeshPlan(batch=n_devices // model, model=model)


def build_mesh(mesh_plan: MeshPlan, devices: Optional[Sequence] = None):
    """Materialize the named 2-D ``jax.sharding.Mesh`` for a plan.

    Device order comes from ``parallel/mesh.py``'s world enumeration
    (``jax.devices()`` — the MPI_COMM_WORLD analog) reshaped
    ``(batch, model)`` row-major, so model-axis neighbours are
    consecutive devices: on a TPU slice those are the ICI-closest pairs,
    which is where the model axis's latency-critical collectives belong
    (the dcn/ici factoring argument of ``hierarchical_mesh``)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.size != mesh_plan.devices:
        raise ValueError(
            f"plan {mesh_plan.describe()} wants {mesh_plan.devices} "
            f"devices, got {devs.size}")
    grid = devs.reshape(mesh_plan.batch, mesh_plan.model)
    return Mesh(grid, mesh_plan.axes)


def param_sharding(mesh, shape: Tuple[int, ...]):
    """``NamedSharding`` for a parameter: model axis over the LARGEST
    divisible dimension, replicated otherwise (GSPMD's propagation fills
    in the rest). Flat meshes always replicate — byte-identical to the
    1-D world."""
    from jax.sharding import NamedSharding, PartitionSpec

    model = mesh.shape[MODEL_AXIS]
    if model == 1 or not shape:
        return NamedSharding(mesh, PartitionSpec())
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if shape[dim] % model == 0:
            spec = [None] * len(shape)
            spec[dim] = MODEL_AXIS
            return NamedSharding(mesh, PartitionSpec(*spec))
    return NamedSharding(mesh, PartitionSpec())


def activation_sharding(mesh, ndim: int = 2):
    """``NamedSharding`` for activations: batch axis on dim 0 (the
    per-example dimension every data-parallel program already has),
    remaining dims replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    if ndim < 1:
        return NamedSharding(mesh, PartitionSpec())
    spec = [None] * ndim
    spec[0] = BATCH_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))
