"""ZeRO-1 optimizer-state partitioner (docs/sharding.md).

Pure data parallelism replicates parameters AND optimizer slots on every
rank — Adam pays 2N× memory for state only one rank ever needs to
update. ZeRO stage 1 ends the slot replication: each rank OWNS a
contiguous shard of every flattened slot leaf, the eager flush executes
reduce-scatter → :class:`ops.fused_apply.ApplyRule` on the local shard →
all-gather as ONE donated compiled program
(``XlaDataPlane.reduce_scatter_apply``), and parameters land fully
replicated exactly as before — front-ends opt in with ``HOROVOD_ZERO=1``
and see identical applied parameters, bit-exact by the single-definition
update math (``ApplyRule.shard_apply_body`` is the same jnp expressions
the replicated bucket program runs, over a slice).

This module is the partition geometry and host-side marshalling —
NO collectives and NO engine state live here:

* **partition math** — :func:`shard_len` / :func:`padded_len` /
  :func:`shard_slice`: leaf of ``n`` elements pads to the next multiple
  of ``world``; rank ``r`` owns flat slice ``[r*sh, (r+1)*sh)``. The pad
  is zeros, landing in no real element (the census reads gradients, and
  pad gradients are zero by construction of the packers below).
* **shard-major bucket layout** — :func:`pack_rows` /
  :func:`unpack_rows` / :func:`pack_shard_row` / :func:`split_shard_row`:
  the engine's ZeRO-1 bucket is ``(world * shard_bucket,)`` with row
  ``r`` holding the concatenation of every leaf's ``r``-th shard, so the
  tiled ``lax.psum_scatter`` chunking IS the ownership map — rank ``r``
  receives exactly the reduced slices it owns, no reshuffle dispatch.
* **sharded state trees** — :class:`ShardLeaf` (an OPAQUE marker, not a
  registered pytree node: byte-level consumers must go through
  :func:`expand_tree` first, and anything that forgets fails loudly on
  the unknown leaf type instead of silently hashing a fragment):
  :func:`localize_tree` cuts a replicated tree into this rank's shards
  (pure local), :func:`expand_tree` reassembles the canonical replicated
  tree through a caller-supplied negotiated allgather (COLLECTIVE —
  every rank must call it), and :func:`adopt_tree` re-cuts a canonical
  tree for a possibly DIFFERENT world size — the elastic resharding
  primitive: the sealed commit stores the canonical form, so an N→N-1
  relaunch just adopts it under the new partition, digest-verified
  through the unchanged PR 17 ledger because the canonical tree is
  byte-identical to what a replicated run would have committed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.registry import registry as _obs_metrics

# Observability plane (docs/metrics.md §sharding): partition geometry and
# per-rank state residency — the bench's memory claim and the dryrun's
# ~1/N certification read these, not ad-hoc accounting.
_SHARD_RANKS = _obs_metrics().gauge(
    "horovod_shard_ranks",
    "World size of the current ZeRO-1 partition (0 = replicated)")
_SHARD_SLOT_BYTES = _obs_metrics().gauge(
    "horovod_shard_slot_bytes",
    "Optimizer-slot bytes resident on THIS rank after partitioning")
_SHARD_PAD = _obs_metrics().counter(
    "horovod_shard_pad_elems_total",
    "Padding elements introduced cutting leaves into equal rank shards")
_SHARD_RESHARD = _obs_metrics().counter(
    "horovod_shard_reshard_total",
    "Repartition events (elastic world-size change adopting a commit)")
_SHARD_IMBALANCE = _obs_metrics().gauge(
    "horovod_shard_imbalance_ratio",
    "This rank's ZeRO-1 contribution ratio world^2*|g_local|^2/|sum g|^2 "
    "(1.0 = balanced; persistently >>1 = this rank's data feeds outsized "
    "gradients). Folds cross-rank in the tensorwatch report")


# -- partition math -----------------------------------------------------------

def shard_len(n: int, world: int) -> int:
    """Per-rank shard length for an ``n``-element leaf: ``ceil(n/world)``
    — every rank's shard is the SAME length (the trailing rank's tail is
    zero pad), which is what lets one ``psum_scatter`` chunk the bucket
    evenly."""
    if world <= 0:
        raise ValueError(f"world must be positive, got {world}")
    return -(-n // world)


def padded_len(n: int, world: int) -> int:
    """``n`` rounded up to a multiple of ``world``."""
    return shard_len(n, world) * world


def shard_slice(n: int, world: int, rank: int) -> Tuple[int, int]:
    """``[start, stop)`` of rank ``rank``'s shard within the PADDED flat
    leaf; ``stop`` may exceed ``n`` (the pad region) but never the
    padded length."""
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside world of {world}")
    sh = shard_len(n, world)
    return rank * sh, (rank + 1) * sh


def payload_elems(sizes: Sequence[int], world: int) -> int:
    """Per-rank shard payload of a leaf batch: Σ ceil(n_i/world) — the
    number the engine rounds up to its power-of-two shard bucket."""
    return int(sum(shard_len(int(n), world) for n in sizes))


# -- shard-major bucket marshalling ------------------------------------------

def pack_rows(leaves: Sequence[Any], world: int, shard_bucket: int,
              dtype=np.float32) -> np.ndarray:
    """Pack full leaves into the shard-major ``(world * shard_bucket,)``
    bucket: row ``r`` is the concatenation of every leaf's ``r``-th
    shard slice, zero-padded to ``shard_bucket``. Used for BOTH the
    gradient bucket (each rank's local contribution) and the replicated
    parameter bucket — identical layout is what lets the compiled
    program ``dynamic_slice`` its own param shard at the psum_scatter
    chunk offset."""
    buf = np.zeros((world * shard_bucket,), dtype)
    off = 0
    for leaf in leaves:
        flat = np.asarray(leaf, dtype=dtype).reshape(-1)
        n = flat.size
        sh = shard_len(n, world)
        padded = np.zeros((sh * world,), dtype)
        padded[:n] = flat
        for r in range(world):
            row = r * shard_bucket + off
            buf[row:row + sh] = padded[r * sh:(r + 1) * sh]
        off += sh
    if off > shard_bucket:
        raise ValueError(
            f"shard payload {off} overflows shard bucket {shard_bucket}")
    return buf


def unpack_rows(buf: np.ndarray, shapes: Sequence[Tuple[int, ...]],
                world: int, shard_bucket: int) -> List[np.ndarray]:
    """Inverse of :func:`pack_rows`: full leaves (original shapes, pad
    trimmed) from a shard-major full bucket."""
    out, off = [], 0
    for shape in shapes:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        sh = shard_len(n, world)
        flat = np.empty((sh * world,), buf.dtype)
        for r in range(world):
            row = r * shard_bucket + off
            flat[r * sh:(r + 1) * sh] = buf[row:row + sh]
        out.append(flat[:n].reshape(shape))
        off += sh
    return out


def pack_shard_row(shards: Sequence[Any], shard_bucket: int,
                   dtype=np.float32) -> np.ndarray:
    """This rank's ``(shard_bucket,)`` slot row from its per-leaf shard
    arrays (concatenated in leaf order, zero-padded) — the 1/N-resident
    input of the ZeRO-1 program."""
    buf = np.zeros((shard_bucket,), dtype)
    off = 0
    for s in shards:
        flat = np.asarray(s, dtype=dtype).reshape(-1)
        buf[off:off + flat.size] = flat
        off += flat.size
    if off > shard_bucket:
        raise ValueError(
            f"shard payload {off} overflows shard bucket {shard_bucket}")
    return buf


def split_shard_row(row: np.ndarray,
                    lens: Sequence[int]) -> List[np.ndarray]:
    """Inverse of :func:`pack_shard_row`: per-leaf shard arrays from one
    ``(shard_bucket,)`` row."""
    out, off = [], 0
    for sh in lens:
        out.append(np.array(row[off:off + sh], copy=True))
        off += sh
    return out


# -- sharded state trees ------------------------------------------------------

@dataclass(frozen=True)
class ShardSpec:
    """Geometry of one partitioned leaf: the FULL shape/dtype it expands
    back to, and the partition that cut it."""

    shape: Tuple[int, ...]
    dtype: str
    world: int
    rank: int

    @property
    def n(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape \
            else 1


class ShardLeaf:
    """One rank's contiguous shard of a ZeRO-1 partitioned leaf.

    Deliberately NOT a registered pytree node: jax tree ops treat it as
    an opaque leaf, so a consumer that expects replicated arrays (digest,
    serialize, arithmetic) fails loudly on the unknown type instead of
    silently processing a fragment as if it were the whole — the same
    fail-closed posture as the seal ledger. Go through
    :func:`expand_tree` first."""

    __slots__ = ("data", "spec")

    def __init__(self, data: np.ndarray, spec: ShardSpec) -> None:
        self.data = data
        self.spec = spec

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ShardLeaf(rank={self.spec.rank}/{self.spec.world}, "
                f"full={self.spec.shape}, shard={self.data.shape})")


def is_shard(x: Any) -> bool:
    return isinstance(x, ShardLeaf)


def has_shards(tree: Any) -> bool:
    """True if any leaf of ``tree`` is a :class:`ShardLeaf`."""
    import jax

    return any(is_shard(leaf) for leaf in jax.tree_util.tree_leaves(
        tree, is_leaf=is_shard))


def localize_leaf(full: Any, world: int, rank: int) -> ShardLeaf:
    """Cut this rank's shard out of a replicated leaf — pure local (the
    replicated invariant means every rank already holds every shard)."""
    arr = np.asarray(full)
    n = arr.size
    sh = shard_len(n, world)
    flat = np.zeros((sh * world,), arr.dtype)
    flat[:n] = arr.reshape(-1)
    start, stop = shard_slice(n, world, rank)
    _SHARD_PAD.inc(sh * world - n)
    return ShardLeaf(
        np.array(flat[start:stop], copy=True),
        ShardSpec(shape=tuple(int(s) for s in arr.shape),
                  dtype=str(arr.dtype), world=world, rank=rank))


def expand_leaf(leaf: ShardLeaf, gather: Callable[..., Any],
                name: str) -> np.ndarray:
    """Reassemble the full leaf from every rank's shard through the
    negotiated allgather (COLLECTIVE): equal-length shards concatenate
    in rank order, pad trims off the tail. The result is byte-identical
    on every rank — the property the seal ledger's digest votes need."""
    full = np.asarray(gather(leaf.data, name=name))
    spec = leaf.spec
    return np.array(full.reshape(-1)[:spec.n], copy=True).reshape(
        spec.shape).astype(np.dtype(spec.dtype), copy=False)


def localize_tree(tree: Any, world: int, rank: int) -> Any:
    """Every array leaf → its :class:`ShardLeaf` for ``(world, rank)``.
    Pure local; updates the residency gauges. Applied to optimizer SLOT
    trees only — parameters stay replicated under ZeRO-1."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree, is_leaf=is_shard)
    out = []
    for leaf in leaves:
        if is_shard(leaf):
            raise ValueError(
                "localize_tree over an already-sharded tree; use "
                "adopt_tree to repartition")
        out.append(localize_leaf(leaf, world, rank))
    _SHARD_RANKS.set(world)
    _SHARD_SLOT_BYTES.set(resident_bytes(
        jax.tree_util.tree_unflatten(treedef, out)))
    return jax.tree_util.tree_unflatten(treedef, out)


def expand_tree(tree: Any, gather: Callable[..., Any],
                tag: str = "zero1.expand") -> Any:
    """Sharded tree → the CANONICAL replicated tree (plain arrays, the
    exact tree a replicated run would hold) via one negotiated allgather
    per shard leaf. COLLECTIVE — every rank of the partition must call
    with the same tree structure and tag, or the negotiation wedges.
    Non-shard leaves pass through untouched."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree, is_leaf=is_shard)
    out = []
    for i, leaf in enumerate(leaves):
        if is_shard(leaf):
            out.append(expand_leaf(leaf, gather, f"{tag}.{i}"))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def adopt_tree(template: Any, canonical: Any, world: int,
               rank: int) -> Any:
    """Re-cut a canonical replicated tree under THIS world's partition,
    sharding exactly the leaves that are sharded in ``template`` (the
    live tree) — the elastic resharding step: the sealed commit's
    canonical form is world-size-independent, so an N→M relaunch adopts
    it by slicing M-way instead of N-way. Pure local."""
    import jax

    t_leaves, t_def = jax.tree_util.tree_flatten(template,
                                                 is_leaf=is_shard)
    c_leaves = jax.tree_util.tree_flatten(canonical)[0]
    if len(t_leaves) != len(c_leaves):
        raise ValueError(
            f"adopt_tree structure mismatch: template has "
            f"{len(t_leaves)} leaves, canonical {len(c_leaves)}")
    out = []
    resharded = False
    for t, c in zip(t_leaves, c_leaves):
        if is_shard(t):
            if t.spec.world != world:
                resharded = True
            out.append(localize_leaf(c, world, rank))
        else:
            out.append(c)
    if resharded:
        _SHARD_RESHARD.inc()
    return jax.tree_util.tree_unflatten(t_def, out)


def resident_bytes(tree: Any) -> int:
    """Bytes of state actually RESIDENT on this rank: shard leaves count
    their shard only — the bench's per-rank memory number."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=is_shard):
        arr = leaf.data if is_shard(leaf) else np.asarray(leaf)
        total += int(arr.size) * int(arr.dtype.itemsize)
    return total


def note_slot_residency(slot_trees: Any) -> int:
    """Point the residency gauge at the FULL slot state (a tuple of
    per-slot trees). ``localize_tree`` sets the gauge per tree it cuts,
    so a multi-slot rule (Adam: m and v) would otherwise report only
    the last slot; the optimizer calls this after localizing the whole
    tuple. Returns the resident bytes it recorded."""
    total = resident_bytes(slot_trees)
    _SHARD_SLOT_BYTES.set(total)
    return total


def record_imbalance(local_rows: Any, reduced_rows: Any,
                     world: int) -> Optional[float]:
    """Set this rank's shard-imbalance gauge from one ZeRO-1 batch:
    ``world^2 * |g_local|^2 / |sum g|^2`` is 1.0 when every rank
    contributes the same gradient and grows toward ``world^2`` as this
    rank's partition dominates the reduction. Returns None (gauge
    untouched) when the reduced bucket is all-zero."""
    local = float(np.square(np.asarray(local_rows,
                                       dtype=np.float64)).sum())
    total = float(np.square(np.asarray(reduced_rows,
                                       dtype=np.float64)).sum())
    if total <= 0.0:
        return None
    ratio = float(world) * float(world) * local / total
    _SHARD_IMBALANCE.set(round(ratio, 6))
    return ratio


def shard_digest(tree: Any) -> bytes:
    """Order-stable digest of THIS rank's resident shard bytes — the
    per-rank vote the seal ledger folds into the partition manifest
    (``shard_manifest`` RPC): structure string + per-shard spec + raw
    shard bytes, blake2b-8 like the consensus window digests."""
    import hashlib

    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree, is_leaf=is_shard)
    h = hashlib.blake2b(digest_size=8)
    h.update(str(treedef).encode())
    for leaf in leaves:
        if is_shard(leaf):
            h.update(repr((leaf.spec.shape, leaf.spec.dtype,
                           leaf.spec.world, leaf.spec.rank)).encode())
            h.update(np.ascontiguousarray(leaf.data).tobytes())
        else:
            arr = np.asarray(leaf)
            h.update(repr((tuple(arr.shape), str(arr.dtype))).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.digest()


def armed() -> bool:
    """The ``HOROVOD_ZERO`` opt-in, resolved like the other build-time
    knobs: pinned config once initialized, env before. Capability (XLA
    plane present, world > 1) is the ENGINE's call — see
    ``ops.zero1_active`` for the runtime answer front-ends act on."""
    from .. import basics

    if basics.is_initialized():
        return basics.config().zero1
    from ..core.config import Config

    return Config.from_env().zero1
