"""Sharding plane: 2-D GSPMD mesh planning + ZeRO-1 partitioned
optimizer state with elastic resharding (docs/sharding.md).

Two sub-planes behind two knobs:

* :mod:`.meshplan` (``HOROVOD_MESH``) — grows the 1-D data axis into a
  named ``(batch, model)`` mesh with ``NamedSharding`` specs; the flat
  default is byte-identical to today's world.
* :mod:`.zero1` (``HOROVOD_ZERO``) — each rank owns a contiguous shard
  of the flattened optimizer state; the eager flush runs reduce-scatter
  → local apply → all-gather as ONE donated compiled program, and
  elastic commits store the world-size-independent canonical form so a
  relaunch at a different size just repartitions the sealed state.
"""

from .meshplan import (  # noqa: F401
    BATCH_AXIS,
    MODEL_AXIS,
    MeshPlan,
    activation_sharding,
    build_mesh,
    param_sharding,
    parse_mesh_spec,
    plan,
)
from .zero1 import (  # noqa: F401
    ShardLeaf,
    ShardSpec,
    adopt_tree,
    expand_tree,
    has_shards,
    is_shard,
    localize_tree,
    padded_len,
    resident_bytes,
    shard_digest,
    shard_len,
    shard_slice,
)

__all__ = [
    "BATCH_AXIS", "MODEL_AXIS", "MeshPlan", "activation_sharding",
    "build_mesh", "param_sharding", "parse_mesh_spec", "plan",
    "ShardLeaf", "ShardSpec", "adopt_tree", "expand_tree", "has_shards",
    "is_shard", "localize_tree", "padded_len", "resident_bytes",
    "shard_digest", "shard_len", "shard_slice",
]
